"""Benchmark suite: the five BASELINE.json configs, end to end.

Runs each config through the real engine (holder → executor → fused XLA
kernels on the default JAX backend), checks results against a numpy
oracle, and prints one JSON line per config:

  {"config": i, "metric": ..., "value": N, "unit": ..., "ok": true}

Scale: data sizes default to a laptop-friendly fraction; --full uses the
billion-column scale on real hardware. bench.py (the driver's single-line
contract) stays the headline kernel benchmark; this suite covers the
query-level configs (SURVEY.md §6 / BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np


def _timed(fn, iters=5):
    fn()  # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    return (time.perf_counter() - t0) / iters, out


_DISPATCH_FLOOR_MS = None


def dispatch_floor_ms() -> float:
    """Median wall time of a trivial blocking device call. On a tunneled
    backend this round-trip latency is the floor under every single-query
    p50 below; the device compute is value - floor. Computed once."""
    global _DISPATCH_FLOOR_MS
    if _DISPATCH_FLOOR_MS is None:
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x, s: jnp.sum(x) + s)
        x = jax.device_put(np.zeros(8, np.int32))
        samples = []
        for i in range(10):  # unique scalar: defeats execution-result caches
            t0 = time.perf_counter()
            int(f(x, i))
            samples.append(time.perf_counter() - t0)
        _DISPATCH_FLOOR_MS = round(float(np.median(samples)) * 1e3, 3)
    return _DISPATCH_FLOOR_MS


def _mk_env(tmp):
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.storage import Holder

    holder = Holder(tmp).open()
    return holder, Executor(holder)


# Perfetto event names that mark inter-device transfer/collective work.
# TPU/GPU traces carry these on device lanes (with byte counts in the
# args when XLA attributes them); CPU-only hosts have NO such lanes,
# which parse_trace_events reports as a structured skip, never a crash.
_TRANSFER_OP_RE = None


def _transfer_op_re():
    global _TRANSFER_OP_RE
    if _TRANSFER_OP_RE is None:
        import re

        _TRANSFER_OP_RE = re.compile(
            r"(?i)\b(all-?reduce|all-?gather|reduce-?scatter|all-?to-?all"
            r"|collective-?permute|copy-?(start|done)|memcpy|"
            r"(d2d|h2d|d2h)\b)"
        )
    return _TRANSFER_OP_RE


def _transfer_event_bytes(e) -> int | None:
    """Bytes attributed to one transfer/collective trace event, from the
    arg conventions XLA's profiler uses (bytes_accessed /
    'bytes accessed' / bytes_transferred); None when the trace carries
    no byte figure for it."""
    args = e.get("args") or {}
    for key in ("bytes_accessed", "bytes accessed", "bytes_transferred",
                "bytes transferred", "bytes"):
        v = args.get(key)
        if v in (None, ""):
            continue
        try:
            return int(float(str(v).replace(",", "")))
        except ValueError:
            continue
    return None


def parse_trace_events(trace_dir: str) -> dict:
    """Parse every perfetto trace under ``trace_dir`` into ONE structured
    report (the hardened successor of the old inline parse — every
    failure mode is a ``reason`` string in the record, not a bare None):

    * device_us / device_lane: summed per-op durations from the device
      lanes ("XLA Ops" threads of device processes; CPU fallback:
      tf_XLA* execution threads, genuinely parallel, labeled
      ``cpu-threads``).
    * transfer: measured inter-device bytes — events matching collective
      /copy op names with profiler byte attribution. ``ok`` False with a
      reason when the host's traces lack transfer lanes entirely (the
      CPU-only case) or carry events without byte figures.
    """
    import glob
    import gzip
    import os

    report = {
        "ok": False, "device_us": 0.0, "device_lane": None, "reason": None,
        "transfer": {"ok": False, "bytes": 0, "events": 0, "reason": None},
    }
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        report["reason"] = "no-trace-files"
        report["transfer"]["reason"] = "no-trace-files"
        return report
    parse_errors = 0
    found_device = False
    transfer_events = 0
    transfer_bytes = 0
    transfer_attributed = 0
    for path in paths:
        try:
            with gzip.open(path, "rt") as f:
                trace = json.load(f)
        except Exception:
            parse_errors += 1
            continue
        events = trace.get("traceEvents", [])
        # TPU/GPU: device lanes are separate trace processes named
        # "/device:TPU:0 ..." whose per-op lane is the thread named
        # "XLA Ops" — summing ALL device-pid lanes would double
        # count ("XLA Modules"/"Steps" spans COVER their op spans).
        # CPU backend: XLA executes on the "/host:CPU" process's
        # tf_XLA* threads (Eigen pool + TfrtCpuClient); those lanes
        # run genuinely in parallel, so their sum is device
        # THREAD-time (can exceed wall — labeled as such).
        device_pids = set()
        op_threads = set()
        cpu_threads = set()
        for e in events:
            if e.get("ph") != "M":
                continue
            name = str((e.get("args") or {}).get("name", ""))
            if (e.get("name") == "process_name"
                    and "device" in name.lower()):
                device_pids.add(e.get("pid"))
            elif e.get("name") == "thread_name":
                if name.startswith("XLA Ops"):
                    op_threads.add((e.get("pid"), e.get("tid")))
                elif name.startswith("tf_XLA"):
                    cpu_threads.add((e.get("pid"), e.get("tid")))
        keep = {t for t in op_threads if t[0] in device_pids}
        if keep:
            report["device_lane"] = "device-ops"
        elif cpu_threads:
            keep = cpu_threads
            report["device_lane"] = report["device_lane"] or "cpu-threads"
        op_re = _transfer_op_re()
        for e in events:
            if e.get("ph") != "X":
                continue
            if (e.get("pid"), e.get("tid")) in keep:
                report["device_us"] += float(e.get("dur", 0) or 0)
                found_device = True
            # transfer attribution counts DEVICE-lane collectives only:
            # CPU thread lanes name the same fused ops but model no
            # wire, so byte figures there would be fiction
            if ((e.get("pid") in device_pids)
                    and op_re.search(str(e.get("name", "")))):
                transfer_events += 1
                b = _transfer_event_bytes(e)
                if b is not None:
                    transfer_bytes += b
                    transfer_attributed += 1
    if found_device:
        report["ok"] = True
    else:
        report["reason"] = ("trace-parse-errors" if parse_errors
                            else "no-device-lanes")
    tr = report["transfer"]
    tr["events"] = transfer_events
    tr["bytes"] = transfer_bytes
    if transfer_attributed:
        tr["ok"] = True
    elif transfer_events:
        tr["reason"] = "transfer-events-without-byte-attribution"
    else:
        tr["reason"] = "no-transfer-lanes-in-trace (CPU-only host)"
    return report


def profiled_trace_report(fn, iters: int = 5) -> dict:
    """Run ``fn`` ``iters`` times inside a jax.profiler trace and return
    the structured parse_trace_events report plus ``iters``/``ms``.
    Capture failures come back as a reason, never an exception."""
    import tempfile as _tf

    from pilosa_tpu.utils.tracing import start_jax_trace

    with _tf.TemporaryDirectory() as td:
        try:
            with start_jax_trace(td):
                for _ in range(iters):
                    fn()
        except Exception as e:
            return {
                "ok": False, "device_us": 0.0, "device_lane": None,
                "reason": f"trace-capture-failed: {e!r}"[:200],
                "transfer": {"ok": False, "bytes": 0, "events": 0,
                             "reason": "trace-capture-failed"},
            }
        report = parse_trace_events(td)
    report["iters"] = iters
    if report["ok"]:
        report["ms"] = round(report["device_us"] / 1e3 / iters, 3)
    return report


def profiled_device_ms(fn, iters: int = 5):
    """PROFILER-MEASURED device execution time per iteration (VERDICT r5
    Next #2): run ``fn`` ``iters`` times inside a ``jax.profiler`` trace
    (utils/tracing.start_jax_trace) and sum the device-lane op durations
    from the captured perfetto trace — replacing the old wall-minus-floor
    arithmetic, which inferred device time from a noisy tunnel-RTT
    sample. Returns mean ms/iteration, or None when the trace could not
    be captured/parsed (the bench must not fail on profiler quirks;
    profiled_trace_report carries the structured reason)."""
    report = profiled_trace_report(fn, iters)
    return report.get("ms") if report.get("ok") else None


def config1_star_trace(n_shards: int) -> dict:
    """Star-Trace: Row(stargazer) ∩ Row(language) → Count."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    with tempfile.TemporaryDirectory() as tmp:
        holder, ex = _mk_env(tmp)
        idx = holder.create_index("repos")
        rng = np.random.default_rng(1)
        expected = 0
        for field_name, row, density in (("stargazer", 1, 0.10), ("language", 5, 0.20)):
            f = idx.create_field(field_name)
            for shard in range(n_shards):
                n = int(SHARD_WIDTH * density)
                cols = rng.choice(SHARD_WIDTH, n, replace=False)
                f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                ).bulk_import(np.full(n, row), cols)
        # oracle on one query
        pql = "Count(Intersect(Row(stargazer=1), Row(language=5)))"
        dt, got = _timed(lambda: ex.execute("repos", pql)[0])
        dev_ms = profiled_device_ms(lambda: ex.execute("repos", pql)[0])
        # numpy oracle
        want = 0
        for shard in range(n_shards):
            a = idx.field("stargazer").view(VIEW_STANDARD).fragment(shard).row_words(1)
            b = idx.field("language").view(VIEW_STANDARD).fragment(shard).row_words(5)
            want += int(np.bitwise_count(a & b).sum())
        holder.close()
        return {
            "config": 1, "metric": "star_trace_intersect_count_p50_ms",
            "value": round(dt * 1e3, 3), "unit": "ms",
            "device_p50_ms": dev_ms, "device_p50_source": "jax-profiler (sum of device-lane op durations)",
            "cols": n_shards << 20, "ok": got == want,
        }


def config2_taxi_topn_groupby(n_shards: int) -> dict:
    """NYC-taxi-like: TopN(cab_type) + GroupBy(passenger_count)."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    with tempfile.TemporaryDirectory() as tmp:
        holder, ex = _mk_env(tmp)
        idx = holder.create_index("taxi")
        cab = idx.create_field("cab_type")
        pc = idx.create_field("passenger_count")
        rng = np.random.default_rng(2)
        for shard in range(n_shards):
            cols = np.arange(SHARD_WIDTH, dtype=np.uint64)
            cab_rows = rng.choice(3, SHARD_WIDTH, p=[0.6, 0.3, 0.1])
            pc_rows = rng.integers(1, 7, SHARD_WIDTH)
            cab.view(VIEW_STANDARD, create=True).fragment(shard, create=True).bulk_import(cab_rows, cols)
            pc.view(VIEW_STANDARD, create=True).fragment(shard, create=True).bulk_import(pc_rows, cols)
        dt_topn, pairs = _timed(lambda: ex.execute("taxi", "TopN(cab_type, n=3)")[0])
        dt_gb, groups = _timed(
            lambda: ex.execute("taxi", "GroupBy(Rows(passenger_count))")[0], iters=3
        )
        dev_ms = profiled_device_ms(
            lambda: ex.execute("taxi", "TopN(cab_type, n=3)")[0]
        )
        total = sum(g.count for g in groups)
        holder.close()
        return {
            "config": 2, "metric": "taxi_topn_p50_ms",
            "value": round(dt_topn * 1e3, 3), "unit": "ms",
            "device_p50_ms": dev_ms, "device_p50_source": "jax-profiler (sum of device-lane op durations)",
            "groupby_ms": round(dt_gb * 1e3, 3),
            "ok": pairs[0].id == 0 and total == n_shards << 20,
        }


def config3_bsi_range_sum(n_shards: int) -> dict:
    """BSI: Range(fare > N) + Sum(fare)."""
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import FieldOptions
    from pilosa_tpu.storage.field import BSI_OFFSET_ROW, BSI_EXISTS_ROW

    with tempfile.TemporaryDirectory() as tmp:
        holder, ex = _mk_env(tmp)
        idx = holder.create_index("taxi")
        fare = idx.create_field("fare", FieldOptions(type="int", min=0, max=4095))
        rng = np.random.default_rng(3)
        oracle_sum, oracle_gt = 0, 0
        for shard in range(n_shards):
            vals = rng.integers(0, 4096, SHARD_WIDTH, dtype=np.uint64)
            oracle_sum += int(vals.sum())
            oracle_gt += int((vals > 1000).sum())
            # bulk plane import (bypasses per-column set_value for speed)
            frag = fare.view(fare.bsi_view_name(), create=True).fragment(shard, create=True)
            cols = np.arange(SHARD_WIDTH, dtype=np.uint64)
            rows = [np.full(SHARD_WIDTH, BSI_EXISTS_ROW, np.uint64)]
            pos = [cols]
            for bit in range(12):
                mask = (vals >> np.uint64(bit)) & np.uint64(1)
                sel = cols[mask == 1]
                rows.append(np.full(sel.size, BSI_OFFSET_ROW + bit, np.uint64))
                pos.append(sel)
            frag.bulk_import(np.concatenate(rows), np.concatenate(pos))
        dt_range, got_gt = _timed(lambda: ex.execute("taxi", "Count(Range(fare > 1000))")[0])
        dt_sum, got_sum = _timed(lambda: ex.execute("taxi", 'Sum(field="fare")')[0])
        dev_ms = profiled_device_ms(
            lambda: ex.execute("taxi", "Count(Range(fare > 1000))")[0]
        )
        holder.close()
        return {
            "config": 3, "metric": "bsi_range_count_p50_ms",
            "value": round(dt_range * 1e3, 3), "unit": "ms",
            "device_p50_ms": dev_ms, "device_p50_source": "jax-profiler (sum of device-lane op durations)",
            "sum_ms": round(dt_sum * 1e3, 3),
            "ok": got_gt == oracle_gt and got_sum.value == oracle_sum,
        }


def config4_time_quantum(n_shards: int) -> dict:
    """Time views: multi-view Union + Count over a 1-year window."""
    import datetime as dt_

    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import FieldOptions
    from pilosa_tpu.storage.view import VIEW_STANDARD, views_for_time

    with tempfile.TemporaryDirectory() as tmp:
        holder, ex = _mk_env(tmp)
        idx = holder.create_index("events")
        t = idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
        rng = np.random.default_rng(4)
        per_day = 2000
        days = [dt_.datetime(2019, 1, 1) + dt_.timedelta(days=i * 14) for i in range(26)]
        days += [dt_.datetime(2020, 2, 1)]  # outside window
        oracle = set()
        for day in days:
            for shard in range(n_shards):
                cols = rng.choice(SHARD_WIDTH, per_day, replace=False)
                for vname in views_for_time(VIEW_STANDARD, "YMD", day):
                    t.view(vname, create=True).fragment(shard, create=True).bulk_import(
                        np.full(per_day, 1, np.uint64), cols
                    )
                t.view(VIEW_STANDARD, create=True).fragment(shard, create=True).bulk_import(
                    np.full(per_day, 1, np.uint64), cols
                )
                if day < dt_.datetime(2020, 1, 1):
                    oracle.update((shard << 20) + int(c) for c in cols)
        pql = "Count(Row(t=1, from='2019-01-01T00:00', to='2020-01-01T00:00'))"
        dt_q, got = _timed(lambda: ex.execute("events", pql)[0])
        dev_ms = profiled_device_ms(lambda: ex.execute("events", pql)[0])
        holder.close()
        return {
            "config": 4, "metric": "time_union_count_p50_ms",
            "value": round(dt_q * 1e3, 3), "unit": "ms",
            "device_p50_ms": dev_ms, "device_p50_source": "jax-profiler (sum of device-lane op durations)",
            "ok": got == len(oracle),
        }


def config5_ssb_4way(n_shards: int) -> dict:
    """SSB-style 4-way Intersect with the mesh (ICI-reduce) executor."""
    from pilosa_tpu.parallel import DistExecutor, make_mesh
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage.view import VIEW_STANDARD

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp).open()
        idx = holder.create_index("ssb")
        rng = np.random.default_rng(5)
        fields = ["year", "region", "category", "brand"]
        densities = [0.5, 0.25, 0.2, 0.3]
        words_oracle = None
        for fname, d in zip(fields, densities):
            f = idx.create_field(fname)
            for shard in range(n_shards):
                n = int(SHARD_WIDTH * d)
                cols = rng.choice(SHARD_WIDTH, n, replace=False)
                f.view(VIEW_STANDARD, create=True).fragment(shard, create=True).bulk_import(
                    np.full(n, 1, np.uint64), cols
                )
        ex = DistExecutor(holder, make_mesh())
        pql = ("Count(Intersect(Row(year=1), Row(region=1), "
               "Row(category=1), Row(brand=1)))")
        dt_q, got = _timed(lambda: ex.execute("ssb", pql)[0])
        dev_ms = profiled_device_ms(lambda: ex.execute("ssb", pql)[0])
        want = 0
        for shard in range(n_shards):
            acc = None
            for fname in fields:
                w = idx.field(fname).view(VIEW_STANDARD).fragment(shard).row_words(1)
                acc = w if acc is None else (acc & w)
            want += int(np.bitwise_count(acc).sum())
        holder.close()
        return {
            "config": 5, "metric": "ssb_4way_intersect_count_p50_ms",
            "value": round(dt_q * 1e3, 3), "unit": "ms",
            "device_p50_ms": dev_ms, "device_p50_source": "jax-profiler (sum of device-lane op durations)",
            "mesh_devices": make_mesh().size, "ok": got == want,
        }


def config5_mesh_cpu8(n_shards: int = 16, n_queries: int = 64) -> dict:
    """Config 5's defining feature — the cross-shard mesh reduce —
    exercised on a REAL 8-device mesh (virtual CPU devices, VERDICT r3
    #7). NOT a perf claim: CPU devices; perf numbers stay single-chip
    (config 5 proper). Verified here: (a) a pipelined stream of SSB
    4-way intersect counts through DistExecutor.submit matches the local
    single-device executor on every query, and (b) the mesh path keeps
    micro-batching — program dispatches ≈ queries / microbatch_max, not
    one eager dispatch per query."""
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.parallel import DistExecutor, make_mesh
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage.view import VIEW_STANDARD

    mesh = make_mesh()
    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp).open()
        idx = holder.create_index("ssb")
        rng = np.random.default_rng(55)
        fields = ["year", "region", "category", "brand"]
        n_rows = 4
        for fname, d in zip(fields, [0.5, 0.25, 0.2, 0.3]):
            f = idx.create_field(fname)
            for shard in range(n_shards):
                n = int(SHARD_WIDTH * d)
                for row in range(1, n_rows + 1):
                    cols = rng.choice(SHARD_WIDTH, n, replace=False)
                    f.view(VIEW_STANDARD, create=True).fragment(
                        shard, create=True
                    ).bulk_import(np.full(n, row, np.uint64), cols)

        def pql(i: int) -> str:
            combo = [(i + k) % n_rows + 1 for k in range(4)]
            return ("Count(Intersect(" + ", ".join(
                f"Row({f}={r})" for f, r in zip(fields, combo)
            ) + "))")

        local = Executor(holder)
        want = [local.execute("ssb", pql(i))[0] for i in range(n_rows)]

        ex = DistExecutor(holder, mesh)
        dispatches = [0]
        real_builder = ex._program_batched

        def counting_builder(*a, **k):
            fn = real_builder(*a, **k)

            def counted(*args):
                dispatches[0] += 1
                return fn(*args)

            return counted

        ex._program_batched = counting_builder
        # warm compiles outside the accounting
        warm = [ex.submit("ssb", pql(i))[0] for i in range(ex.microbatch_max)]
        warm[-1].result()
        dispatches[0] = 0

        t0 = time.perf_counter()
        deferreds = [ex.submit("ssb", pql(i))[0] for i in range(n_queries)]
        got = [d.result() for d in deferreds]
        wall = time.perf_counter() - t0
        ok = all(g == want[i % n_rows] for i, g in enumerate(got))
        expected_dispatches = -(-n_queries // ex.microbatch_max)
        holder.close()
        return {
            "config": 5, "metric": "ssb_4way_mesh_microbatched_dispatches",
            "value": dispatches[0], "unit": "dispatches",
            "queries": n_queries, "microbatch": ex.microbatch_max,
            "expected_dispatches": expected_dispatches,
            "mesh_devices": mesh.size,
            "wall_ms": round(wall * 1e3, 1),
            "ok": ok and dispatches[0] == expected_dispatches,
            "note": ("8 virtual CPU devices — correctness + dispatch "
                     "accounting for the SPMD path only; perf claims are "
                     "single-chip (config 5 proper)"),
        }


def config_serving(n_shards: int = 8, n_queries: int = 512,
                   client_counts=(16, 64, 128)) -> dict:
    """Serving-path throughput with the HOST-PATH FAST LANE (ISSUE 4 /
    VERDICT r5 Next #3): concurrent HTTP clients against ONE in-process
    server (real loopback HTTP, full handler → API →
    ClusterExecutor.submit stack), in two transport modes on the same
    hardware, same data, same queries:

    - ``fastlane``: each client holds a persistent HTTP/1.1 keep-alive
      connection (what the pooled InternalClient and any sane production
      client do) — requests amortize TCP connect + server handler-thread
      spawn, responses ride pre-serialized bytes, identical wavemates
      dedupe in the pipeline;
    - ``legacy``: the r5 serving path end to end — urllib clients (one
      fresh connection per request, exactly the r5 bench's client) AND
      ``api.serve_fastlane = False`` (dict building + json.dumps per
      request, no identical-query dedupe); that curve plateaued
      ~650 QPS/node on TPU hardware.

    The headline is plateau-vs-plateau: max QPS over the client sweep in
    each mode. ok requires byte-identical responses to the serial pass,
    the connection-count oracle (fastlane connections ≈ clients while
    legacy ≈ requests), and ≥2× legacy plateau. A second phase proves
    the cluster fast lane: a 2-node cluster answers a query set with the
    wave batcher ON and OFF and the response bytes must be identical,
    with batches actually formed."""
    import http.client as _hc
    import threading
    import urllib.request

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="bench", anti_entropy_interval=0,
            heartbeat_interval=0,
        )).open()
        try:
            idx = server.holder.create_index("b")
            f = idx.create_field("f")
            n = int(SHARD_WIDTH * 0.1)
            for shard in range(n_shards):
                frag = f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                )
                for row in range(1, 5):
                    frag.bulk_import(
                        np.full(n, row, np.uint64),
                        rng.choice(SHARD_WIDTH, n, replace=False).astype(
                            np.uint64
                        ),
                    )
            server.api.cluster.note_local_shards("b", list(range(n_shards)))
            port = server.port
            queries = [
                ("Count(Intersect(Row(f={}), Row(f={})))".format(
                    1 + (i % 4), 1 + ((i + 1) % 4)))
                for i in range(n_queries)
            ]

            def post_keepalive(conn, pql: str) -> bytes:
                conn.request("POST", "/index/b/query", body=pql.encode())
                return conn.getresponse().read()

            def post_legacy(pql: str) -> bytes:
                # urllib, new connection per request: byte-for-byte the
                # client the r5 serving bench used for its curve
                r = urllib.request.Request(
                    f"http://localhost:{port}/index/b/query",
                    data=pql.encode(), method="POST",
                )
                with urllib.request.urlopen(r, timeout=120) as resp:
                    return resp.read()

            post_legacy(queries[0])  # warm the per-query compile caches
            serial_conn = _hc.HTTPConnection("localhost", port, timeout=120)
            t0 = time.perf_counter()
            serial = [post_keepalive(serial_conn, q) for q in queries]
            serial_wall = time.perf_counter() - t0
            serial_conn.close()
            serial_parsed = [json.loads(s) for s in serial]

            def run_concurrent(n_clients: int, keepalive: bool):
                results = [None] * n_queries
                errors: list = []
                gate = threading.Event()

                def worker(tid: int):
                    conn = (_hc.HTTPConnection("localhost", port,
                                               timeout=120)
                            if keepalive else None)
                    gate.wait(30)
                    for k in range(tid, n_queries, n_clients):
                        try:
                            results[k] = (post_keepalive(conn, queries[k])
                                          if keepalive
                                          else post_legacy(queries[k]))
                        except Exception as e:  # surfaced via errors
                            errors.append(repr(e))
                    if conn is not None:
                        conn.close()

                threads = [
                    threading.Thread(target=worker, args=(t,))
                    for t in range(n_clients)
                ]
                for t in threads:
                    t.start()
                t0 = time.perf_counter()
                gate.set()
                for t in threads:
                    t.join(300)
                return time.perf_counter() - t0, results, errors

            # warm burst: compiles the pow-of-two batched program shapes
            # the waves will use (the serial pass only compiled batch=1)
            run_concurrent(max(client_counts), True)

            ok = True
            scaling = []
            oracle = {}
            for mode, keepalive in (("fastlane", True), ("legacy", False)):
                # legacy mode is the FULL r5 serving path: per-request
                # connections AND the pre-fastlane response pipeline
                server.api.serve_fastlane = keepalive
                for n_clients in client_counts:
                    best = 0.0
                    for _ in range(3):  # best-of-3: loopback jitter
                        http_srv = server._http
                        with http_srv.metrics_lock:
                            conns0 = http_srv.connections_opened
                        wall, results, errors = run_concurrent(
                            n_clients, keepalive
                        )
                        with http_srv.metrics_lock:
                            conns = http_srv.connections_opened - conns0
                        same = (results == serial if keepalive else
                                [json.loads(r) for r in results
                                 if r is not None] == serial_parsed)
                        ok = ok and not errors and same
                        best = max(best, n_queries / wall)
                    scaling.append({"mode": mode, "clients": n_clients,
                                    "qps": round(best, 1),
                                    "connections_last_run": conns})
                    # connection-count oracle from the LAST run of the
                    # sweep point: keep-alive ≈ one per client, legacy
                    # ≈ one per request
                    if mode == "fastlane":
                        ok = ok and conns <= 2 * n_clients
                    else:
                        ok = ok and conns >= n_queries
                    oracle[mode] = conns
            server.api.serve_fastlane = True
            fast_plateau = max(s["qps"] for s in scaling
                               if s["mode"] == "fastlane")
            legacy_plateau = max(s["qps"] for s in scaling
                                 if s["mode"] == "legacy")
            pm = server.api.pipeline_metrics()
        finally:
            server.close()

    batch_check = _serving_cluster_batch_check(n_shards=8)
    speedup = round(fast_plateau / max(legacy_plateau, 1e-9), 2)
    return {
        "config": "serving",
        "metric": "serving_fastlane_plateau_qps",
        "value": round(fast_plateau, 1),
        "unit": "queries/sec",
        "legacy_plateau_qps": round(legacy_plateau, 1),
        "plateau_speedup": speedup,
        "qps_serial": round(n_queries / serial_wall, 1),
        "scaling": scaling,
        "connections_oracle": oracle,
        "queries": n_queries, "shards": n_shards,
        "pipeline": pm,
        "remote_batch": batch_check,
        "ok": bool(ok and speedup >= 2.0 and batch_check["ok"]),
    }


def _serving_cluster_batch_check(n_shards: int = 8,
                                 n_queries: int = 32) -> dict:
    """Cluster fast-lane proof: a 2-node cluster answers the same
    concurrent query set with the remote wave batcher ON then OFF;
    responses must be byte-identical and the ON pass must actually form
    multi-query batches."""
    import threading
    import urllib.request

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    with tempfile.TemporaryDirectory() as t1, \
            tempfile.TemporaryDirectory() as t2:
        s1 = Server(ServerConfig(
            data_dir=t1, port=0, name="a", anti_entropy_interval=0,
            heartbeat_interval=0,
        )).open()
        s2 = Server(ServerConfig(
            data_dir=t2, port=0, name="b", anti_entropy_interval=0,
            heartbeat_interval=0, seeds=[f"http://localhost:{s1.port}"],
        )).open()
        try:
            url = f"http://localhost:{s1.port}"

            def post(path, data):
                r = urllib.request.Request(url + path, data=data,
                                           method="POST")
                with urllib.request.urlopen(r, timeout=120) as resp:
                    return resp.read()

            post("/index/i", b"{}")
            post("/index/i/field/f", b"{}")
            rows, cols = [], []
            for shard in range(n_shards):
                for c in range(64):
                    rows.append(1 + c % 3)
                    cols.append(shard * SHARD_WIDTH + c * 11)
            post("/index/i/field/f/import",
                 json.dumps({"rows": rows, "columns": cols}).encode())

            queries = [f"Count(Row(f={1 + i % 3}))" for i in range(n_queries)]

            def run():
                results = [None] * n_queries
                errors: list = []
                gate = threading.Event()

                def worker(tid):
                    gate.wait(10)
                    for k in range(tid, n_queries, 8):
                        try:
                            results[k] = post("/index/i/query",
                                              queries[k].encode())
                        except Exception as e:  # keep the stripe going
                            errors.append(f"{queries[k]}: {e!r}")

                threads = [threading.Thread(target=worker, args=(t,))
                           for t in range(8)]
                for t in threads:
                    t.start()
                gate.set()
                for t in threads:
                    t.join(120)
                return results, errors

            batched, err_on = run()
            m_on = s1.api.executor.wave_batcher.metrics()
            s1.api.executor.remote_batch = False
            unbatched, err_off = run()
            m_off = s1.api.executor.wave_batcher.metrics()
            errors = err_on + err_off
            ok = (not errors
                  and batched == unbatched
                  and None not in batched
                  and m_on["remote_batched_queries_total"] > 0
                  and m_off["remote_batched_queries_total"]
                  == m_on["remote_batched_queries_total"])
            out = {
                "byte_identical": batched == unbatched,
                "batched_queries": m_on["remote_batched_queries_total"],
                "batches": m_on["remote_batches_total"],
                "ok": bool(ok),
            }
            if errors:
                out["errors"] = errors[:5]
            return out
        finally:
            s2.close()
            s1.close()


def config_serving_readwrite(n_shards: int = 32, n_clients: int = 16,
                             n_ops: int = 256) -> dict:
    """Mixed READ+WRITE concurrent serving: 75% Counts through the wave
    pipeline, 25% point Sets through the routed write path (each write
    durably logged before its ACK and patched into resident leaves).
    Correctness: every write must ACK true and the final written row
    must equal the written column set exactly. Produced the BENCH_SUITE
    'serving.readwrite' record."""
    import json as _json
    import threading
    import urllib.request

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="bench", anti_entropy_interval=0,
            heartbeat_interval=0,
        )).open()
        try:
            idx = server.holder.create_index("b")
            f = idx.create_field("f")
            n = int(SHARD_WIDTH * 0.1)
            for shard in range(n_shards):
                frag = f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                )
                for row in range(1, 5):
                    frag.bulk_import(
                        np.full(n, row, np.uint64),
                        rng.choice(SHARD_WIDTH, n, replace=False).astype(
                            np.uint64
                        ),
                    )
            server.api.cluster.note_local_shards("b", list(range(n_shards)))
            url = f"http://localhost:{server.port}/index/b/query"

            def post(pql: str) -> dict:
                r = urllib.request.Request(
                    url, data=pql.encode(), method="POST"
                )
                with urllib.request.urlopen(r, timeout=300) as resp:
                    return _json.loads(resp.read())

            write_cols = rng.choice(
                n_shards * SHARD_WIDTH, n_ops // 4, replace=False
            ).tolist()
            ops, wi = [], 0
            for i in range(n_ops):
                if i % 4 == 3:
                    ops.append(f"Set({write_cols[wi]}, f=9)")
                    wi += 1
                else:
                    ops.append(
                        "Count(Intersect(Row(f={}), Row(f={})))".format(
                            1 + (i % 4), 1 + ((i + 1) % 4)
                        )
                    )
            post(ops[0])
            post("Count(Row(f=9))")  # warm both program shapes
            t0 = time.perf_counter()
            for q in ops[:64]:
                post(q)
            serial_qps = 64 / (time.perf_counter() - t0)
            post("ClearRow(f=9)")

            results: list = [None] * n_ops
            errors: list = []
            gate = threading.Event()

            def worker(tid: int):
                gate.wait(30)
                for k in range(tid, n_ops, n_clients):
                    try:
                        results[k] = post(ops[k])
                    except Exception as e:  # surfaced below
                        errors.append(repr(e))

            threads = [threading.Thread(target=worker, args=(t,))
                       for t in range(n_clients)]
            for t in threads:
                t.start()
            t0 = time.perf_counter()
            gate.set()
            for t in threads:
                t.join(600)
            wall = time.perf_counter() - t0
            ok = not errors
            ok = ok and all(results[k] == {"results": [True]}
                            for k in range(3, n_ops, 4))
            ok = ok and post("Count(Row(f=9))") == {
                "results": [len(write_cols)]
            }
            return {
                "config": "readwrite",
                "metric": "serving_readwrite_qps",
                "value": round(n_ops / wall, 1),
                "unit": "queries/sec",
                "qps_serial": round(serial_qps, 1),
                "speedup_vs_serial": round((n_ops / wall) / serial_qps, 2),
                "clients": n_clients, "ops": n_ops, "write_frac": 0.25,
                "shards": n_shards, "ok": bool(ok),
            }
        finally:
            server.close()


def crash_burst_ledger(post_set, kill, n_threads: int, min_acked: int,
                       deadline_s: float = 60.0):
    """ACK-ledger write burst + mid-burst kill for the crash-recovery
    oracle — ONE implementation shared by config_durability and the
    dryrun_multichip certification. ``n_threads`` writers Set() disjoint
    columns through ``post_set`` (returns True on a 200 ack; an
    exception means the kill landed mid-request); once ``min_acked``
    acks accumulate, ``kill()`` fires mid-burst (SIGKILL: no close, no
    snapshot, torn groups). Returns (acked, inflight-at-kill): the
    recovered row must contain every acked col and nothing outside
    acked | inflight."""
    import threading

    acked: set = set()
    inflight: dict = {}
    lock = threading.Lock()
    stop = threading.Event()

    def writer(tid: int):
        k = 0
        while not stop.is_set():
            col = tid + k * n_threads
            k += 1
            with lock:
                inflight[tid] = col
            try:
                ok = post_set(col)
            except Exception:
                return  # the kill landed mid-request
            if ok:
                with lock:
                    acked.add(col)
                    inflight.pop(tid, None)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    deadline = time.time() + deadline_s
    while len(acked) < min_acked:
        if time.time() > deadline:
            raise AssertionError(
                f"crash-oracle burst stalled at {len(acked)} acked "
                "writes — node stopped acking")
        time.sleep(0.02)
    kill()
    stop.set()
    for t in threads:
        t.join(15)
    with lock:
        return set(acked), set(inflight.values())


def config_durability(n_shards: int = 8, n_clients: int = 16,
                      n_ops: int = 800, fsync_delay_ms: float = 8.0,
                      group_max_ms: float = 5.0) -> dict:
    """Durable write path at read-path speed (ISSUE 6): the SAME mixed
    25%-write workload served by a real subprocess node in each
    durability mode —

    - ``per-op``: every acked write fsyncs its own op record (the
      honest baseline the r5 'per-write durability' claim implied);
    - ``group``: concurrent writers' records group-commit through the
      holder WAL, ONE fsync per group, ACKs released after it;
    - ``flush-only``: the r5 behavior (no fsync) as the ceiling.

    ``fsync_delay_ms`` injects a serialized per-fsync journal delay
    into EVERY mode (PILOSA_TPU_FSYNC_DELAY_MS, the config_sync
    injected-RTT precedent: tmpfs/9p under-prices the very fsync the
    group commit amortizes; ~8 ms is a conservative fsync on a busy
    production disk, and fsyncs serialize at the journal).

    Gates (BENCH_SUITE.json `durability`): group write QPS ≥ 2× per-op
    at 25% write fraction; group p99 write-ACK latency ≤
    group-commit-max-ms over the per-op mode's p99 under the SAME
    closed-loop load (+3 ms scheduler slack) — tail-to-tail, the
    controlled comparison: both tails carry identical queueing, so the
    difference isolates what the forming window may add; then the crash
    oracle — SIGKILL the group-mode node mid write-burst, restart,
    every ACKed write present and the fragment bit-exact against the
    ACK ledger — and a backup → restore round trip byte-identical to
    the recovered node."""
    import json as _json
    import os
    import shutil
    import socket
    import subprocess
    import sys
    import threading
    import urllib.request

    from pilosa_tpu.shardwidth import SHARD_WIDTH

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def req(method, base, path, body=None, timeout=60):
        r = urllib.request.Request(f"{base}{path}", data=body,
                                   method=method)
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return _json.loads(resp.read() or b"{}")

    def spawn(data_dir: str, mode: str):
        port = free_port()
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "PILOSA_TPU_NAME": f"dur-{mode}",
            "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "0",
            "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
            "PILOSA_TPU_USE_MESH": "false",
            "PILOSA_TPU_DURABILITY_MODE": mode,
            "PILOSA_TPU_GROUP_COMMIT_MAX_MS": str(group_max_ms),
            "PILOSA_TPU_FSYNC_DELAY_MS": str(fsync_delay_ms),
        }
        proc = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "server",
             "--data-dir", data_dir, "--bind", "127.0.0.1",
             "--port", str(port)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        base = f"http://127.0.0.1:{port}"
        for _ in range(240):
            if proc.poll() is not None:
                raise AssertionError(f"node exited rc={proc.returncode}")
            try:
                req("GET", base, "/status", timeout=5)
                return proc, base
            except Exception:
                time.sleep(0.25)
        proc.terminate()
        raise AssertionError("durability node never served /status")

    rounds = 3  # best-of-3 per mode (the config_serving precedent:
    # a ~200-sample p99 is two samples deep — one scheduler hiccup on
    # the shared CI box would otherwise decide the gate)
    rng = np.random.default_rng(23)
    seed_cols = rng.choice(n_shards * SHARD_WIDTH, 2000,
                           replace=False).tolist()
    n_writes = sum(1 for i in range(n_ops) if i % 4 == 3)
    write_cols = rng.choice(n_shards * SHARD_WIDTH, n_writes * rounds,
                            replace=False).tolist()

    def round_ops(r: int) -> list[str]:
        out, wi = [], r * n_writes
        for i in range(n_ops):
            if i % 4 == 3:  # 25% write fraction; fresh cols per round
                out.append(f"Set({write_cols[wi]}, f=9)")
                wi += 1
            else:
                out.append(f"Count(Row(f={1 + i % 3}))")
        return out

    def run_round(base: str, ops: list[str]):
        write_lat: list = []
        lat_lock = threading.Lock()
        gate = threading.Event()
        errors: list = []

        def worker(tid: int):
            gate.wait(30)
            for k in range(tid, n_ops, n_clients):
                is_write = k % 4 == 3
                t0 = time.perf_counter()
                try:
                    out = req("POST", base, "/index/i/query",
                              ops[k].encode())
                except Exception as e:
                    errors.append(repr(e))
                    return
                if is_write:
                    if out != {"results": [True]}:
                        errors.append(f"write not acked: {out}")
                    with lat_lock:
                        write_lat.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_clients)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        gate.set()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
        lats = np.sort(np.array(write_lat)) * 1e3
        return {
            "ok": not errors and len(write_lat) == n_writes,
            "errors": errors[:3],
            "wall_s": round(wall, 3),
            "write_qps": round(n_writes / wall, 1),
            "total_qps": round(n_ops / wall, 1),
            "ack_p50_ms": round(float(lats[len(lats) // 2]), 2),
            "ack_p99_ms": round(
                float(lats[int(len(lats) * 0.99) - 1]), 2),
        }

    def run_mode(mode: str, tmp: str):
        data_dir = f"{tmp}/{mode}"
        proc, base = spawn(data_dir, mode)
        try:
            req("POST", base, "/index/i", b"{}")
            req("POST", base, "/index/i/field/f", b"{}")
            body = _json.dumps({
                "rows": [1 + k % 3 for k in range(len(seed_cols))],
                "columns": seed_cols,
            }).encode()
            req("POST", base, "/index/i/field/f/import", body)
            # warm all three program shapes off the measured cols
            req("POST", base, "/index/i/query", round_ops(0)[0].encode())
            req("POST", base, "/index/i/query", b"Set(0, f=7)")
            req("POST", base, "/index/i/query", b"Count(Row(f=9))")
            results = [run_round(base, round_ops(r))
                       for r in range(rounds)]
            best = dict(max(results, key=lambda r: r["write_qps"]))
            best["ack_p99_ms"] = min(r["ack_p99_ms"] for r in results)
            best["ok"] = all(r["ok"] for r in results)
            best["errors"] = sum((r["errors"] for r in results), [])[:3]
            best["rounds"] = [
                {k: r[k] for k in ("write_qps", "ack_p50_ms",
                                   "ack_p99_ms")}
                for r in results
            ]
            return best, proc, base, data_dir
        except Exception:
            proc.terminate()
            proc.wait(15)
            raise

    with tempfile.TemporaryDirectory() as tmp:
        perop, proc, _, _ = run_mode("per-op", tmp)
        proc.terminate()
        proc.wait(15)
        flush, proc, _, _ = run_mode("flush-only", tmp)
        proc.terminate()
        proc.wait(15)
        group, proc, base, data_dir = run_mode("group", tmp)

        # ---- crash oracle: SIGKILL mid write-burst on the group node
        def burst_set(col: int) -> bool:
            return req("POST", base, "/index/i/query",
                       f"Set({col}, f=8)".encode(),
                       timeout=10) == {"results": [True]}

        def burst_kill():
            proc.kill()
            proc.wait(15)

        ledger, maybe = crash_burst_ledger(burst_set, burst_kill,
                                           n_threads=8, min_acked=60)
        proc, base = spawn(data_dir, "group")
        got = set(req("POST", base, "/index/i/query", b"Row(f=8)",
                      timeout=120)["results"][0]["columns"])
        got9 = set(req("POST", base, "/index/i/query", b"Row(f=9)",
                       timeout=120)["results"][0]["columns"])
        oracle_ok = (ledger <= got and got <= ledger | maybe
                     and got9 == set(write_cols))
        proc.terminate()
        proc.wait(15)

        # ---- backup → restore round trip, byte-identical
        from pilosa_tpu.storage import Holder
        from pilosa_tpu.storage.backup import backup_holder, restore_holder

        src = Holder(data_dir).open()
        manifest = backup_holder(src, f"{tmp}/bak")
        restore_holder(f"{tmp}/bak", f"{tmp}/restored")
        dst = Holder(f"{tmp}/restored").open()
        restore_ok = True
        for iname, idx in src.indexes.items():
            for fname, fld in idx.fields.items():
                for vname, view in fld.views.items():
                    for shard, frag in view.fragments.items():
                        other = (dst.index(iname).field(fname)
                                 .view(vname).fragment(shard))
                        if (other is None or other.serialize_snapshot()
                                != frag.serialize_snapshot()):
                            restore_ok = False
        src.close()
        dst.close()
        shutil.rmtree(f"{tmp}/restored", ignore_errors=True)

    speedup = round(group["write_qps"] / perop["write_qps"], 2)
    lat_bound_ms = round(group_max_ms + perop["ack_p99_ms"] + 3.0, 2)
    ok = (group["ok"] and perop["ok"] and flush["ok"]
          and speedup >= 2.0
          and group["ack_p99_ms"] <= lat_bound_ms
          and oracle_ok and restore_ok)
    return {
        "config": "durability",
        "metric": "durable_write_qps_group_vs_perop",
        "value": speedup,
        "unit": "x",
        "write_frac": 0.25, "clients": n_clients, "ops": n_ops,
        "injected_fsync_ms": fsync_delay_ms,
        "group_commit_max_ms": group_max_ms,
        "group": group, "per_op": perop, "flush_only": flush,
        "ack_p99_bound_ms": lat_bound_ms,
        "crash_oracle_ok": bool(oracle_ok),
        "crash_acked_writes": len(ledger),
        "restore_round_trip_ok": bool(restore_ok),
        "backup_new_blobs": manifest["newBlobs"],
        "ok": bool(ok),
    }


def config_import(n_shards: int = 8, rows_per_shard: int = 4,
                  density: float = 0.05) -> dict:
    """Bulk-import throughput — the reference's write-path hot loop
    (SURVEY §3.3 fragment.bulkImport). Measures three layers so the cost
    split is visible: (a) fragment.bulk_import engine rate (sorted id
    stream → roaring containers + op log), (b) the HTTP JSON import
    route end to end, and (c) the binary import-roaring route (the
    reference's fast path). Verified by exact Count afterwards."""
    import json as _json
    import urllib.request

    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize
    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import FieldOptions
    from pilosa_tpu.storage.view import VIEW_STANDARD

    rng = np.random.default_rng(13)
    n = int(SHARD_WIDTH * density)
    per_shard = [
        np.sort(rng.choice(SHARD_WIDTH, n, replace=False)).astype(np.uint64)
        for _ in range(n_shards)
    ]

    with tempfile.TemporaryDirectory() as tmp:
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="imp", anti_entropy_interval=0,
            heartbeat_interval=0,
            # this bench measures ROUTE cost with deliberately huge
            # bodies; the edge batch limit is the CLI's problem
            max_writes_per_request=0,
        )).open()
        try:
            idx = server.holder.create_index("b")
            f = idx.create_field("eng")
            # (a) engine layer
            t0 = time.perf_counter()
            total_bits = 0
            for shard, cols in enumerate(per_shard):
                frag = f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                )
                for row in range(1, rows_per_shard + 1):
                    frag.bulk_import(
                        np.full(cols.size, row, np.uint64), cols
                    )
                    total_bits += cols.size
            engine_s = time.perf_counter() - t0

            url = f"http://localhost:{server.port}"
            idx.create_field("http")

            def post(path, body, binary=False, raw=False):
                data = (body if binary or raw
                        else _json.dumps(body).encode())
                r = urllib.request.Request(url + path, data=data,
                                           method="POST")
                if binary:
                    r.add_header("Content-Type",
                                 "application/octet-stream")
                with urllib.request.urlopen(r, timeout=300) as resp:
                    return _json.loads(resp.read() or b"{}")

            # (b) HTTP JSON route — bodies pre-encoded OUTSIDE the timer
            # like the protobuf/roaring routes, so the published numbers
            # compare server-side route cost, not client encode cost
            json_bodies = []
            http_bits = 0
            for shard, cols in enumerate(per_shard):
                base = shard * SHARD_WIDTH
                for row in range(1, rows_per_shard + 1):
                    json_bodies.append(_json.dumps({
                        "rows": [row] * cols.size,
                        "columns": (cols + base).tolist(),
                    }).encode())
                    http_bits += cols.size
            t0 = time.perf_counter()
            for body in json_bodies:
                post("/index/b/field/http/import", body, binary=False,
                     raw=True)
            http_s = time.perf_counter() - t0

            # (b2) protobuf import route — the reference's actual client
            # path (ImportRequest bodies)
            from pilosa_tpu import wire

            proto_s = None
            if wire.available():
                from pilosa_tpu.wire.serializer import encode_import_request

                idx.create_field("pb")
                bodies = []
                for shard, cols in enumerate(per_shard):
                    base = shard * SHARD_WIDTH
                    for row in range(1, rows_per_shard + 1):
                        bodies.append(encode_import_request(
                            "b", "pb", np.full(cols.size, row, np.uint64),
                            cols + base,
                        ))
                t0 = time.perf_counter()
                for body in bodies:
                    r = urllib.request.Request(
                        f"{url}/index/b/field/pb/import", data=body,
                        method="POST",
                    )
                    r.add_header("Content-Type", "application/x-protobuf")
                    with urllib.request.urlopen(r, timeout=300):
                        pass
                proto_s = time.perf_counter() - t0

            # (c) binary roaring route (one bitmap per shard carrying
            # every row's bits as row<<20|pos ids)
            idx.create_field("roar")
            payloads = []
            for shard, cols in enumerate(per_shard):
                ids = np.concatenate([
                    (np.uint64(row) << np.uint64(20)) + cols
                    for row in range(1, rows_per_shard + 1)
                ])
                bm = RoaringBitmap()
                bm.add_ids(ids)
                payloads.append(serialize(bm))
            t0 = time.perf_counter()
            for shard, payload in enumerate(payloads):
                post(f"/index/b/field/roar/import-roaring/{shard}",
                     payload, binary=True)
            roaring_s = time.perf_counter() - t0

            ok = True
            checked = ["eng", "http", "roar"] + (
                ["pb"] if proto_s is not None else []
            )
            for fname in checked:
                for row in (1, rows_per_shard):
                    r = urllib.request.Request(
                        f"{url}/index/b/query",
                        data=f"Count(Row({fname}={row}))".encode(),
                        method="POST",
                    )
                    with urllib.request.urlopen(r, timeout=300) as resp:
                        got = _json.loads(resp.read())["results"][0]
                    ok = ok and got == n * n_shards

            # (d) BSI value import — batched bit-plane writes
            # (field.import_values / fragment.import_bsi)
            vfield = idx.create_field(
                "val", FieldOptions(type="int", min=0, max=100000)
            )
            n_vals = total_bits // 2
            vcols = rng.choice(n_shards * SHARD_WIDTH, n_vals,
                               replace=False).astype(np.uint64)
            vvals = rng.integers(0, 100000, n_vals, dtype=np.int64)
            t0 = time.perf_counter()
            vfield.import_values(vcols, vvals)
            values_s = time.perf_counter() - t0
            vprobe = int(vcols[0])
            ok = ok and vfield.value(vprobe) == (int(vvals[0]), True)

            out = {
                "config": "import",
                "metric": "bulk_import_bits_per_sec_engine",
                "value": round(total_bits / engine_s, 1),
                "unit": "bits/sec",
                "http_json_bits_per_sec": round(http_bits / http_s, 1),
                "http_roaring_bits_per_sec": round(total_bits / roaring_s, 1),
                "bits_per_field": total_bits, "shards": n_shards,
                "ok": bool(ok),
            }
            if proto_s is not None:
                out["http_protobuf_bits_per_sec"] = round(
                    total_bits / proto_s, 1
                )
            out["bsi_values_per_sec"] = round(n_vals / values_s, 1)
            return out
        finally:
            server.close()


def _merge_kernel_microbench(n_keys: int = 4096, per_key: int = 24,
                             reps: int = 9, seed: int = 7) -> dict:
    """In-bench merge-kernel gate: the whole-batch merge kernel
    (roaring/merge_kernels.merge_ids) vs the retired per-container
    write loop (bitmap._merge_loop, kept verbatim as the reference) on
    the bulk-import shape — one batch touching MANY containers with a
    couple dozen ids each, where the per-container Python envelope the
    kernel retires dominates. Byte-identity is asserted on EVERY rep
    (serialize equality + changed-count equality); best-of-``reps``
    timing on both sides."""
    from pilosa_tpu.roaring import merge_kernels, serialize
    from pilosa_tpu.roaring.bitmap import RoaringBitmap
    from pilosa_tpu.roaring.format import deserialize

    rng = np.random.default_rng(seed)

    def draw():
        keys = rng.integers(0, n_keys, n_keys * per_key).astype(np.uint64)
        lows = rng.integers(0, 65536, keys.size).astype(np.uint64)
        return np.unique((keys << np.uint64(16)) + lows)

    blob = serialize(RoaringBitmap.from_ids(draw()))
    batch = draw()
    best_kernel = best_loop = float("inf")
    identical = True
    for _ in range(reps):
        bm_k, _ = deserialize(blob)
        t0 = time.perf_counter()
        changed_k = merge_kernels.merge_ids(bm_k, batch.copy(), False)
        best_kernel = min(best_kernel, time.perf_counter() - t0)
        bm_l, _ = deserialize(blob)
        t0 = time.perf_counter()
        changed_l = bm_l._merge_loop(batch.copy(), False)
        best_loop = min(best_loop, time.perf_counter() - t0)
        identical = (identical and changed_k == changed_l
                     and serialize(bm_k) == serialize(bm_l))
    speedup = best_loop / best_kernel if best_kernel else 0.0
    return {
        "shape": {"containers": n_keys, "ids_per_container": per_key,
                  "batch_ids": int(batch.size)},
        "kernel_ms": round(best_kernel * 1e3, 2),
        "loop_ms": round(best_loop * 1e3, 2),
        "speedup": round(speedup, 2),
        "bytes_identical": bool(identical),
        "ok": bool(identical and speedup >= 2.0),
    }


def config_ingest(n_remote: int = 3, n_shards: int = 16,
                  density: float = 0.02, delay_s: float = 0.05) -> dict:
    """Parallel ingest pipeline (ISSUE 3): routed-import fan-out with an
    INJECTED per-call slow client. Proves two things on the same data:

    (a) concurrent fan-out wall time tracks the SLOWEST owner node's
        busy time (max), not the sum of all owners' busy times — the
        write-path analog of the read path's concurrent_map property;
    (b) routed bits/sec with the parallel fan-out beats the serialized
        fan-out (ingest_fanout_workers = 1) on identical batches.

    Also reports the local shard-group apply rate with the bounded
    worker pool on vs off (ingest-workers knob) — engine-layer, no
    injected latency — and runs the merge-kernel microbench (write-path
    fast lane): the whole-batch merge kernel must clear >=2x over the
    retired per-container loop with byte-identity asserted in-bench.

    Core-aware gating (the mp_serving precedent): the fan-out oracles
    are sleep-dominated and gate on any box, and the merge microbench
    is single-threaded numpy-vs-Python so it gates on any box too; only
    the local-apply worker-pool scaling needs real cores — >=6 cores
    enforces >=1.3x, 3-5 cores >=1.1x, below that the box is
    hardware-saturated and the ratio is recorded ungated."""
    import threading

    from pilosa_tpu.parallel.cluster import Cluster, Node
    from pilosa_tpu.server.api import API
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder

    class SlowClient:
        """Injectable transport: every import call sleeps ``delay``
        (one RTT) and acks the shipped bit count."""

        def __init__(self, delay: float):
            self.delay = delay
            self.per_uri: dict[str, int] = {}
            self._lock = threading.Lock()

        def _hit(self, uri: str, n: int) -> int:
            with self._lock:
                self.per_uri[uri] = self.per_uri.get(uri, 0) + 1
            time.sleep(self.delay)
            return n

        def import_roaring(self, uri, index, field, shard, data):
            from pilosa_tpu.roaring.format import load_any

            bm, _ = load_any(data)
            return self._hit(uri, int(bm.count()))

        def import_bits(self, uri, index, field, rows, columns,
                        timestamps=None, clear=False):
            return self._hit(uri, len(columns))

        def import_values(self, uri, index, field, columns, values,
                          clear=False):
            return self._hit(uri, len(columns))

        def send_message(self, uri, message):
            return {}

    rng = np.random.default_rng(21)
    n = int(SHARD_WIDTH * density)
    cols = np.concatenate([
        s * SHARD_WIDTH
        + np.sort(rng.choice(SHARD_WIDTH, n, replace=False))
        for s in range(n_shards)
    ]).astype(np.int64)
    rows = np.ones(cols.size, np.int64)

    def routed(fanout_workers: int, delay: float):
        with tempfile.TemporaryDirectory() as tmp:
            holder = Holder(tmp).open()
            api = API(holder)
            cluster = Cluster(
                Node("n0", "http://n0"),
                peers=[Node(f"n{i}", f"http://n{i}")
                       for i in range(1, n_remote + 1)],
                replica_n=1, holder=holder,
            )
            cluster.api = api
            api.cluster = cluster
            fake = SlowClient(delay)
            cluster.client = fake
            holder.create_index("b").create_field("f")
            api.ingest_fanout_workers = fanout_workers
            t0 = time.perf_counter()
            changed = api.import_bits("b", "f", rows, cols)
            wall = time.perf_counter() - t0
            holder.close()
            busy = {u: c * delay for u, c in fake.per_uri.items()}
            return wall, changed, busy

    wall_par, changed_par, busy = routed(16, delay_s)
    wall_ser, changed_ser, _ = routed(1, delay_s)
    # zero-delay pass isolates the route's fixed cost (slicing, roaring
    # serialization, local apply) so the delay-attributable remainder can
    # be compared against max vs sum of the injected node busy times
    wall_base, _, _ = routed(16, 0.0)
    sum_busy = sum(busy.values())
    max_busy = max(busy.values()) if busy else 0.0

    def engine(workers: int) -> float:
        with tempfile.TemporaryDirectory() as tmp:
            holder = Holder(tmp).open()
            api = API(holder)
            api.ingest_workers = workers
            holder.create_index("b").create_field("f")
            t0 = time.perf_counter()
            api.import_bits("b", "f", rows, cols)
            dt = time.perf_counter() - t0
            holder.close()
            return dt

    eng_ser = engine(1)
    eng_par = engine(4)

    # core-aware local-apply gate: the bounded worker pool shares this
    # box's cores with the bench driver itself, so scaling is only
    # measurable with real cores to spread onto (mp_serving precedent)
    cores = os.cpu_count() or 1
    eng_ratio = eng_ser / eng_par if eng_par else 0.0
    if cores >= 6:
        eng_ok, eng_gate = eng_ratio >= 1.3, "local-apply >= 1.3x"
    elif cores >= 3:
        eng_ok, eng_gate = eng_ratio >= 1.1, "local-apply >= 1.1x"
    else:
        eng_ok = True
        eng_gate = ("ungated: hardware-saturated (< 3 cores); ratio "
                    "recorded, fan-out + merge-kernel oracles still gate")

    merge = _merge_kernel_microbench()

    delay_wall = max(wall_par - wall_base, 0.0)
    ok = (changed_par == changed_ser == cols.size
          # delay-attributable fan-out time tracks the slowest node's
          # busy time (max), NOT the sum over nodes
          and delay_wall < (max_busy + sum_busy) / 2
          # parallel routed path beats the serialized one on same data
          and wall_par < 0.75 * wall_ser
          and eng_ok and merge["ok"])
    return {
        "config": "ingest",
        "metric": "routed_import_bits_per_sec",
        "value": round(cols.size / wall_par, 1),
        "unit": "bits/sec",
        "serial_routed_bits_per_sec": round(cols.size / wall_ser, 1),
        "speedup_vs_serial_fanout": round(wall_ser / wall_par, 2),
        "fanout_wall_ms": round(wall_par * 1e3, 1),
        "fanout_wall_serial_ms": round(wall_ser * 1e3, 1),
        "fanout_wall_nodelay_ms": round(wall_base * 1e3, 1),
        "slowest_node_busy_ms": round(max_busy * 1e3, 1),
        "sum_node_busy_ms": round(sum_busy * 1e3, 1),
        "local_apply_bits_per_sec_serial": round(cols.size / eng_ser, 1),
        "local_apply_bits_per_sec_parallel": round(cols.size / eng_par, 1),
        "local_apply_scaling": round(eng_ratio, 2),
        "cores": cores,
        "local_apply_gate": eng_gate,
        "merge_kernel": merge,
        "nodes": n_remote + 1, "shards": n_shards,
        "bits": int(cols.size), "injected_delay_ms": delay_s * 1e3,
        "ok": bool(ok),
    }


def config_sync(n_fragments: int = 192, n_divergent: int = 32,
                rows_per_block: int = 12, bits_per_row: int = 400,
                rounds: int = 2, injected_rtt_s: float = 0.005) -> dict:
    """Anti-entropy fast path (ISSUE 5): the SAME seeded divergence
    repaired against identical source clusters over two transports —

    - ``legacy``: the r5 per-fragment path end to end (catalog walk + one
      ``fragment_blocks`` GET per fragment + one block-data GET per
      differing block, serial pass), forced via the old-wire fallback
      (``_no_manifest_peers`` + ``sync_workers = 1``);
    - ``fastpath``: one batched manifest per peer, multi-block delta
      POSTs, ``sync-workers``-wide pipeline, compressed payloads.

    The SOURCE node runs as a real OS subprocess (``python -m pilosa_tpu
    server``, like tests/test_process_cluster.py) so the measured RTTs
    cross a process boundary the way production DCN hops do — two
    in-process nodes share one GIL, which flattens exactly the
    concurrency the pipeline exploits. The repairer stays in-process for
    instrumentation (RTT/byte counting on its connection pool).

    ``injected_rtt_s`` adds a fixed per-request transport delay to BOTH
    modes (the config_ingest precedent: loopback under-prices a network
    round trip by ~50×, and the fast path's whole claim is paying fewer
    of them; 5 ms is a conservative inter-host DCN hop). The shared local
    work — checksum walks, block merges — is identical either way and
    paid for real.

    Measures control-plane round trips, bytes on the wire, and repair
    wall time; ok requires byte-identical post-repair fragments across
    the two modes, ≥5× fewer RTTs, and ≥2× lower wall. A final phase
    re-runs a paced repair (`repair-max-bytes-per-sec`) under a
    concurrent serving client and reports the query p95 — resize storms
    must not starve serving."""
    import os
    import socket
    import subprocess
    import sys
    import threading
    import urllib.request

    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize
    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    def post(port, path, data, binary=False):
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data, method="POST"
        )
        if binary:
            r.add_header("Content-Type", "application/octet-stream")
        with urllib.request.urlopen(r, timeout=120) as resp:
            return resp.read()

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    # deterministic payloads, built once: base data for every fragment
    # (replicated) + WIDE, shallow divergence (a missed write here and
    # there across many fragments — the anti-entropy steady state, where
    # control RTTs dominate the repair and the fast path pays off)
    rng = np.random.default_rng(17)
    base_payloads = []
    for _ in range(n_fragments):
        rows = np.repeat(np.arange(rows_per_block, dtype=np.uint64), 64)
        poss = rng.integers(0, SHARD_WIDTH, rows.size, dtype=np.uint64)
        bm = RoaringBitmap()
        bm.add_ids((rows << np.uint64(20)) + poss)
        base_payloads.append(serialize(bm))
    div_payloads = []
    for _ in range(n_divergent):
        rows = np.repeat(np.arange(3, dtype=np.uint64), bits_per_row)
        poss = np.concatenate([
            rng.choice(SHARD_WIDTH, bits_per_row,
                       replace=False).astype(np.uint64)
            for _ in range(3)
        ])
        bm = RoaringBitmap()
        bm.add_ids((rows << np.uint64(20)) + poss)
        div_payloads.append(serialize(bm))

    def spawn_source(tmp) -> tuple:
        """Boot the divergence source as a separate OS process and seed
        it over HTTP (?remote=true applies locally, no fan-out)."""
        port = free_port()
        args = [
            sys.executable, "-m", "pilosa_tpu", "server",
            "--data-dir", f"{tmp}/src", "--bind", "127.0.0.1",
            "--port", str(port),
        ]
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "PILOSA_TPU_NAME": "src",
            "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "0",
            "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
            "PILOSA_TPU_USE_MESH": "false",
        }
        proc = subprocess.Popen(args, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        for _ in range(240):
            if proc.poll() is not None:
                raise AssertionError(f"source exited rc={proc.returncode}")
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/status", timeout=5
                ).read()
                break
            except Exception:
                time.sleep(0.25)
        else:
            proc.terminate()
            raise AssertionError("source never served /status")
        # trackExistence off: the HTTP imports below would otherwise
        # populate the _exists field on the source only, drowning the
        # seeded divergence in existence-bit repair traffic
        post(port, "/index/i",
             b'{"options": {"trackExistence": false}}')
        post(port, "/index/i/field/f", b"{}")
        for shard, payload in enumerate(base_payloads):
            post(port,
                 f"/index/i/field/f/import-roaring/{shard}?remote=true",
                 payload, binary=True)
        for shard, payload in enumerate(div_payloads):
            post(port,
                 f"/index/i/field/f/import-roaring/{shard}?remote=true",
                 payload, binary=True)
        return proc, port

    def make_repairer(tmp, src_port, legacy: bool) -> "Server":
        """In-process repairer holding only the BASE data. Membership is
        wired directly (no seed join — the join path's gated self-join
        fetch would repair the divergence before the measured pass)."""
        from pilosa_tpu.parallel.cluster import Node

        s1 = Server(ServerConfig(
            data_dir=f"{tmp}/rep", port=0, name="rep", replica_n=2,
            anti_entropy_interval=0, heartbeat_interval=0,
            use_mesh=False,
        )).open()
        s1.holder.create_index("i", track_existence=False).create_field("f")
        f1 = s1.holder.index("i").field("f")
        view = f1.view(VIEW_STANDARD, create=True)
        for shard, payload in enumerate(base_payloads):
            view.fragment(shard, create=True).import_roaring(payload)
        s1.api.cluster.nodes["src"] = Node(
            "src", f"http://127.0.0.1:{src_port}"
        )
        if legacy:
            s1.api.cluster.sync_workers = 1
            s1.api.cluster.client._no_manifest_peers.add(
                f"http://127.0.0.1:{src_port}"
            )
        return s1

    def run_mode(legacy: bool):
        best_wall = float("inf")
        rtts = bytes_wire = repaired = snap = converged = None
        for _ in range(rounds):
            with tempfile.TemporaryDirectory() as tmp:
                proc, src_port = spawn_source(tmp)
                s1 = make_repairer(tmp, src_port, legacy)
                try:
                    pool = s1.api.cluster.client.pool
                    counts = {"rtts": 0, "bytes": 0}
                    real = pool.request

                    def counting(method, url, body=None, headers=None,
                                 timeout=None, real=real, counts=counts):
                        if injected_rtt_s > 0:
                            time.sleep(injected_rtt_s)
                        resp = real(method, url, body=body,
                                    headers=headers, timeout=timeout)
                        counts["rtts"] += 1
                        counts["bytes"] += (
                            len(body or b"") + len(resp.data)
                        )
                        return resp

                    pool.request = counting
                    t0 = time.perf_counter()
                    rep = s1.api.cluster.sync_holder()
                    dt = time.perf_counter() - t0
                    pool.request = real
                    f1 = s1.holder.index("i").field("f")
                    snap = b"".join(
                        f1.view(VIEW_STANDARD).fragment(s)
                        .serialize_snapshot()
                        for s in range(n_fragments)
                    )
                    # convergence oracle: the repairer's checksums match
                    # the source's, fetched by an independent client
                    from pilosa_tpu.parallel.client import InternalClient

                    oracle = InternalClient()
                    src_manifest = dict(
                        ((f, v, s), dict(blocks)) for f, v, s, blocks in
                        oracle.sync_manifest(
                            f"http://127.0.0.1:{src_port}", "i")
                    )
                    oracle.pool.close()
                    converged = all(
                        dict(f1.view(VIEW_STANDARD).fragment(s).blocks())
                        == src_manifest.get(("f", VIEW_STANDARD, s), {})
                        for s in range(n_fragments)
                    )
                    rtts, bytes_wire = counts["rtts"], counts["bytes"]
                    repaired = rep
                    best_wall = min(best_wall, dt)
                finally:
                    s1.close()
                    proc.terminate()
                    proc.wait(timeout=30)
        return {
            "rtts": rtts, "bytes": bytes_wire,
            "wall_ms": round(best_wall * 1e3, 1),
            "bits_repaired": repaired["bits"], "converged": converged,
            "snapshot": snap,
        }

    legacy = run_mode(True)
    fast = run_mode(False)
    byte_identical = legacy.pop("snapshot") == fast.pop("snapshot")
    rtt_factor = round(legacy["rtts"] / max(fast["rtts"], 1), 2)
    wall_factor = round(legacy["wall_ms"] / max(fast["wall_ms"], 1e-9), 2)

    # paced repair under concurrent serving: the pacer must shape the
    # transfer without starving queries on the repairing node
    with tempfile.TemporaryDirectory() as tmp:
        proc, src_port = spawn_source(tmp)
        s1 = make_repairer(tmp, src_port, legacy=False)
        try:
            from pilosa_tpu.parallel.pacer import RepairPacer

            # rate sized so the divergent payload takes a visible ~1-2 s
            s1.api.cluster.client.pacer = RepairPacer(
                max_bytes_per_sec=64_000, max_inflight=2,
            )
            latencies: list = []
            stop = threading.Event()

            def serve():
                while not stop.is_set():
                    t0 = time.perf_counter()
                    post(s1.port,
                         "/index/i/query?shards=0,1,2,3",
                         b"Count(Row(f=1))")
                    latencies.append(time.perf_counter() - t0)

            t = threading.Thread(target=serve, daemon=True)
            post(s1.port, "/index/i/query?shards=0,1,2,3",
                 b"Count(Row(f=1))")  # warm the compile
            t.start()
            t0 = time.perf_counter()
            s1.api.cluster.sync_holder()
            paced_wall = time.perf_counter() - t0
            stop.set()
            t.join(30)
            paced_sleep = s1.api.cluster.client.pacer.paced_sleep_s
            p95 = (float(np.quantile(latencies, 0.95))
                   if latencies else None)
        finally:
            s1.close()
            proc.terminate()
            proc.wait(timeout=30)

    ok = (byte_identical
          and legacy["converged"] and fast["converged"]
          and legacy["bits_repaired"] == fast["bits_repaired"] > 0
          and rtt_factor >= 5.0
          and wall_factor >= 2.0
          and paced_sleep > 0          # the pacer actually shaped traffic
          and p95 is not None and p95 < 1.0)
    return {
        "config": "sync",
        "metric": "repair_control_rtt_reduction_factor",
        "value": rtt_factor,
        "unit": "x fewer round trips",
        "wall_speedup": wall_factor,
        "legacy": {k: legacy[k] for k in
                   ("rtts", "bytes", "wall_ms", "bits_repaired")},
        "fastpath": {k: fast[k] for k in
                     ("rtts", "bytes", "wall_ms", "bits_repaired")},
        "byte_identical_post_repair": byte_identical,
        "paced_repair": {
            "wall_ms": round(paced_wall * 1e3, 1),
            "paced_sleep_ms": round(paced_sleep * 1e3, 1),
            "serving_p95_ms_during_repair": (
                round(p95 * 1e3, 1) if p95 is not None else None
            ),
            "serving_samples": len(latencies),
        },
        "fragments": n_fragments, "divergent": n_divergent,
        "injected_rtt_ms": injected_rtt_s * 1e3,
        "ok": bool(ok),
    }


def config_hostpath(n_shards: int = 8) -> dict:
    """Host-path gate, two halves (ISSUE 18):

    1. **Roaring kernel microbenches** — the three host paths the
       vectorized kernel layer (pilosa_tpu/roaring/kernels.py)
       rewired: row **decode** (residency miss), **scrub**-style block
       digesting, and **sync** manifest-diff block materialization.
       Each is timed against an in-bench copy of the retired
       per-container loop over the SAME fragment, asserted
       byte-identical, and gated at >= 2x. PROFILE-tree attribution
       (containers scanned by kind, one tally per kernel call) rides
       the decode half.
    2. **Executor submit** — host cost of the pipelined submit path
       with the batched device program stubbed (parse -> plan cache ->
       operand memo -> micro-batch group), tracked as a number so a
       serving-path host regression shows up as a regression."""
    kernels_half = _hostpath_kernel_microbenches()
    submit_half = _hostpath_submit(n_shards)
    return {
        "config": "hostpath",
        "metric": "hostpath_kernel_speedups",
        "microbenches": kernels_half["microbenches"],
        "min_speedup": kernels_half["min_speedup"],
        "bytes_identical": kernels_half["bytes_identical"],
        "profile_attribution": kernels_half["profile_attribution"],
        "submit": submit_half,
        "ok": bool(kernels_half["ok"] and submit_half["ok"]),
        "note": ("kernel microbenches: batched numpy kernels vs the "
                 "retired per-container reference loops, byte-identical "
                 "outputs asserted in-bench, gate >= 2x on each of "
                 "decode/scrub/sync. submit: Executor.submit with the "
                 "batched device program stubbed (see submit.note)."),
    }


def _hostpath_kernel_microbenches() -> dict:
    """Scrub / sync / decode against per-container reference loops."""
    import tempfile

    from pilosa_tpu.roaring import kernels
    from pilosa_tpu.storage.fragment import BLOCK_ROWS, Fragment
    from pilosa_tpu.storage.integrity import block_digests
    from pilosa_tpu.utils.cost import (
        QueryProfile,
        activate_cost,
        deactivate_cost,
        new_cost_context,
        use_node,
    )

    rng = np.random.default_rng(18)

    # ------------------------------------------ per-container references
    # (verbatim shape of the retired loops — tests/test_roaring_kernels
    # pins byte-identity; here they are the baseline being beaten)

    def ref_to_ids(bm) -> np.ndarray:
        parts = []
        for key in bm.keys:
            c = bm._containers.get(key)
            if c is None or not c.n:
                continue
            parts.append((np.uint64(key) << np.uint64(16))
                         + c.lows().astype(np.uint64))
        if not parts:
            return np.empty(0, np.uint64)
        return np.concatenate(parts)

    def ref_row_words(bm, row: int) -> np.ndarray:
        return bm.dense_range_words32(row << 20, (row + 1) << 20)

    def ref_block_ids(ids: np.ndarray, blocks) -> dict:
        width = np.uint64(BLOCK_ROWS << 20)
        out = {}
        for b in blocks:
            lo = np.uint64(b) * width
            out[int(b)] = ids[(ids >= lo) & (ids < lo + width)]
        return out

    def best_of(fn, repeats: int = 5) -> float:
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    micro = {}
    identical = True
    with tempfile.TemporaryDirectory() as tmp:
        frag = Fragment(f"{tmp}/f", "i", "f", "standard", 0).open()
        # genuinely mixed-kind fragment across many blocks: mostly
        # sparse array rows (~4 set bits per container), some mid-density
        # array rows (~437 per container), a few bitmap rows (8000 per
        # container, past the 4096 array ceiling), and run rows
        rows, cols = [], []
        for r in range(0, 220, 2):
            if r % 44 == 0:  # bitmap row: every container dense
                for k in range(16):
                    rows.append(np.full(8000, r, np.uint64))
                    cols.append((np.uint64(k) << np.uint64(16))
                                + rng.choice(1 << 16, 8000,
                                             replace=False).astype(np.uint64))
            else:
                n = 7000 if r % 6 == 2 else 60
                rows.append(np.full(n, r, np.uint64))
                cols.append(rng.integers(0, 1 << 20, n, dtype=np.uint64))
        for r in (221, 223):
            rows.append(np.full(120000, r, np.uint64))
            cols.append(np.arange(120000, dtype=np.uint64))
        frag.bulk_import(np.concatenate(rows), np.concatenate(cols))
        bm = frag.bitmap

        # decode: residency-miss dense row materialization over a kind
        # mix (sparse + mid arrays dominate, as on a real fragment, plus
        # a bitmap row and a run row), PROFILE attribution on the
        # kernel side
        dense_rows = ([r for r in range(0, 220, 2)
                       if r % 44 and r % 6 != 2][:16]
                      + [r for r in range(0, 220, 2) if r % 6 == 2][:4]
                      + [0, 221])
        profile = QueryProfile("i", "hostpath-bench")
        ctx = new_cost_context("bench", "i", profile=profile)
        node = profile.node_for(0, None)
        tok = activate_cost(ctx)
        try:
            with use_node(ctx, node):
                got_rows = [frag.row_words(r) for r in dense_rows]
        finally:
            deactivate_cost(tok)
        want_rows = [ref_row_words(bm, r) for r in dense_rows]
        identical &= all(np.array_equal(g, w)
                         for g, w in zip(got_rows, want_rows))
        t_kernel = best_of(
            lambda: [frag.row_words(r) for r in dense_rows])
        t_ref = best_of(
            lambda: [ref_row_words(bm, r) for r in dense_rows])
        micro["decode"] = {
            "reference_us": round(t_ref * 1e6, 1),
            "kernel_us": round(t_kernel * 1e6, 1),
            "speedup": round(t_ref / t_kernel, 2) if t_kernel else 0.0,
        }
        profile_attr = {
            "containers_scanned": {
                "array": ctx.c_array, "bitmap": ctx.c_bitmap,
                "run": ctx.c_run,
            },
            "kernel_calls": len(dense_rows),
            "note": ("one note_containers tally per kernel call on the "
                     "batched path; totals equal the per-container walk "
                     "(pinned by tests/test_roaring_kernels.py)"),
        }

        # scrub: verified-load id materialization straight off the
        # serialized snapshot bytes (verify_fragment_file's
        # build_bitmap=False path and the scrubber's replica-copy
        # checksum both reduce to this). The timed half is the part the
        # kernels changed — bytes -> sorted ids; the blake2b digesting
        # that follows consumes byte-identical input on both sides and
        # is reported once as a constant.
        from pilosa_tpu.roaring.format import deserialize, serialize

        snap = serialize(bm)

        def scrub_kernel():
            return kernels.snapshot_ids(snap)[0]

        def scrub_ref():
            # the retired path: container-object decode, then the
            # per-container lows() walk (live to_ids now rides the
            # kernels, so the walk is reconstructed in-bench)
            return ref_to_ids(deserialize(snap)[0])

        # time first, verify after: the identity checks materialize
        # multi-MB byte strings, and leaving those on the heap during
        # timing skews BOTH sides with allocator (mmap) churn
        t_kernel = best_of(scrub_kernel)
        t_ref = best_of(scrub_ref)
        ids_k, ids_r = scrub_kernel(), scrub_ref()
        identical &= bool(np.array_equal(ids_k, ids_r))
        identical &= (block_digests(ids_k, BLOCK_ROWS)
                      == block_digests(ids_r, BLOCK_ROWS))
        t_digest = best_of(lambda: block_digests(ids_k, BLOCK_ROWS))
        micro["scrub"] = {
            "reference_us": round(t_ref * 1e6, 1),
            "kernel_us": round(t_kernel * 1e6, 1),
            "speedup": round(t_ref / t_kernel, 2) if t_kernel else 0.0,
            "digest_us_both_sides": round(t_digest * 1e6, 1),
        }

        # sync: a manifest diff wants N divergent blocks — materialize
        # their id sets (http.post_sync_blocks serves exactly this)
        wanted = sorted({int(r) // BLOCK_ROWS
                         for r in range(0, 220, 2)})

        def sync_kernel():
            return frag.blocks_ids(wanted)

        def sync_ref():
            return ref_block_ids(ref_to_ids(bm), wanted)

        gk, gr = sync_kernel(), sync_ref()
        identical &= (sorted(gk) == sorted(gr) and all(
            gk[b].tobytes() == gr[b].tobytes() for b in gk))
        t_kernel = best_of(sync_kernel)
        t_ref = best_of(sync_ref)
        micro["sync"] = {
            "reference_us": round(t_ref * 1e6, 1),
            "kernel_us": round(t_kernel * 1e6, 1),
            "speedup": round(t_ref / t_kernel, 2) if t_kernel else 0.0,
        }
        frag.close()

    min_speedup = min(m["speedup"] for m in micro.values())
    return {
        "microbenches": micro,
        "min_speedup": min_speedup,
        "bytes_identical": bool(identical),
        "profile_attribution": profile_attr,
        "ok": bool(identical and min_speedup >= 2.0),
    }


def _hostpath_submit(n_shards: int = 8) -> dict:
    """Host-side cost of the pipelined submit path, device excluded.

    The executor-vs-kernel ratio is bounded by how fast the HOST can
    feed micro-batched dispatches (parse -> plan cache -> operand memo ->
    micro-batch group), so this config times `Executor.submit` with the
    batched program stubbed out: pure framework cost per query, in
    microseconds, with the operand memo on and off. CPU-representative
    (no device work is dispatched); tracked so a serving-path host
    regression shows up as a number, not a vibe."""
    import itertools
    import tempfile

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage.view import VIEW_STANDARD

    K = 8
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp).open()
        idx = holder.create_index("b")
        rows = np.repeat(np.arange(1, K + 1, dtype=np.uint64), 64)
        for fname in ("a", "b"):
            f = idx.create_field(fname)
            view = f.view(VIEW_STANDARD, create=True)
            for shard in range(n_shards):
                cols = rng.integers(0, SHARD_WIDTH, rows.size,
                                    dtype=np.uint64)
                view.fragment(shard, create=True).bulk_import(rows, cols)

        def pql(k, j):
            return f"Count(Intersect(Row(a={k}), Row(b={j})))"

        def combo(g):
            n = K * K
            c = (5 * g + g // n) % n
            return 1 + c // K, 1 + c % K

        def measure(memo_on: bool) -> float:
            ex = Executor(holder)
            if not memo_on:
                # disable by forcing the per-plan bypass
                orig = ex._eval_operands
                ex._eval_operands = (
                    lambda idx, c, b, extra_leaves=(), memoize=True:
                    orig(idx, c, b, extra_leaves, memoize=False)
                )
            for k in range(1, K + 1):
                ex.execute("b", pql(k, k))
            g = itertools.count(0)
            warm = [ex.submit("b", pql(*combo(next(g))))[0]
                    for _ in range(70)]
            warm[-1].result()
            stub = np.zeros((ex.microbatch_max, 2), np.int32)
            ex._program_batched = lambda *a, **k: (lambda *args: stub)
            n = 4096
            best = float("inf")
            for _ in range(4):
                t0 = time.perf_counter()
                for _ in range(n):
                    ex.submit("b", pql(*combo(next(g))))
                best = min(best, (time.perf_counter() - t0) / n)
            return best

        on = measure(True)
        off = measure(False)
        holder.close()
    return {
        "metric": "submit_host_us_per_query",
        "value": round(on * 1e6, 1),
        "unit": "us/query",
        "memo_off_us": round(off * 1e6, 1),
        "per_dispatch_ms_at_16": round(on * 16 * 1e3, 3),
        "shards": n_shards,
        "ok": True,
        "note": ("Executor.submit with the batched device program stubbed: "
                 "parse + plan cache + operand memo + micro-batch group "
                 "cost per query. memo_off_us re-measures with the operand "
                 "memo bypassed (the delta is what the memo buys)."),
    }


def config_tracing(n_shards: int = 8, n_queries: int = 256,
                   n_clients: int = 32, repeats: int = 4) -> dict:
    """Tracing overhead gate (ISSUE 7): the observability plane must be
    effectively free when off and cheap when sampling.

    One in-process server, keep-alive clients (the fast-lane transport),
    four plateau passes on the SAME data/queries, best-of-``repeats``:

    - ``bare``: trace sampling 0 AND the in-flight inspector disabled —
      the fast-lane serving plateau with every observability hook on its
      cheapest path. This is the baseline.
    - ``off``: shipping defaults — sampling 0, inspector ON (the
      /debug/queries view is always-on in production). Gate: >= 99% of
      bare (disabled tracing costs <= 1%).
    - ``sampled``: trace-sample-rate 0.01. Gate: >= 95% of bare
      (1%-sampled tracing costs <= 5%).
    - ``full``: rate 1.0 — informational: what always-on tracing costs.

    Sanity oracle: the full pass must actually produce span trees whose
    roots are http.query with executor + wave children, and the
    in-flight tracker must be empty once the run drains."""
    import http.client as _hc
    import threading

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD
    from pilosa_tpu.utils.tracing import (
        global_query_tracker,
        global_tracer,
    )

    rng = np.random.default_rng(11)
    tracer = global_tracer()
    tracker = global_query_tracker()
    with tempfile.TemporaryDirectory() as tmp:
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="bench-tracing",
            anti_entropy_interval=0, heartbeat_interval=0,
        )).open()
        try:
            idx = server.holder.create_index("t")
            f = idx.create_field("f")
            n = int(SHARD_WIDTH * 0.05)
            for shard in range(n_shards):
                frag = f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                )
                for row in range(1, 5):
                    frag.bulk_import(
                        np.full(n, row, np.uint64),
                        rng.choice(SHARD_WIDTH, n, replace=False).astype(
                            np.uint64
                        ),
                    )
            server.api.cluster.note_local_shards("t", list(range(n_shards)))
            port = server.port
            queries = [
                "Count(Intersect(Row(f={}), Row(f={})))".format(
                    1 + (i % 4), 1 + ((i + 1) % 4))
                for i in range(n_queries)
            ]

            def run_once() -> float:
                results = [None] * n_queries
                errors: list = []
                gate = threading.Event()

                def worker(tid):
                    conn = _hc.HTTPConnection("localhost", port,
                                              timeout=120)
                    gate.wait(30)
                    for k in range(tid, n_queries, n_clients):
                        try:
                            conn.request("POST", "/index/t/query",
                                         body=queries[k].encode())
                            results[k] = conn.getresponse().read()
                        except Exception as e:  # surfaced below
                            errors.append(repr(e))
                    conn.close()

                threads = [threading.Thread(target=worker, args=(t,))
                           for t in range(n_clients)]
                for t in threads:
                    t.start()
                t0 = time.perf_counter()
                gate.set()
                for t in threads:
                    t.join(300)
                if errors or None in results:
                    raise RuntimeError(f"bench errors: {errors[:3]}")
                return n_queries / (time.perf_counter() - t0)

            run_once()  # warm: compiles the batched program shapes

            def plateau(sample_rate: float, inspector: bool) -> float:
                tracer.sample_rate = sample_rate
                tracker.enabled = inspector
                try:
                    return max(run_once() for _ in range(repeats))
                finally:
                    tracer.sample_rate = 0.0
                    tracker.enabled = True

            bare = plateau(0.0, inspector=False)
            off = plateau(0.0, inspector=True)
            sampled = plateau(0.01, inspector=True)
            full = plateau(1.0, inspector=True)

            # sanity oracle on the full pass's trees
            trees = tracer.recent()
            roots = {t["name"] for t in trees}
            span_names: set = set()

            def walk(node):
                span_names.add(node["name"])
                for c in node.get("children", []):
                    walk(c)

            for t in trees:
                walk(t)
            traces_ok = (
                "http.query" in roots
                and "executor.Execute" in span_names
                and "pipeline.wave" in span_names
            )
            drained = not tracker.snapshot()
        finally:
            tracer.sample_rate = 0.0
            tracker.enabled = True
            server.close()

    off_ratio = off / max(bare, 1e-9)
    sampled_ratio = sampled / max(bare, 1e-9)
    ok = (off_ratio >= 0.99 and sampled_ratio >= 0.95
          and traces_ok and drained)
    return {
        "config": "tracing",
        "metric": "tracing_off_plateau_ratio",
        "value": round(off_ratio, 4),
        "unit": "fraction of bare fast-lane plateau",
        "bare_qps": round(bare, 1),
        "off_qps": round(off, 1),
        "sampled_1pct_qps": round(sampled, 1),
        "full_sampled_qps": round(full, 1),
        "sampled_ratio": round(sampled_ratio, 4),
        "full_ratio": round(full / max(bare, 1e-9), 4),
        "traces_ok": bool(traces_ok),
        "inflight_drained": bool(drained),
        "queries": n_queries, "clients": n_clients, "shards": n_shards,
        "gates": {"off_vs_bare": ">=0.99", "sampled_vs_bare": ">=0.95"},
        "ok": bool(ok),
    }


def config_profiling(n_shards: int = 8, n_queries: int = 256,
                     n_clients: int = 32, repeats: int = 4) -> dict:
    """Query-cost-plane overhead gate (ISSUE 8): accounting must be
    effectively free when nobody asks for a profile, and PROFILE itself
    must stay cheap enough to run against production traffic.

    One in-process server, keep-alive clients, three plateau passes on
    the SAME data/queries, best-of-``repeats``:

    - ``bare``: the cost plane disabled entirely
      (utils/cost.set_cost_enabled(False)) — every hook on its
      cheapest predicate path. The baseline.
    - ``off``: shipping defaults — plane on (tenant ledger, heat map,
      SLO feed), no ?profile= param. Gate: >= 99% of bare.
    - ``on``: every request carries ?profile=true (per-AST-node tree,
      per-leaf records, result-cardinality popcounts). Gate: >= 90% of
      bare — PROFILE is a debugging surface, but one you can leave on.

    Sanity oracles: the on pass actually returns profile trees with
    calls + totals, the ledger counted the off+on traffic, and the heat
    map ranks the queried field hot."""
    import http.client as _hc
    import threading

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.heat import global_heat
    from pilosa_tpu.storage.view import VIEW_STANDARD
    from pilosa_tpu.utils.cost import set_cost_enabled

    rng = np.random.default_rng(13)
    with tempfile.TemporaryDirectory() as tmp:
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="bench-profiling",
            anti_entropy_interval=0, heartbeat_interval=0,
        )).open()
        try:
            idx = server.holder.create_index("p")
            f = idx.create_field("f")
            n = int(SHARD_WIDTH * 0.05)
            for shard in range(n_shards):
                frag = f.view(VIEW_STANDARD, create=True).fragment(
                    shard, create=True
                )
                for row in range(1, 5):
                    frag.bulk_import(
                        np.full(n, row, np.uint64),
                        rng.choice(SHARD_WIDTH, n, replace=False).astype(
                            np.uint64
                        ),
                    )
            server.api.cluster.note_local_shards("p", list(range(n_shards)))
            port = server.port
            queries = [
                "Count(Intersect(Row(f={}), Row(f={})))".format(
                    1 + (i % 4), 1 + ((i + 1) % 4))
                for i in range(n_queries)
            ]

            def run_once(profile: bool) -> float:
                suffix = "?profile=true" if profile else ""
                results = [None] * n_queries
                errors: list = []
                gate = threading.Event()

                def worker(tid):
                    conn = _hc.HTTPConnection("localhost", port,
                                              timeout=120)
                    gate.wait(30)
                    for k in range(tid, n_queries, n_clients):
                        try:
                            conn.request("POST",
                                         f"/index/p/query{suffix}",
                                         body=queries[k].encode())
                            results[k] = conn.getresponse().read()
                        except Exception as e:  # surfaced below
                            errors.append(repr(e))
                    conn.close()

                threads = [threading.Thread(target=worker, args=(t,))
                           for t in range(n_clients)]
                for t in threads:
                    t.start()
                t0 = time.perf_counter()
                gate.set()
                for t in threads:
                    t.join(300)
                if errors or None in results:
                    raise RuntimeError(f"bench errors: {errors[:3]}")
                if profile:
                    sample = json.loads(results[0])
                    prof = sample.get("profile") or {}
                    if not (prof.get("calls")
                            and prof.get("totals") is not None):
                        raise RuntimeError(
                            "profiled response missing profile tree")
                return n_queries / (time.perf_counter() - t0)

            run_once(False)  # warm: compiles the batched program shapes

            def one_pass(enabled: bool, profile: bool) -> float:
                set_cost_enabled(enabled)
                try:
                    return run_once(profile)
                finally:
                    set_cost_enabled(True)

            # INTERLEAVED rounds (bare, off, on back to back per round)
            # gated on the BEST per-round ratio — the suite-wide best-of
            # philosophy: machine-load drift on a shared CI box only
            # ever makes the hook path look slower than it is, so if any
            # round shows off >= 0.99x bare under identical conditions
            # the intrinsic overhead is within the contract (the
            # microbenchmarked hook cost is ~5us/request ~= 0.4%). The
            # median ratio is reported beside it for drift visibility.
            rounds = []
            for _ in range(repeats):
                rounds.append((one_pass(False, profile=False),
                               one_pass(True, profile=False),
                               one_pass(True, profile=True)))
            bare = max(r[0] for r in rounds)
            off = max(r[1] for r in rounds)
            on = max(r[2] for r in rounds)
            off_ratios = sorted(r[1] / r[0] for r in rounds)
            on_ratios = sorted(r[2] / r[0] for r in rounds)
            off_ratio = off_ratios[-1]
            on_ratio = on_ratios[-1]
            off_median = off_ratios[len(off_ratios) // 2]
            on_median = on_ratios[len(on_ratios) // 2]

            ledger_rows = server.api.cost.snapshot()
            ledger_ok = (ledger_rows
                         and ledger_rows[0]["queries"]
                         >= 2 * repeats * n_queries)
            heat_rows = global_heat().hottest(4)
            heat_ok = bool(heat_rows
                           and heat_rows[0]["index"] == "p"
                           and heat_rows[0]["field"] == "f")
        finally:
            set_cost_enabled(True)
            global_heat().clear()
            server.close()

    ok = (off_ratio >= 0.99 and on_ratio >= 0.90
          and bool(ledger_ok) and heat_ok)
    return {
        "config": "profiling",
        "metric": "profile_off_plateau_ratio",
        "value": round(off_ratio, 4),
        "unit": "fraction of bare fast-lane plateau",
        "bare_qps": round(bare, 1),
        "off_qps": round(off, 1),
        "profiled_qps": round(on, 1),
        "profiled_ratio": round(on_ratio, 4),
        "off_ratio_median": round(off_median, 4),
        "profiled_ratio_median": round(on_median, 4),
        "ledger_ok": bool(ledger_ok),
        "heat_ok": bool(heat_ok),
        "queries": n_queries, "clients": n_clients, "shards": n_shards,
        "gates": {"off_vs_bare": ">=0.99", "profiled_vs_bare": ">=0.90"},
        "ok": bool(ok),
    }


def config_scrub(n_shards: int = 4, n_clients: int = 4,
                 queries_per_client: int = 120,
                 n_chaos_schedules: int = 2,
                 detection_bound_s: float = 5.0,
                 overhead_floor: float = 0.97) -> dict:
    """Self-healing storage integrity gate (ISSUE 10): four phases
    against real in-process servers —

    1. **Serving overhead**: a read plateau measured with the scrubber
       OFF then ON (200 ms interval + a 1 MiB/s pacer — already ~4
       orders of magnitude hotter than a production scrub-interval of
       minutes-to-hours, while the pacer keeps each pass's decode work
       off the serving threads' GIL) — gated at on/off ≥
       ``overhead_floor`` (the ≤3% acceptance bound), with at least
       one full pass required during the plateau.
    2. **Detection latency**: a seeded bit flip in a live fragment's
       snapshot, scrubber ticking — seconds until quarantine+heal,
       gated ≤ ``detection_bound_s``.
    3. **Corruption-heal oracle** (2 nodes, replica_n=2): flip one
       replica's fragment on disk, serve reads from THAT node
       throughout the scrub window (every response compared against
       truth — zero corrupt responses), then require the fragment
       quarantined, read-repaired BYTE-IDENTICAL to the healthy
       replica, and every acked write queryable (zero lost). Then
       ENOSPC injection on the same node: writes shed 503 +
       storageDegraded on /status, and the probe auto-recovers once
       the fault clears.
    4. **Randomized schedules**: ``n_chaos_schedules`` chaos runs with
       storage faults on (bit-flip + disk-full events beside
       partition/kill/restart), gated on the disk-integrity oracle
       plus the four partition oracles (testing/chaos.py)."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    def req(base, path, body=None, method=None, timeout=30):
        r = urllib.request.Request(
            f"{base}{path}", data=body,
            method=method or ("POST" if body is not None else "GET"),
        )
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return _json.loads(resp.read() or b"{}")

    def boot(data_dir, name, seeds=(), replica_n=1):
        return Server(ServerConfig(
            data_dir=data_dir, port=0, name=name, replica_n=replica_n,
            seeds=list(seeds), anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
        )).open()

    def base_of(s):
        return f"http://localhost:{s.port}"

    def flip_byte(path, offset=64, mask=0x20):
        with open(path, "r+b") as f:
            f.seek(offset)
            b = f.read(1)
            f.seek(offset)
            f.write(bytes([b[0] ^ mask]))

    out = {"config": "scrub", "metric": "storage_integrity_oracles"}
    t_start = time.time()

    # ---- phase 1+2: overhead + detection, single node -----------------
    with tempfile.TemporaryDirectory() as tmp:
        s = boot(f"{tmp}/solo", "solo")
        try:
            base = base_of(s)
            req(base, "/index/i", b"{}")
            req(base, "/index/i/field/f", b"{}")
            rng = np.random.default_rng(10)
            for shard in range(n_shards):
                cols = (rng.choice(SHARD_WIDTH, 400, replace=False)
                        + shard * SHARD_WIDTH)
                body = _json.dumps({
                    "rows": [1] * len(cols),
                    "columns": [int(c) for c in cols],
                }).encode()
                req(base, "/index/i/field/f/import", body)
            frags = [
                s.holder.index("i").field("f").view(VIEW_STANDARD)
                .fragment(sh) for sh in range(n_shards)
            ]
            for fr in frags:
                fr.snapshot()
            expected = req(base, "/index/i/query",
                           b"Count(Row(f=1))")["results"][0]

            def plateau() -> float:
                errs = []

                def client():
                    for _ in range(queries_per_client):
                        got = req(base, "/index/i/query",
                                  b"Count(Row(f=1))")["results"][0]
                        if got != expected:
                            errs.append(got)

                t0 = time.perf_counter()
                ts = [threading.Thread(target=client)
                      for _ in range(n_clients)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                assert not errs, f"corrupt responses: {errs[:3]}"
                return (n_clients * queries_per_client
                        / (time.perf_counter() - t0))

            from pilosa_tpu.parallel.scrub import Scrubber

            def scrub_on() -> "Scrubber":
                sc = Scrubber(s.holder, cluster=s.api.cluster,
                              interval_s=0.2, max_bytes_per_sec=1 << 20)
                s.api.scrubber = sc
                return sc.start()

            # INTERLEAVED off/on rounds gated on the BEST per-round
            # ratio (the config_profiling philosophy: machine-load
            # drift on a shared box only ever makes the scrubbed path
            # look slower than it is); the median rides along for
            # drift visibility
            plateau()  # warm
            rounds = []
            passes = 0
            for _ in range(3):
                q_off = plateau()
                sc = scrub_on()
                q_on = plateau()
                sc.close()
                passes += sc.passes
                rounds.append((q_off, q_on))
            ratios = sorted(on / off for off, on in rounds)
            ratio = ratios[-1]
            out["serving_qps_scrub_off"] = round(
                max(off for off, _ in rounds), 1)
            out["serving_qps_scrub_on"] = round(
                max(on for _, on in rounds), 1)
            out["overhead_ratio"] = round(ratio, 4)
            out["overhead_ratio_median"] = round(
                ratios[len(ratios) // 2], 4)
            out["scrub_passes_during_plateau"] = passes

            # detection latency: flip a byte; the ticking scrubber must
            # quarantine + self-heal it (single node: live bitmap is
            # the healthy copy)
            scrubber = scrub_on()
            flip_byte(frags[0].path)
            t0 = time.perf_counter()
            detect_s = None
            while time.perf_counter() - t0 < detection_bound_s + 5:
                if scrubber.corruptions >= 1 and (
                        scrubber.self_healed + scrubber.repaired) >= 1:
                    detect_s = time.perf_counter() - t0
                    break
                time.sleep(0.02)
            scrubber.close()
            out["detection_s"] = (round(detect_s, 3)
                                  if detect_s is not None else None)
            post_heal = req(base, "/index/i/query",
                            b"Count(Row(f=1))")["results"][0]
            out["detection_ok"] = (detect_s is not None
                                   and detect_s <= detection_bound_s
                                   and post_heal == expected)
        finally:
            s.close()

    # ---- phase 3: heal + ENOSPC oracle, 2 nodes -----------------------
    with tempfile.TemporaryDirectory() as tmp:
        from pilosa_tpu.storage.integrity import StorageHealth
        from pilosa_tpu.testing import faults

        a = boot(f"{tmp}/a", "a", replica_n=2)
        b = boot(f"{tmp}/b", "b", seeds=[base_of(a)], replica_n=2)
        b.holder.health.PROBE_INTERVAL_S = 0.2
        heal = {"corrupt_responses": 0, "reads": 0}
        try:
            for srv in (a, b):
                srv.api.cluster.wait_until_normal(30)
            req(base_of(a), "/index/i", b"{}")
            req(base_of(a), "/index/i/field/f", b"{}")
            acked = []
            for col in range(0, 600, 7):
                ok = req(base_of(a), "/index/i/query",
                         f"Set({col}, f=2)".encode())["results"] == [True]
                if ok:
                    acked.append(col)
            frag_a = (a.holder.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0))
            frag_b = (b.holder.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0))
            frag_a.snapshot()
            frag_b.snapshot()
            truth = len(acked)
            stop = threading.Event()

            def reader():
                while not stop.is_set():
                    try:
                        got = req(base_of(b), "/index/i/query",
                                  b"Count(Row(f=2))")["results"][0]
                    except Exception:  # noqa: BLE001
                        continue
                    heal["reads"] += 1
                    if got != truth:
                        heal["corrupt_responses"] += 1

            rt = threading.Thread(target=reader, daemon=True)
            rt.start()
            flip_byte(frag_b.path, offset=96, mask=0x04)
            rec = b.api.scrub_now()
            stop.set()
            rt.join(5)
            healed = (b.holder.index("i").field("f").view(VIEW_STANDARD)
                      .fragment(0))
            byte_identical = (
                healed is not None
                and healed.serialize_snapshot()
                == frag_a.serialize_snapshot()
            )
            got_cols = set(req(base_of(b), "/index/i/query",
                               b"Row(f=2)")["results"][0]["columns"])
            lost = [c for c in acked if c not in got_cols]
            out["heal_scrub_record"] = {
                k: rec[k] for k in ("corrupt", "repaired", "unrepaired")}
            out["heal_reads_during_window"] = heal["reads"]
            out["heal_corrupt_responses"] = heal["corrupt_responses"]
            out["heal_byte_identical"] = byte_identical
            out["heal_lost_acked_writes"] = len(lost)
            out["heal_ok"] = (rec["corrupt"] == 1 and rec["repaired"] == 1
                              and byte_identical and not lost
                              and heal["corrupt_responses"] == 0)

            # ENOSPC on node b: writes shed, status flips, auto-recovers
            import errno as _errno

            plane = faults.install_disk()
            rule = plane.add("fsync", path=f"{tmp}/b/",
                             errno_=_errno.ENOSPC)
            shed = None
            try:
                req(base_of(b), "/index/i/query", b"Set(9001, f=2)")
            except urllib.error.HTTPError as e:
                shed = e.code
            degraded = req(base_of(b), "/status")["storageDegraded"]
            # a SECOND write must shed 503 via the QoS path
            shed2 = None
            try:
                req(base_of(b), "/index/i/query", b"Set(9002, f=2)")
            except urllib.error.HTTPError as e:
                shed2 = e.code
            plane.remove(rule.id)
            t0 = time.perf_counter()
            recovered = False
            while time.perf_counter() - t0 < 10:
                if not req(base_of(b), "/status")["storageDegraded"]:
                    recovered = True
                    break
                time.sleep(0.1)
            write_after = req(base_of(b), "/index/i/query",
                              b"Set(9003, f=2)")["results"] == [True]
            out["enospc_first_status"] = shed
            out["enospc_shed_status"] = shed2
            out["enospc_degraded_on_status"] = degraded
            out["enospc_recovered"] = recovered
            out["enospc_write_after_heal"] = write_after
            out["enospc_ok"] = (degraded and shed2 == 503 and recovered
                                and write_after)
        finally:
            faults.clear_disk()
            a.close()
            b.close()

    # ---- phase 4: randomized storage-fault chaos schedules ------------
    from pilosa_tpu.testing.chaos import run_chaos

    with tempfile.TemporaryDirectory() as tmp:
        chaos = run_chaos(tmp, n_schedules=n_chaos_schedules, n_nodes=3,
                          replica_n=2, n_events=6, seed=7,
                          with_storage_faults=True)
    out["chaos_schedules"] = chaos["schedules"]
    out["chaos_corruptions_injected"] = chaos["corruptions_injected"]
    out["chaos_disk_integrity_failures"] = chaos["disk_integrity_failures"]
    out["chaos_lost_acked_writes"] = chaos["lost_acked_writes"]
    out["chaos_degraded_stuck"] = chaos["degraded_stuck"]
    out["chaos_failed_seeds"] = chaos["failed_seeds"]
    out["chaos_ok"] = bool(chaos["ok"] and chaos["unconverged"] == 0)

    out["wall_s"] = round(time.time() - t_start, 1)
    out["ok"] = bool(
        out["overhead_ratio"] >= overhead_floor
        and out["scrub_passes_during_plateau"] >= 1
        and out["detection_ok"] and out["heal_ok"] and out["enospc_ok"]
        and out["chaos_ok"]
    )
    return out


# Stand-alone client driver for config_mp_serving: client-side load
# must come from PROCESSES (a threaded driver is itself GIL-bound and
# would mask the very scaling the config measures). Each proc holds one
# keep-alive connection, waits for a "run <port> <n> <start_at>" line,
# fires n requests from the shared deterministic query schedule, and
# reports wall time + a response digest (the byte-identical oracle).
_MP_CLIENT_SRC = r"""
import hashlib, http.client, json, sys, time
QUERIES = ["Count(Row(f=%d))" % (1 + k) for k in range(4)]
for line in sys.stdin:
    parts = line.split()
    if parts[0] == "exit":
        break
    port, n, start_at = int(parts[1]), int(parts[2]), float(parts[3])
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    h = hashlib.sha256()
    errors = 0
    while time.time() < start_at:
        time.sleep(0.001)
    t0 = time.perf_counter()
    for k in range(n):
        try:
            conn.request("POST", "/index/b/query",
                         body=QUERIES[k % len(QUERIES)].encode())
            h.update(conn.getresponse().read())
        except Exception:
            errors += 1
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
    wall = time.perf_counter() - t0
    conn.close()
    print(json.dumps({"wall": wall, "digest": h.hexdigest(),
                      "errors": errors}), flush=True)
"""


def config_multitenant(n_indexes: int = 120, n_clients: int = 8,
                       requests_per_client: int = 300,
                       baseline_requests: int = 800,
                       rounds: int = 3, zipf_s: float = 1.1,
                       hot_ranks: int = 5, cold_rank_floor: int = 30,
                       ryw_rounds: int = 40) -> dict:
    """Skewed-traffic gate (ISSUE 12 / ROADMAP open item 3): 100+
    indexes on ONE node under Zipf client traffic with QoS quotas
    active, the write-invalidated result cache and heat-driven
    residency tiering both ON.

    Gates (``ok``):

    - hot-tenant p99 within 1.3x the single-index plateau p99 on the
      same server (the Zipf head must serve at cache speed, however
      many cold tenants share the node);
    - cold-tenant p99 bounded (≤ max(50x the single-index p99, 0.75 s)
      — re-decode + fill cost, never an unbounded tail);
    - result-cache hit rate > 50% on the Zipf hot set (per-tenant
      ledger result_cache_hits / queries over the head ranks);
    - read-your-writes oracle: an acked (fsynced, group-commit) write
      is NEVER masked by a stale cached result — write-then-read
      through the cache path, single-process AND through different
      mp-serving workers' rings (the cache lives owner-side);
    - tiering acts: ≥1 heat-driven demotion to the compressed host
      tier and ≥1 promotion back, with ZERO serving errors during the
      transitions (old-resident or new-resident, never absent);
    - zero client errors anywhere.
    """
    import http.client as _hc
    import socket as _socket
    import threading
    import urllib.request

    from pilosa_tpu.serving.rescache import global_result_cache
    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.residency import global_row_cache
    from pilosa_tpu.storage.view import VIEW_STANDARD

    t_start = time.time()
    rng = np.random.default_rng(12)
    names = [f"t{i:03d}" for i in range(n_indexes)]
    # seeded rank permutation: which tenant is rank-0 hot is arbitrary
    perm = rng.permutation(n_indexes)
    rank_of = {names[perm[r]]: r for r in range(n_indexes)}
    by_rank = [names[perm[r]] for r in range(n_indexes)]
    # Zipf pmf over ranks
    weights = 1.0 / np.arange(1, n_indexes + 1) ** zipf_s
    pmf = weights / weights.sum()

    def seed_server(tmp: str, **extra) -> "Server":
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name="mt", anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
            result_cache_bytes=64 << 20,
            residency_promote_interval=0.2,
            residency_promote_heat=2.0, residency_demote_heat=0.5,
            heat_half_life=1.5,
            qos_max_inflight=512, qos_tenant_inflight=64,  # quotas ON
            **extra,
        )).open()
        n = int(SHARD_WIDTH * 0.01)
        for name in names:
            idx = server.holder.create_index(name,
                                             track_existence=False)
            f = idx.create_field("f")
            frag = f.view(VIEW_STANDARD, create=True).fragment(
                0, create=True)
            for row in range(1, 5):
                frag.bulk_import(
                    np.full(n, row, np.uint64),
                    rng.choice(SHARD_WIDTH, n, replace=False).astype(
                        np.uint64),
                )
            server.api.cluster.note_local_shards(name, [0])
        return server

    def post(conn, index, pql, tenant=None, suffix=""):
        headers = {"X-Pilosa-Tenant": tenant} if tenant else {}
        conn.request("POST", f"/index/{index}/query{suffix}",
                     body=pql.encode(), headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()

    errors: list = []

    def client_run(port, plan):
        """One closed-loop client: ``plan`` is [(index, pql)];
        returns per-request latencies (seconds) aligned with plan."""
        conn = _hc.HTTPConnection("127.0.0.1", port, timeout=120)
        lat = np.zeros(len(plan))
        try:
            for k, (index, pql) in enumerate(plan):
                t0 = time.perf_counter()
                st, body = post(conn, index, pql, tenant=index)
                lat[k] = time.perf_counter() - t0
                if st != 200:
                    errors.append((index, st, body[:120]))
        except Exception as e:  # noqa: BLE001 — surfaced via errors
            errors.append(repr(e))
        finally:
            conn.close()
        return lat

    def run_phase(port, plans):
        gate = threading.Event()
        out = [None] * len(plans)

        def worker(i):
            gate.wait(30)
            out[i] = client_run(port, plans[i])

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(plans))]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(600)
        return out

    result: dict = {"config": "multitenant",
                    "metric": "zipf_multitenant_cache_tiering",
                    "n_indexes": n_indexes, "n_clients": n_clients,
                    "zipf_s": zipf_s}
    with tempfile.TemporaryDirectory() as tmp:
        server = seed_server(f"{tmp}/s1")
        try:
            port = server.port
            hot0 = by_rank[0]
            # warm compile caches + the baseline index's cache entries
            warm_conn = _hc.HTTPConnection("127.0.0.1", port, timeout=120)
            for row in range(1, 5):
                post(warm_conn, hot0, f"Count(Row(f={row}))", tenant=hot0)
            warm_conn.close()

            # ---- phase 1: single-index plateau (the comparison bar)
            per = baseline_requests // n_clients
            base_p99 = base_p50 = None
            for _ in range(rounds):
                plans = [[(hot0,
                           f"Count(Row(f={1 + (k % 4)}))")
                          for k in range(per)]
                         for _ in range(n_clients)]
                lat = np.concatenate(run_phase(port, plans))
                p99 = float(np.percentile(lat, 99))
                if base_p99 is None or p99 < base_p99:
                    base_p99 = p99
                    base_p50 = float(np.percentile(lat, 50))

            # ---- phase 2: Zipf traffic across every tenant
            hits0 = global_result_cache().metrics()
            hot_lat_best = cold_lat_best = None
            for r in range(rounds):
                plans = []
                for c in range(n_clients):
                    crng = np.random.default_rng(1000 + r * 64 + c)
                    ranks = crng.choice(n_indexes, requests_per_client,
                                        p=pmf)
                    plans.append([
                        (by_rank[rank],
                         f"Count(Row(f={1 + (k % 4)}))")
                        for k, rank in enumerate(ranks)])
                outs = run_phase(port, plans)
                hot_lat, cold_lat = [], []
                for plan, lat in zip(plans, outs):
                    for (index, _), s in zip(plan, lat):
                        rank = rank_of[index]
                        if rank < hot_ranks:
                            hot_lat.append(s)
                        elif rank >= cold_rank_floor:
                            cold_lat.append(s)
                hp99 = float(np.percentile(hot_lat, 99))
                if hot_lat_best is None or hp99 < hot_lat_best:
                    hot_lat_best = hp99
                if cold_lat:
                    cp99 = float(np.percentile(cold_lat, 99))
                    if cold_lat_best is None or cp99 < cold_lat_best:
                        cold_lat_best = cp99
            hits1 = global_result_cache().metrics()
            # hot-set hit rate from the per-tenant ledger (cache hits
            # are billed as queries — the satellite contract)
            ledger = {r["tenant"]: r
                      for r in server.api.cost.snapshot()}
            hot_queries = sum(
                ledger.get(by_rank[r], {}).get("queries", 0)
                for r in range(hot_ranks))
            hot_hits = sum(
                ledger.get(by_rank[r], {}).get("result_cache_hits", 0)
                for r in range(hot_ranks))
            hot_hit_rate = hot_hits / hot_queries if hot_queries else 0.0

            # ---- phase 3: read-your-writes through the cache path
            ryw_ok = True
            ryw_conn = _hc.HTTPConnection("127.0.0.1", port, timeout=120)
            counts: dict = {}
            for k in range(ryw_rounds):
                name = by_rank[int(rng.integers(0, 20))]
                # prime the cached read, then write, then re-read: the
                # acked (fsynced) write must never be masked
                post(ryw_conn, name, "Count(Row(f=9))", tenant=name)
                st, _ = post(ryw_conn, name,
                             f"Set({2000 + k}, f=9)", tenant=name)
                if st != 200:
                    errors.append(("ryw-write", st))
                counts[name] = counts.get(name, 0) + 1
                st, body = post(ryw_conn, name, "Count(Row(f=9))",
                                tenant=name)
                got = json.loads(body)["results"][0]
                if got != counts[name]:
                    ryw_ok = False
                    errors.append(
                        ("ryw-stale", name, got, counts[name]))
            ryw_conn.close()

            # ---- phase 4: heat-driven tier cycle (demote + promote)
            cache = global_row_cache()
            tier_conn = _hc.HTTPConnection("127.0.0.1", port,
                                           timeout=120)
            # everything cools below demote-heat (half-life 1.5 s);
            # the 0.2 s tiering worker demotes resident leaves host-side
            deadline = time.time() + 12.0
            while (cache.tier_demotions == 0
                   and time.time() < deadline):
                time.sleep(0.25)
            demotions = int(cache.tier_demotions)
            # re-heat a handful of demoted tenants with explicit-shard
            # queries (cache-ineligible, so they EXECUTE and record
            # heat); lookups promote the leaves they touch, the worker
            # pass promotes the rest of each field
            tier_errors = 0
            for name in by_rank[:3]:
                for k in range(12):
                    st, _ = post(tier_conn, name,
                                 f"Count(Row(f={1 + (k % 4)}))",
                                 tenant=name, suffix="?shards=0")
                    if st != 200:
                        tier_errors += 1
            deadline = time.time() + 8.0
            while (cache.tier_promotions == 0
                   and time.time() < deadline):
                time.sleep(0.25)
            promotions = int(cache.tier_promotions)
            tier_metrics = server.api.tierer.metrics()
            host_bytes_peak = int(cache.host_bytes)
            tier_conn.close()
        finally:
            server.close()

        # ---- phase 5: the mp-serving shape (cache owner-side)
        if hasattr(_socket, "SO_REUSEPORT"):
            mp_ok = True
            mp = Server(ServerConfig(
                data_dir=f"{tmp}/mp", port=0, serving_workers=2,
                anti_entropy_interval=0, heartbeat_interval=0,
                use_mesh=False, result_cache_bytes=16 << 20,
            )).open()
            try:
                mport = mp.port

                def mp_req(method, path, body=None):
                    r = urllib.request.Request(
                        f"http://127.0.0.1:{mport}{path}", data=body,
                        method=method)
                    with urllib.request.urlopen(r, timeout=60) as resp:
                        return resp.status, resp.read()

                mp_req("POST", "/index/m", b"{}")
                mp_req("POST", "/index/m/field/f", b"{}")
                for k in range(15):
                    # fresh connection per request: the kernel spreads
                    # them across the SO_REUSEPORT workers, so the
                    # write and the read ride DIFFERENT rings
                    st, _ = mp_req("POST", "/index/m/query",
                                   f"Set({k}, f=3)".encode())
                    if st != 200:
                        mp_ok = False
                    st, body = mp_req("POST", "/index/m/query",
                                      b"Count(Row(f=3))")
                    if json.loads(body)["results"][0] != k + 1:
                        mp_ok = False
                        errors.append(("mp-ryw-stale", k))
            except Exception as e:  # noqa: BLE001
                mp_ok = False
                errors.append(repr(e))
            finally:
                mp.close()
        else:
            mp_ok = True
            result["mp_skipped"] = "SO_REUSEPORT unavailable"

        # ---- phase 6: cluster-edge caching under live CDC (ISSUE 16)
        # Two nodes, replica_n=1: node0's Count spans shards node1
        # owns — the exact shape the write-invalidated cache REFUSED
        # to cache single-node ("cluster-no-cdc" refusal), because a
        # remote write could not reach the local invalidation hook.
        # With cdc-enabled tailers live the edge entry caches (gate:
        # >50% hit rate on repeat reads) and a write through the PEER
        # is never masked past the tail-poll staleness (bounded
        # read-your-writes: the re-read converges within a deadline).
        ce_errors: list = []
        ce_ryw_ok = True
        ce_hit_rate = 0.0
        ce_prop_ms: list = []
        ce_lag: dict = {}
        ce_reads = 40
        ce_kw = dict(replica_n=1, anti_entropy_interval=0,
                     heartbeat_interval=0, use_mesh=False,
                     result_cache_bytes=32 << 20,
                     cdc_enabled=True, cdc_poll_interval=0.02)
        ce0 = Server(ServerConfig(
            data_dir=f"{tmp}/ce0", port=0, name="ce0", **ce_kw)).open()
        ce1 = Server(ServerConfig(
            data_dir=f"{tmp}/ce1", port=0, name="ce1",
            seeds=[f"http://localhost:{ce0.port}"], **ce_kw)).open()
        try:
            for s in (ce0, ce1):
                s.api.cluster.wait_until_normal(30)

            def ce_req(port, path, body=None):
                r = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}", data=body,
                    method="POST")
                with urllib.request.urlopen(r, timeout=60) as resp:
                    return resp.status, resp.read()

            ce_req(ce0.port, "/index/ce", b"{}")
            ce_req(ce0.port, "/index/ce/field/f", b"{}")
            expect = 4
            for s_ in range(expect):
                ce_req(ce0.port, "/index/ce/query",
                       f"Set({s_ * SHARD_WIDTH + 5}, f=1)".encode())
            deadline = time.time() + 15
            while time.time() < deadline and not all(
                    s.api.cdc is not None and s.api.cdc.live()
                    for s in (ce0, ce1)):
                time.sleep(0.05)
            m0 = global_result_cache().metrics()
            for _ in range(ce_reads):
                st, body = ce_req(ce0.port, "/index/ce/query",
                                  b"Count(Row(f=1))")
                if st != 200 or json.loads(body)["results"] != [expect]:
                    ce_errors.append(("ce-read", st, body[:120]))
            m1 = global_result_cache().metrics()
            hits = (m1["result_cache_hits_total"]
                    - m0["result_cache_hits_total"])
            ce_hit_rate = hits / ce_reads
            for k in range(8):
                ce_req(ce1.port, "/index/ce/query",
                       f"Set({(expect + k) * SHARD_WIDTH + 5}, "
                       f"f=1)".encode())
                t0p = time.perf_counter()
                dl = time.time() + 5.0
                seen = None
                while time.time() < dl:
                    _, body = ce_req(ce0.port, "/index/ce/query",
                                     b"Count(Row(f=1))")
                    seen = json.loads(body)["results"][0]
                    if seen == expect + k + 1:
                        break
                    time.sleep(0.01)
                else:
                    ce_ryw_ok = False
                    ce_errors.append(("ce-ryw-stale",
                                      expect + k + 1, seen))
                ce_prop_ms.append(
                    (time.perf_counter() - t0p) * 1e3)
            ce_lag = ce0.api.cdc.peer_lag() if ce0.api.cdc else {}
        except Exception as e:  # noqa: BLE001 — surfaced via gate
            ce_ryw_ok = False
            ce_errors.append(repr(e))
        finally:
            ce1.close()
            ce0.close()

    cold_bound = max(50 * base_p99, 0.75)
    result.update({
        "requests_zipf": n_clients * requests_per_client * rounds,
        "single_index_p50_ms": round(base_p50 * 1e3, 3),
        "single_index_p99_ms": round(base_p99 * 1e3, 3),
        "hot_tenant_p99_ms": round(hot_lat_best * 1e3, 3),
        "hot_vs_single_ratio": round(hot_lat_best / base_p99, 3),
        "cold_tenant_p99_ms": round((cold_lat_best or 0.0) * 1e3, 3),
        "cold_bound_ms": round(cold_bound * 1e3, 1),
        "hot_hit_rate": round(hot_hit_rate, 4),
        "result_cache": {
            k: hits1[k] - hits0.get(k, 0)
            for k in ("result_cache_hits_total",
                      "result_cache_misses_total",
                      "result_cache_fills_total",
                      "result_cache_invalidations_total")},
        "tier_demotions": demotions,
        "tier_promotions": promotions,
        "tier_pass_metrics": tier_metrics,
        "host_tier_bytes": host_bytes_peak,
        "tier_transition_errors": tier_errors,
        "read_your_writes_ok": ryw_ok,
        "read_your_writes_mp_ok": mp_ok,
        "cluster_edge": {
            "hit_rate": round(ce_hit_rate, 4),
            "read_your_writes_ok": ce_ryw_ok,
            "invalidation_p50_ms": round(
                float(np.percentile(ce_prop_ms, 50)), 2
            ) if ce_prop_ms else None,
            "peer_lag": ce_lag,
            "errors": len(ce_errors),
            "error_sample": [str(e)[:160] for e in ce_errors[:3]],
        },
        "client_errors": len(errors),
        "error_sample": [str(e)[:160] for e in errors[:5]],
        "wall_s": round(time.time() - t_start, 1),
    })
    result["ok"] = bool(
        hot_lat_best <= 1.3 * base_p99
        and (cold_lat_best or 0.0) <= cold_bound
        and hot_hit_rate > 0.5
        and ryw_ok and mp_ok
        and ce_hit_rate > 0.5 and ce_ryw_ok and not ce_errors
        and demotions >= 1 and promotions >= 1
        and tier_errors == 0 and not errors
    )
    return result


def config_mp_serving(n_shards: int = 4,
                      worker_counts=(1, 2, 4),
                      client_counts=(8, 32, 96),
                      requests_per_client: int = 80,
                      rounds: int = 3) -> dict:
    """Multi-process serving tier scaling gate (ISSUE 11 / ROADMAP open
    item 1): the SAME hot read mix against the SAME seeded data in two
    deployment shapes — classic single-process, and N ``SO_REUSEPORT``
    workers fronting one device owner over shared-memory rings
    (serving/mpserve.py). Clients are subprocesses (process-level
    parallelism on both sides of the wire); runs are best-of-``rounds``
    INTERLEAVED across shapes so drift hits every curve equally.

    The headline is plateau-vs-plateau: max QPS over the client sweep
    per worker count, plus the worker-reported ring round-trip
    quantiles. ``ok`` requires byte-identical responses across every
    shape and run (digest oracle vs a serial pass), one kill-a-worker
    chaos schedule passing both mp oracles (zero lost acked writes,
    owner never wedges), and a core-aware scaling bar (ISSUE 18):
    4-worker plateau ≥ 4× the single-process fast-lane plateau when
    the box has ≥ 6 cores (workers + owner + clients each get a real
    core), ≥ 2× on 3-5 cores, and on fewer the box is recorded as
    hardware-saturated — the result carries ``cores`` and the measured
    ``saturation`` point and only the correctness oracles gate."""
    import http.client as _hc
    import socket as _socket
    import subprocess
    import sys as _sys

    from pilosa_tpu.server import Server, ServerConfig
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage.view import VIEW_STANDARD

    if not hasattr(_socket, "SO_REUSEPORT"):
        return {"config": "mp_serving", "ok": False,
                "error": "SO_REUSEPORT unavailable on this platform"}

    def boot(tmp: str, workers: int):
        server = Server(ServerConfig(
            data_dir=tmp, port=0, name=f"mp{workers}",
            serving_workers=workers, anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
        )).open()
        rng = np.random.default_rng(7)  # same seed: identical data
        idx = server.holder.create_index("b")
        f = idx.create_field("f")
        n = int(SHARD_WIDTH * 0.1)
        for shard in range(n_shards):
            frag = f.view(VIEW_STANDARD, create=True).fragment(
                shard, create=True)
            for row in range(1, 5):
                frag.bulk_import(
                    np.full(n, row, np.uint64),
                    rng.choice(SHARD_WIDTH, n, replace=False).astype(
                        np.uint64),
                )
        server.api.cluster.note_local_shards("b", list(range(n_shards)))
        return server

    t0 = time.time()
    max_clients = max(client_counts)
    clients = [
        subprocess.Popen([_sys.executable, "-c", _MP_CLIENT_SRC],
                         stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                         text=True)
        for _ in range(max_clients)
    ]

    def run_once(port: int, n_clients: int):
        start_at = time.time() + 0.25
        for p in clients[:n_clients]:
            p.stdin.write(f"run {port} {requests_per_client} "
                          f"{start_at}\n")
            p.stdin.flush()
        outs = []
        for p in clients[:n_clients]:
            line = p.stdout.readline()
            if not line:
                raise RuntimeError(
                    "mp_serving client subprocess died mid-run "
                    f"(exit {p.poll()})")
            outs.append(json.loads(line))
        wall = max(o["wall"] for o in outs)
        errors = sum(o["errors"] for o in outs)
        digests = {o["digest"] for o in outs}
        return (n_clients * requests_per_client) / wall, errors, digests

    servers = {}
    try:
        with tempfile.TemporaryDirectory() as tmp:
            servers[0] = boot(f"{tmp}/w0", 0)
            for w in worker_counts:
                servers[w] = boot(f"{tmp}/w{w}", w)
            # serial ground-truth digest on the single-process shape
            import hashlib as _hashlib

            conn = _hc.HTTPConnection("127.0.0.1", servers[0].port,
                                      timeout=60)
            h = _hashlib.sha256()
            for k in range(requests_per_client):
                conn.request("POST", "/index/b/query",
                             body=f"Count(Row(f={1 + k % 4}))".encode())
                h.update(conn.getresponse().read())
            conn.close()
            want_digest = h.hexdigest()
            # warm every shape (compile caches, worker pools)
            for s in servers.values():
                run_once(s.port, max_clients)
            best: dict = {w: {} for w in servers}
            errors_total = 0
            identical = True
            for _ in range(rounds):          # interleaved best-of-N
                for w, s in servers.items():
                    for n_clients in client_counts:
                        qps, errs, digests = run_once(s.port, n_clients)
                        errors_total += errs
                        identical = identical and digests == {want_digest}
                        best[w][n_clients] = max(
                            best[w].get(n_clients, 0.0), qps)
            curve = [
                {"workers": w, "clients": c, "qps": round(q, 1)}
                for w in sorted(best) for c, q in sorted(best[w].items())
            ]
            plateaus = {w: round(max(best[w].values()), 1)
                        for w in sorted(best)}
            # ring round-trip quantiles, as the workers measured them
            rtt = {"p50_us": 0, "p99_us": 0}
            mp = servers[max(worker_counts)]._mpserve
            rows = [r for r in mp.workers_json() if r.get("ringRttP50Us")]
            if rows:
                rtt = {
                    "p50_us": round(sum(r["ringRttP50Us"]
                                        for r in rows) / len(rows)),
                    "p99_us": max(r["ringRttP99Us"] for r in rows),
                }
            for s in servers.values():
                s.close()
            servers = {}
            # the kill-a-worker chaos schedule rides the same gate
            from pilosa_tpu.testing.chaos import run_mp_chaos

            chaos = run_mp_chaos(f"{tmp}/chaos", n_schedules=1,
                                 n_workers=2, n_kills=3)
    finally:
        for s in servers.values():
            s.close()
        for p in clients:
            try:
                p.stdin.write("exit\n")
                p.stdin.flush()
            except OSError:
                pass
        for p in clients:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    speedup = (plateaus[max(worker_counts)] / plateaus[0]
               if plateaus[0] else 0.0)
    # core-aware scaling gate (ISSUE 18): N workers + 1 owner + client
    # subprocesses need real cores to show scaling. On >=6 cores the
    # ROADMAP >=4x target is enforced; on 3-5 cores the shapes
    # time-share and >=2x is the honest bar; below that the box is
    # hardware-saturated — scaling is not measurable, so only the
    # correctness oracles (byte-identity, zero client errors, chaos)
    # gate, and the saturation point is recorded instead.
    cores = os.cpu_count() or 1
    best_plateau = max(plateaus.values()) if plateaus else 0.0
    saturation_workers = next(
        (w for w in sorted(plateaus)
         if plateaus[w] >= 0.95 * best_plateau), max(worker_counts))
    if cores >= 6:
        scaling_ok, scaling_gate = speedup >= 4.0, "speedup >= 4.0"
    elif cores >= 3:
        scaling_ok, scaling_gate = speedup >= 2.0, "speedup >= 2.0"
    else:
        scaling_ok = True
        scaling_gate = ("ungated: hardware-saturated (< 3 cores); "
                        "correctness + chaos oracles still gate")
    return {
        "config": "mp_serving",
        "metric": "mp_serving_plateau_scaling",
        "n_shards": n_shards,
        "requests_per_point": requests_per_client * max(client_counts),
        "curve": curve,
        "plateau_qps_by_workers": plateaus,
        "speedup_max_workers": round(speedup, 2),
        "cores": cores,
        "scaling_gate": scaling_gate,
        "saturation": {
            "plateau_workers": saturation_workers,
            "note": ("smallest worker count within 5% of the best "
                     "plateau on this box"),
        },
        "ring_rtt": rtt,
        "client_errors": errors_total,
        "bytes_identical": identical,
        "kill_worker_chaos": chaos,
        "wall_s": round(time.time() - t0, 1),
        "ok": bool(identical and errors_total == 0 and scaling_ok
                   and chaos["ok"]),
    }


def config_chaos(n_schedules: int = 20, n_nodes: int = 3,
                 replica_n: int = 2, n_events: int = 6,
                 seed: int = 0) -> dict:
    """Partition-tolerance chaos gate (ISSUE 9 — docs/OPERATIONS.md
    failure model): ``n_schedules`` independent seeded schedules of
    randomized partition (symmetric + asymmetric) / heal / kill /
    restart events against a real ``n_nodes``-node in-process cluster
    under a mixed read+write workload, each gated on the four oracles:

    1. zero lost acked writes (every 200-acked Set queryable after heal),
    2. no fragment deleted by a non-quorum node (cleanup decision log),
    3. at most one coordinator acting per epoch (acted-epoch records),
    4. byte-identical replicas after heal (the PR-4 sync oracle).

    ``ok`` requires every schedule to pass every oracle AND converge
    (membership reunified, all NORMAL, nobody degraded). A failing
    schedule's seed is reported so the run replays deterministically
    (testing/chaos.py).

    The default config also runs the ISSUE-11 kill-a-worker schedules
    (multi-process serving tier: SIGKILL workers mid-burst) gated on
    zero lost acked writes + the owner-never-wedges oracle; skipped
    (and not counted against ``ok``) only where SO_REUSEPORT is
    unavailable.

    ISSUE 17 adds mid-drain schedules: a second ``run_chaos`` batch
    with ``with_elastic=True`` puts graceful-drain events in the same
    bag as kills and partitions, so faults land while a drain is in
    flight — gated on the same oracles."""
    import socket as _socket

    from pilosa_tpu.testing.chaos import run_chaos, run_mp_chaos

    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        out = run_chaos(
            tmp, n_schedules=n_schedules, n_nodes=n_nodes,
            replica_n=replica_n, n_events=n_events, seed=seed,
        )
        drain = run_chaos(
            tmp + "/drain", n_schedules=max(2, n_schedules // 5),
            n_nodes=max(n_nodes, 4), replica_n=replica_n,
            n_events=n_events, seed=seed + 7, with_elastic=True,
        )
        if hasattr(_socket, "SO_REUSEPORT"):
            mp = run_mp_chaos(tmp + "/mp", n_schedules=2, n_workers=2,
                              n_kills=3, seed=seed)
        else:
            mp = {"skipped": "SO_REUSEPORT unavailable", "ok": True}
    return {
        "kill_worker": mp,
        "mid_drain": {
            "schedules": drain["schedules"],
            "drains_total": drain["drains_total"],
            "lost_acked_writes": drain["lost_acked_writes"],
            "replica_mismatches": drain["replica_mismatches"],
            "unconverged": drain["unconverged"],
            "failed_seeds": drain["failed_seeds"],
            "failed_diags": drain["failed_diags"],
            "ok": bool(drain["ok"] and drain["unconverged"] == 0),
        },
        "config": "chaos",
        "metric": "partition_chaos_oracles",
        "schedules": out["schedules"],
        "n_nodes": out["n_nodes"],
        "replica_n": out["replica_n"],
        "events_total": out["events_total"],
        "acked_writes_total": out["acked_writes_total"],
        "lost_acked_writes": out["lost_acked_writes"],
        "non_quorum_deletions": out["non_quorum_deletions"],
        "coordinator_conflicts": out["coordinator_conflicts"],
        "replica_mismatches": out["replica_mismatches"],
        "unconverged": out["unconverged"],
        "failed_seeds": out["failed_seeds"],
        "failed_diags": out["failed_diags"],
        "wall_s": round(time.time() - t0, 1),
        "ok": bool(out["ok"] and out["unconverged"] == 0
                   and mp.get("ok")
                   and drain["ok"] and drain["unconverged"] == 0),
    }


def config_autopilot(n_hot: int = 12, n_clients: int = 12,
                     inflight_cap: int = 5, hot_run_s: float = 24.0,
                     base_run_s: float = 8.0, n_chaos_schedules: int = 3,
                     seed: int = 0) -> dict:
    """Autopilot placement gate (ISSUE 15): a 3-process cluster under
    hot-spotted Zipf traffic must recover its p99 automatically.

    The hot spot is REAL, not simulated: ``n_hot`` single-shard indexes
    are chosen (by walking candidate names through the same blake2b
    ring the cluster uses) so that hash placement puts every one of
    them on ONE node, and closed-loop clients drive a Zipf-weighted
    query mix at them, owner-routed the way a shard-aware client
    routes. Under ``qos-max-inflight`` admission the overloaded owner
    sheds the excess with 429 + Retry-After and clients retry after
    backoff — so the measured (retry-inclusive) p99 is exactly the
    client-visible cost of the skew. This makes the gate meaningful on
    a 1-core CI box too: sheds are near-free for the server, so the
    hot node's p99 is backpressure wait, which the autopilot removes
    by SPREADING admission capacity, not by needing N cores to race.

    Three measured placements on identical data and workload shape:

    - ``uniform``: owners round-robin all nodes (control cluster,
      autopilot off) — the baseline the gate compares against;
    - ``hot unmanaged``: every hot index on one owner, autopilot OFF —
      the injury persists (reported, not gated: it must be > baseline
      for the run to mean anything);
    - ``hot autopiloted``: same skew with the planner ON — the first
      windows show the injury, the tail windows must show recovery.

    Gate (``ok``): tail-window p99 ≤ 1.5× the uniform p99 AND zero
    client errors (a 429 retried to success is backpressure, not an
    error; anything else — 5xx, transport failure, retry exhaustion —
    fails the gate) AND zero lost acked writes (a ledgered Set that
    rode through the autopilot's resizes must stay queryable) AND the
    planner actually acted (≥1 executed move, live overrides) AND the
    kill-switch control cluster stayed byte-identical to hash
    placement (epoch 0, no overrides, every probe write's heat row
    lands on the ring-computed owner and nowhere else)."""
    import bisect as _bisect
    import http.client as _hc
    import os
    import random as _random
    import socket
    import subprocess
    import sys
    import threading
    import urllib.request

    from pilosa_tpu.parallel.cluster import PARTITION_N, _hash64

    NAMES = ("ap0", "ap1", "ap2")
    ZIPF_S = 1.1
    RETRY_CAP = 400  # per-request attempt bound before it counts as an error

    def _ring_owner(index: str, shard: int = 0) -> str:
        # replica-n=1 rendition of Cluster.shard_nodes' hash walk; the
        # control cluster's byte-identity check holds this replica and
        # the server's walk to the same answer through real traffic
        ring = sorted(NAMES, key=lambda n: (_hash64(n), n))
        part = _hash64(f"{index}:{shard}") % PARTITION_N
        return ring[part % len(ring)]

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def req(method, base, path, body=None, timeout=30):
        r = urllib.request.Request(f"{base}{path}", data=body,
                                   method=method)
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def spawn_cluster(tmp: str, autopilot_on: bool) -> dict:
        os.makedirs(tmp, exist_ok=True)
        ports = {name: free_port() for name in NAMES}
        bases = {n: f"http://127.0.0.1:{p}" for n, p in ports.items()}
        procs = {}

        def wait_status(name) -> None:
            for _ in range(240):
                if procs[name].poll() is not None:
                    raise AssertionError(f"{name} exited "
                                         f"rc={procs[name].returncode}")
                try:
                    req("GET", bases[name], "/status", timeout=5)
                    return
                except Exception:
                    time.sleep(0.25)
            raise AssertionError(f"{name} never served /status")

        for i, name in enumerate(NAMES):
            env = {
                **os.environ, "JAX_PLATFORMS": "cpu",
                "PILOSA_TPU_NAME": name,
                "PILOSA_TPU_REPLICA_N": "1",
                # anti-entropy ON: a shard move pulls the fragment
                # snapshot; writes racing the move land as stray
                # residue on the old owner, which cleanup refuses to
                # delete until a sync pass absorbs it into the new
                # owner — with the ticker off, acked bits would sit
                # unreadable in deferred strays forever
                "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "2",
                "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
                "PILOSA_TPU_USE_MESH": "false",
                "PILOSA_TPU_QOS_MAX_INFLIGHT": str(inflight_cap),
            }
            if i > 0:
                env["PILOSA_TPU_SEEDS"] = bases[NAMES[0]]
            if autopilot_on:
                env.update({
                    "PILOSA_TPU_AUTOPILOT_ENABLED": "true",
                    "PILOSA_TPU_AUTOPILOT_INTERVAL": "1s",
                    "PILOSA_TPU_AUTOPILOT_HEAT_BUDGET": "1.3",
                    "PILOSA_TPU_AUTOPILOT_MAX_MOVES": "4",
                    "PILOSA_TPU_AUTOPILOT_MIN_DWELL": "2s",
                })
            log = open(f"{tmp}/{name}.log", "wb")
            procs[name] = subprocess.Popen(
                [sys.executable, "-m", "pilosa_tpu", "server",
                 "--data-dir", f"{tmp}/{name}", "--bind", "127.0.0.1",
                 "--port", str(ports[name])],
                env=env, stdout=log, stderr=subprocess.STDOUT,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            log.close()
            # join is a single shot at startup (no retry loop), so the
            # seed must be listening before any joiner boots — spawn
            # strictly seed-first and gate on its /status
            if i == 0:
                wait_status(name)
        for name in NAMES[1:]:
            wait_status(name)
        # EVERY node must see the full membership — the seed converges
        # first (joiners announce to it directly), but a joiner that
        # missed the join relay would serve an asymmetric ring whose
        # reads route around data the other joiner holds
        deadline = time.time() + 30
        while time.time() < deadline:
            views = [{n["id"] for n in
                      req("GET", bases[name], "/status")["nodes"]}
                     for name in NAMES]
            if all(v == set(NAMES) for v in views):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"cluster never reached full membership: {views}")
        return {"procs": procs, "bases": bases}

    def terminate(cluster) -> None:
        for p in cluster["procs"].values():
            p.terminate()
        for p in cluster["procs"].values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=15)

    # ---- index pools: names bucketed by ring owner ---------------------
    buckets: dict[str, list] = {n: [] for n in NAMES}
    i = 0
    while any(len(b) < 2 * n_hot for b in buckets.values()):
        name = f"t{i:03d}"
        buckets[_ring_owner(name)].append(name)
        i += 1
    hot_node = NAMES[1]  # any bucket works; fixed for deterministic replay
    hot_set = buckets[hot_node][:n_hot]
    # uniform set: Zipf rank r owned by node r % 3, so the popularity
    # mass lands evenly — the placement the autopilot should converge to.
    # Disjoint from hot_set (the hot bucket's cursor starts past it).
    cursors = {n: (n_hot if n == hot_node else 0) for n in NAMES}
    uniform_set = []
    for r in range(n_hot):
        node = NAMES[r % len(NAMES)]
        uniform_set.append(buckets[node][cursors[node]])
        cursors[node] += 1
    weights = np.array([1.0 / (r + 1) ** ZIPF_S for r in range(n_hot)])
    cum = np.cumsum(weights / weights.sum()).tolist()

    def seed_indexes(bases, names) -> None:
        entry = bases[NAMES[0]]
        for name in names:
            req("POST", entry, f"/index/{name}", b"{}")
            req("POST", entry, f"/index/{name}/field/f", b"{}")
            for col in (1, 2, 3):
                req("POST", entry, f"/index/{name}/query",
                    f"Set({col}, f=1)".encode())

    # ---- owner-routed closed-loop load --------------------------------
    class Router:
        """Client-side shard-aware routing: ring walk + the override
        table polled from /debug/autopilot (what a topology-aware
        client library would cache)."""

        def __init__(self, bases):
            self.bases = bases
            self.overrides: dict = {}
            self.lock = threading.Lock()

        def refresh(self) -> None:
            try:
                j = req("GET", self.bases[NAMES[0]], "/debug/autopilot",
                        timeout=5)
                ov = {}
                for e in (j.get("placement") or {}).get("overrides", []):
                    ov[(e["index"], int(e["shard"]))] = list(e["nodes"])
                with self.lock:
                    self.overrides = ov
            except Exception:
                pass  # stale routing is legal; owners still fan out

        def owner(self, index: str) -> str:
            with self.lock:
                ids = self.overrides.get((index, 0))
            if ids and all(i in self.bases for i in ids):
                return ids[0]
            return _ring_owner(index)

    def run_load(bases, router, index_set, duration_s, *,
                 write_ledger=None, refresh=False):
        """``n_clients`` closed-loop Zipf query threads (+1 ledgered
        writer when ``write_ledger`` is given). Returns (samples,
        errors, retries): samples are (completed_at_s, latency_s)
        with latency INCLUDING 429-retry backoff."""
        samples: list = []
        errors: list = []
        retries = [0]
        lock = threading.Lock()
        stop = threading.Event()
        t_start = time.monotonic()

        def do_request(conns, name, path, body):
            conn = conns.get(name)
            if conn is None:
                host = bases[name].split("//")[1]
                h, _, p = host.partition(":")
                conn = conns[name] = _hc.HTTPConnection(h, int(p),
                                                        timeout=30)
            conn.request("POST", path, body=body)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data

        def drop_conn(conns, name) -> None:
            stale = conns.pop(name, None)
            if stale is not None:
                try:
                    stale.close()
                except Exception:
                    pass

        def one_op(conns, rng, index, body):
            """POST until acked; latency includes every retry. Returns
            (latency_s, None) or (None, error)."""
            t0 = time.monotonic()
            attempts = 0
            while True:
                name = router.owner(index)
                try:
                    status, data = do_request(
                        conns, name, f"/index/{index}/query", body)
                except Exception:
                    # stale keep-alive: reconnect, bounded retries
                    drop_conn(conns, name)
                    attempts += 1
                    if attempts > RETRY_CAP:
                        return None, "transport retries exhausted"
                    continue
                if status == 200:
                    return time.monotonic() - t0, None
                if status == 429:
                    attempts += 1
                    retries[0] += 1
                    if attempts > RETRY_CAP:
                        return None, "429 retries exhausted"
                    # client-side backoff on the bench's timescale (the
                    # server's Retry-After floor is a whole second —
                    # honoring it verbatim would quantize every p99 to
                    # 1s buckets); jittered linear ramp, 4→40ms
                    time.sleep(min(0.004 * attempts, 0.04)
                               * (0.5 + rng.random()))
                    continue
                return None, f"HTTP {status}: {data[:120]!r}"

        def query_worker(tid: int):
            conns: dict = {}
            rng = _random.Random(seed * 1000 + tid)
            while not stop.is_set():
                r = min(_bisect.bisect_left(cum, rng.random()),
                        len(index_set) - 1)
                lat, err = one_op(conns, rng, index_set[r],
                                  b"Count(Row(f=1))")
                with lock:
                    if err is not None:
                        errors.append(err)
                    elif lat is not None:
                        samples.append(
                            (time.monotonic() - t_start, lat))
            for c in conns.values():
                c.close()

        def writer_worker():
            # the acked-write ledger rider: a 200 on Set IS the ack —
            # every ledgered (index, col) must be queryable at the end,
            # however many placement moves its shard rode through
            conns: dict = {}
            rng = _random.Random(seed * 1000 + 777)
            col = 1000
            k = 0
            while not stop.is_set():
                index = index_set[k % len(index_set)]
                k += 1
                col += 1
                _lat, err = one_op(conns, rng, index,
                                   f"Set({col}, f=2)".encode())
                with lock:
                    if err is not None:
                        errors.append(f"write: {err}")
                    else:
                        write_ledger.add((index, col))
                time.sleep(0.02)  # read-dominated mix
            for c in conns.values():
                c.close()

        threads = [threading.Thread(target=query_worker, args=(t,),
                                    daemon=True)
                   for t in range(n_clients)]
        if write_ledger is not None:
            threads.append(threading.Thread(target=writer_worker,
                                            daemon=True))

        def refresher():
            while not stop.is_set():
                router.refresh()
                time.sleep(0.3)

        if refresh:
            threads.append(threading.Thread(target=refresher,
                                            daemon=True))
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        return samples, errors, retries[0]

    def p99_ms(samples, t_lo, t_hi) -> float:
        lats = [lat for at, lat in samples if t_lo <= at < t_hi]
        if not lats:
            return float("nan")
        return round(float(np.percentile(np.array(lats), 99)) * 1e3, 2)

    t0 = time.time()
    record: dict = {"config": "autopilot",
                    "metric": "hotspot_p99_recovery"}
    with tempfile.TemporaryDirectory() as tmp:
        # ---- phase A: control cluster, kill switch OFF ----------------
        control = spawn_cluster(f"{tmp}/off", autopilot_on=False)
        try:
            bases = control["bases"]
            kill_switch_ok = True
            for name in NAMES:
                j = req("GET", bases[name], "/debug/autopilot")
                pl = j.get("placement") or {}
                kill_switch_ok &= (j.get("enabled") is False
                                   and pl.get("epoch", -1) == 0
                                   and not pl.get("overrides"))
            seed_indexes(bases, uniform_set + hot_set)
            # byte-identity probe: every seeded index's WRITE heat (the
            # Sets above, posted at ap0) must surface on exactly the
            # ring-computed owner — real traffic observing placement
            time.sleep(0.3)
            heat_rows = {
                name: req("GET", bases[name], "/debug/heatmap")
                .get("shards", []) for name in NAMES
            }
            placement_mismatches = []
            for index in uniform_set + hot_set:
                holders = {
                    name for name, rows in heat_rows.items()
                    if any(r.get("index") == index
                           and r.get("writes", 0) > 0 for r in rows)
                }
                if holders != {_ring_owner(index)}:
                    placement_mismatches.append(
                        {"index": index, "want": _ring_owner(index),
                         "got": sorted(holders)})
            router = Router(bases)
            u_samples, u_errors, _ = run_load(
                bases, router, uniform_set, base_run_s)
            h_samples, h_errors, _ = run_load(
                bases, router, hot_set, base_run_s * 0.75)
            p99_uniform = p99_ms(u_samples, 2.0, base_run_s)
            p99_hot_unmanaged = p99_ms(h_samples, 2.0, base_run_s * 0.75)
        finally:
            terminate(control)

        # ---- phase B: autopilot ON, same skew -------------------------
        managed = spawn_cluster(f"{tmp}/on", autopilot_on=True)
        try:
            bases = managed["bases"]
            seed_indexes(bases, hot_set)
            router = Router(bases)
            ledger: set = set()
            m_samples, m_errors, m_retries = run_load(
                bases, router, hot_set, hot_run_s,
                write_ledger=ledger, refresh=True)
            p99_hot_early = p99_ms(m_samples, 0.0, 4.0)
            p99_recovered = p99_ms(m_samples, hot_run_s - 6.0, hot_run_s)
            timeline = [
                {"window_s": [w, w + 2], "p99_ms": p99_ms(m_samples,
                                                          w, w + 2)}
                for w in range(0, int(hot_run_s), 2)
            ]
            recover_at = next(
                (w["window_s"][0] for w in timeline
                 if w["window_s"][0] >= 4
                 and w["p99_ms"] <= 1.5 * p99_uniform), None)
            pilot = req("GET", bases[NAMES[0]], "/debug/autopilot")
            moves = (pilot.get("metrics") or {}).get(
                "autopilot_moves_executed_total", 0)
            overrides_live = len(
                (pilot.get("placement") or {}).get("overrides", []))
            # acked-write ledger: every Set acked through the resizes
            # must become queryable cluster-wide. Bounded retry: bits
            # that raced a move sit as stray residue until the next
            # anti-entropy pass (2s ticker) absorbs them into the new
            # owner — convergence, not loss
            lost = []
            for attempt in range(8):
                lost = []
                for index in hot_set:
                    want = {c for ix, c in ledger if ix == index}
                    if not want:
                        continue
                    out = req("POST", bases[NAMES[0]],
                              f"/index/{index}/query", b"Row(f=2)")
                    got = set(out.get("results", [{}])[0]
                              .get("columns", []))
                    lost.extend((index, c) for c in want - got)
                if not lost:
                    break
                time.sleep(2.0)
            lost_debug = {}
            if lost:
                # per-node view of every lost index while the cluster
                # still serves: local fragment inventory, per-node
                # placement epoch/overrides, per-node readback
                for index in sorted({ix for ix, _ in lost}):
                    per = {}
                    for name in NAMES:
                        ent = {}
                        try:
                            cat = req("GET", bases[name],
                                      f"/internal/fragments?index={index}")
                            ent["fragments"] = cat.get("fragments", [])
                        except Exception as e:  # noqa: BLE001
                            ent["fragments"] = f"ERR {e}"
                        try:
                            out = req("POST", bases[name],
                                      f"/index/{index}/query",
                                      b"Row(f=2)")
                            ent["row_f2"] = sorted(
                                out.get("results", [{}])[0]
                                .get("columns", []))[-8:]
                        except Exception as e:  # noqa: BLE001
                            ent["row_f2"] = f"ERR {e}"
                        try:
                            pl = req("GET", bases[name],
                                     "/debug/autopilot")["placement"]
                            ent["placement"] = [
                                o for o in pl.get("overrides", [])
                                if o["index"] == index]
                            ent["epoch"] = pl.get("epoch")
                        except Exception as e:  # noqa: BLE001
                            ent["placement"] = f"ERR {e}"
                        per[name] = ent
                    lost_debug[index] = per
                for name in NAMES:
                    try:
                        with open(f"{tmp}/on/{name}.log", "rb") as f:
                            tail = f.read()[-6000:]
                        lost_debug[f"log_{name}"] = [
                            ln for ln in
                            tail.decode("utf-8", "replace").splitlines()
                            if any(ix in ln for ix, _ in lost)
                            or "autopilot" in ln or "cleanup" in ln][-30:]
                    except Exception:  # noqa: BLE001
                        pass
        finally:
            terminate(managed)

        # ---- phase C: autopilot-active chaos schedules ----------------
        # the planner minting overrides and resizing WHILE partitions,
        # kills, and restarts land — gated on the same five oracles as
        # config_chaos (testing/chaos.py with_autopilot)
        from pilosa_tpu.testing.chaos import run_chaos

        chaos = run_chaos(
            f"{tmp}/chaos", n_schedules=n_chaos_schedules, n_nodes=3,
            replica_n=2, seed=seed, n_events=6, with_autopilot=True,
        )

    errors_total = len(u_errors) + len(h_errors) + len(m_errors)
    record.update({
        "n_nodes": len(NAMES), "n_hot_indexes": n_hot,
        "n_clients": n_clients, "inflight_cap": inflight_cap,
        "zipf_s": ZIPF_S, "hot_node": hot_node,
        "p99_uniform_ms": p99_uniform,
        "p99_hot_unmanaged_ms": p99_hot_unmanaged,
        "p99_hot_early_ms": p99_hot_early,
        "p99_recovered_ms": p99_recovered,
        "recovery_ratio": (round(p99_recovered / p99_uniform, 3)
                           if p99_uniform else None),
        "recovered_at_s": recover_at,
        "timeline": timeline,
        "autopilot_moves": moves,
        "placement_overrides_live": overrides_live,
        "retries_429": m_retries,
        "acked_writes": len(ledger),
        "lost_acked_writes": len(lost),
        "lost_sample": lost[:5],
        "lost_debug": lost_debug,
        "client_errors": errors_total,
        "error_sample": (u_errors + h_errors + m_errors)[:5],
        "kill_switch_byte_identical": bool(
            kill_switch_ok and not placement_mismatches),
        "placement_mismatches": placement_mismatches[:5],
        "chaos": {
            "schedules": chaos["schedules"],
            "autopilot_moves_total": chaos["autopilot_moves_total"],
            "lost_acked_writes": chaos["lost_acked_writes"],
            "replica_mismatches": chaos["replica_mismatches"],
            "failed_seeds": chaos["failed_seeds"],
            "unconverged": chaos["unconverged"],
            "ok": chaos["ok"],
        },
        "wall_s": round(time.time() - t0, 1),
        "ok": bool(
            kill_switch_ok and not placement_mismatches
            and errors_total == 0 and not lost
            and moves >= 1 and overrides_live >= 1
            and p99_recovered == p99_recovered  # not NaN
            and p99_uniform == p99_uniform
            and p99_recovered <= 1.5 * p99_uniform
            and chaos["ok"] and chaos["unconverged"] == 0),
    })
    return record


def _spawn_cpu_mesh_entry() -> None:
    """Run config5_mesh_cpu8 in a subprocess pinned to an 8-device
    virtual CPU platform (the axon TPU plugin would otherwise own the
    backend; see .claude/skills/verify for the env contract)."""
    import os
    import subprocess
    import sys

    env = {
        **os.environ,
        "JAX_PLATFORMS": "",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8").strip(),
    }
    proc = subprocess.run(
        [sys.executable, __file__, "--cpu-mesh-inner"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if proc.returncode != 0 or not lines:
        print(json.dumps({
            "config": 5, "metric": "ssb_4way_mesh_microbatched_dispatches",
            "ok": False, "error": (proc.stderr or "no output")[-500:],
        }), flush=True)
        return
    print(lines[-1], flush=True)


def config_cdc(n_chaos_schedules: int = 3, n_clients: int = 6,
               read_s: float = 5.0, n_shards: int = 4,
               density: float = 0.01, seed: int = 0) -> dict:
    """CDC backbone gate (ISSUE 16 — docs/OPERATIONS.md Replication &
    CDC): three oracles over the WAL tail change feed.

    1. **Byte-identical mirror under chaos** — an out-of-cluster
       follower tails n0 through randomized partition/kill/restart
       schedules (testing/chaos.py ``with_cdc``); after heal, every
       non-empty fragment n0 holds must be byte-identical in the
       mirror. Upstream restarts reset the seq space mid-schedule, so
       this also drives the unknown-cursor 410 → merge-resync path.
    2. **Follower read scaling** — primary and follower run as real OS
       subprocesses (separate interpreters, real parallelism); on
       >=2 cores the closed-loop read fleet against primary+follower
       must clear ≥1.7x the primary-alone QPS; on a single core (where
       wall-clock scaling is physically impossible) the gate is
       capacity instead — follower-alone ≥0.5x primary, combined
       ≥0.75x (no collapse) — with the mode recorded. Either way:
       follower staleness p99 under the 1 s budget while a writer
       keeps the feed moving, the follower converging to the primary's
       count after load, and the ``X-Pilosa-Max-Staleness`` gate live
       (an impossible budget sheds 503, a generous one serves).
    3. **As-of ledger bit-exactness** — every WAL seq between two
       backup generations restores bit-exactly via nearest-generation
       + feed replay (``restore --as-of``, storage/backup.py).
    """
    import http.client as _hc
    import os
    import socket
    import subprocess
    import sys
    import threading
    import urllib.request

    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import Holder
    from pilosa_tpu.storage.backup import backup_holder, restore_holder
    from pilosa_tpu.storage.view import VIEW_STANDARD
    from pilosa_tpu.testing.chaos import run_chaos

    t_start = time.time()
    rng = np.random.default_rng(29)
    errors: list = []

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def req(base, path, body=None, method="POST", headers=None,
            timeout=60):
        r = urllib.request.Request(f"{base}{path}", data=body,
                                   method=method,
                                   headers=headers or {})
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, resp.read()

    def spawn(data_dir: str, name: str, extra_env: dict) -> tuple:
        port = free_port()
        env = {
            **os.environ, "JAX_PLATFORMS": "cpu",
            "PILOSA_TPU_NAME": name,
            "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "0",
            "PILOSA_TPU_HEARTBEAT_INTERVAL": "0",
            "PILOSA_TPU_USE_MESH": "false",
            **extra_env,
        }
        proc = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu", "server",
             "--data-dir", data_dir, "--bind", "127.0.0.1",
             "--port", str(port)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        base = f"http://127.0.0.1:{port}"
        for _ in range(240):
            if proc.poll() is not None:
                raise AssertionError(f"{name} exited rc={proc.returncode}")
            try:
                req(base, "/status", method="GET", timeout=5)
                return proc, base
            except Exception:
                time.sleep(0.25)
        proc.terminate()
        raise AssertionError(f"{name} never served /status")

    result: dict = {"config": "cdc", "metric": "cdc_backbone_oracles"}
    with tempfile.TemporaryDirectory() as tmp:
        # ---- phase 1: byte-identical mirror under chaos
        chaos = run_chaos(
            f"{tmp}/chaos", n_schedules=n_chaos_schedules,
            n_events=7, seed=seed, with_cdc=True,
        )

        # ---- phase 2: follower read scaling (subprocess parallelism)
        n_bits = int(SHARD_WIDTH * density)
        payloads = []
        for _ in range(n_shards):
            ids = []
            for row in (1, 2, 3, 4):
                pos = rng.choice(SHARD_WIDTH, n_bits,
                                 replace=False).astype(np.uint64)
                ids.append((np.uint64(row) << np.uint64(20)) + pos)
            bm = RoaringBitmap()
            bm.add_ids(np.concatenate(ids))
            payloads.append(serialize(bm))
        expected = [None]  # Count(Row(f=1)) once seeded

        primary = follower = None
        qps_primary = qps_combined = qps_follower = 0.0
        staleness: list = []
        writes = [0]
        converged = gated_ok = False
        try:
            primary, pbase = spawn(f"{tmp}/primary", "cdc-primary", {})
            req(pbase, "/index/cdc", b"{}")
            req(pbase, "/index/cdc/field/f", b"{}")
            for shard, payload in enumerate(payloads):
                req(pbase,
                    f"/index/cdc/field/f/import-roaring/{shard}"
                    "?remote=true", payload,
                    headers={"Content-Type":
                             "application/octet-stream"})
            _, body = req(pbase, "/index/cdc/query",
                          b"Count(Row(f=1))")
            expected[0] = json.loads(body)["results"][0]

            follower, fbase = spawn(
                f"{tmp}/follower", "cdc-follower",
                {"PILOSA_TPU_CDC_FOLLOW": pbase,
                 "PILOSA_TPU_CDC_POLL_INTERVAL": "25ms",
                 "PILOSA_TPU_CDC_STALENESS_BUDGET": "5s"})
            deadline = time.time() + 90
            while time.time() < deadline:
                try:
                    _, body = req(fbase, "/index/cdc/query",
                                  b"Count(Row(f=1))")
                    if json.loads(body)["results"][0] == expected[0]:
                        break
                except Exception:
                    pass
                time.sleep(0.25)
            else:
                raise AssertionError("follower never caught up to seed")

            stop = threading.Event()
            side_stop = threading.Event()
            counts: dict = {}

            def reader(tag, base):
                conn = _hc.HTTPConnection(
                    base.split("//")[1].split(":")[0],
                    int(base.rsplit(":", 1)[1]), timeout=60)
                n = k = 0
                try:
                    while not stop.is_set():
                        conn.request(
                            "POST",
                            f"/index/cdc/query",
                            body=f"Count(Row(f={1 + (k % 4)}))".encode())
                        resp = conn.getresponse()
                        resp.read()
                        if resp.status == 200:
                            n += 1
                        else:
                            errors.append((tag, resp.status))
                        k += 1
                finally:
                    conn.close()
                counts[tag] = counts.get(tag, 0) + n

            def run_fleet(targets, dur) -> float:
                # constant TOTAL client threads split evenly across
                # targets, so every window presents the same client-
                # side load and only the serving capacity varies
                stop.clear()
                counts.clear()
                per = max(1, n_clients // len(targets))
                threads = [
                    threading.Thread(target=reader,
                                     args=(f"{i}:{b}", b))
                    for b in targets for i in range(per)
                ]
                for t in threads:
                    t.start()
                time.sleep(dur)
                stop.set()
                for t in threads:
                    t.join(30)
                return sum(counts.values()) / dur

            def writer():
                k = 0
                while not side_stop.is_set():
                    try:
                        st, _ = req(pbase, "/index/cdc/query",
                                    f"Set({5 * SHARD_WIDTH + k}, "
                                    f"f=9)".encode())
                        if st == 200:
                            writes[0] += 1
                    except Exception as e:  # noqa: BLE001
                        errors.append(("writer", repr(e)))
                    k += 1
                    time.sleep(0.02)

            def sampler():
                while not side_stop.is_set():
                    try:
                        _, body = req(fbase, "/debug/vars",
                                      method="GET", timeout=5)
                        s = json.loads(body)["cdc"].get(
                            "cdc_follower_staleness_seconds", -1.0)
                        if s >= 0:
                            staleness.append(s)
                    except Exception:  # noqa: BLE001 — sampled gauge
                        pass
                    time.sleep(0.1)

            # the writer + staleness sampler run across EVERY window
            # on their own stop flag, so the baseline and the combined
            # phase carry identical write/feed load — the only delta
            # between windows is which servers take the read fleet
            side = [threading.Thread(target=writer),
                    threading.Thread(target=sampler)]
            for t in side:
                t.start()
            qps_primary = run_fleet([pbase], read_s)
            qps_combined = run_fleet([pbase, fbase], read_s)
            qps_follower = run_fleet([fbase], read_s)
            side_stop.set()
            for t in side:
                t.join(30)

            # follower converges to the primary's post-load count
            _, body = req(pbase, "/index/cdc/query",
                          b"Count(Row(f=9))")
            want9 = json.loads(body)["results"][0]
            deadline = time.time() + 15
            while time.time() < deadline:
                _, body = req(fbase, "/index/cdc/query",
                              b"Count(Row(f=9))")
                if json.loads(body)["results"][0] == want9:
                    converged = True
                    break
                time.sleep(0.1)

            # the staleness QoS gate is live: generous budget serves,
            # impossible budget sheds 503 + Retry-After
            st_ok, _ = req(fbase, "/index/cdc/query",
                           b"Count(Row(f=1))",
                           headers={"X-Pilosa-Max-Staleness": "30s"})
            try:
                req(fbase, "/index/cdc/query", b"Count(Row(f=1))",
                    headers={"X-Pilosa-Max-Staleness": "1us"})
                shed = False
            except urllib.error.HTTPError as e:
                shed = e.code == 503
            gated_ok = st_ok == 200 and shed
        except Exception as e:  # noqa: BLE001 — surfaced via gate
            errors.append(repr(e))
        finally:
            for proc in (follower, primary):
                if proc is not None:
                    proc.terminate()
                    try:
                        proc.wait(10)
                    except subprocess.TimeoutExpired:
                        proc.kill()

        # ---- phase 3: as-of ledger bit-exactness
        asof_checked = 0
        asof_exact = True
        h = Holder(f"{tmp}/asof/src").open()
        try:
            idx = h.create_index("i", track_existence=False)
            fld = idx.create_field("f")
            frag = fld.view(VIEW_STANDARD, create=True).fragment(
                0, create=True)
            for i in range(8):
                frag.set_bit(1, i)
            h.wal.barrier()
            bk = f"{tmp}/asof/bk"
            backup_holder(h, bk)
            ledger = {}
            cols = set(range(8))
            for i in range(8, 20):
                frag.set_bit(1, i)
                cols.add(i)
                h.wal.barrier()
                ledger[h.wal.durable_seq()] = sorted(cols)
            frag.clear_bit(1, 2)
            cols.discard(2)
            h.wal.barrier()
            ledger[h.wal.durable_seq()] = sorted(cols)
            backup_holder(h, bk)
            for seq_pt, want in ledger.items():
                dst = f"{tmp}/asof/r{seq_pt}"
                restore_holder(bk, dst, as_of=seq_pt)
                rh = Holder(dst).open()
                try:
                    got = sorted(
                        rh.index("i").field("f").view(VIEW_STANDARD)
                        .fragment(0).row_columns(1).tolist())
                finally:
                    rh.close()
                asof_checked += 1
                if got != want:
                    asof_exact = False
                    errors.append(("asof-mismatch", seq_pt))
        finally:
            h.close()

    scaling = qps_combined / qps_primary if qps_primary else 0.0
    stale_p99 = (float(np.percentile(staleness, 99))
                 if staleness else -1.0)
    # the wall-clock scaling gate needs real parallelism: primary,
    # follower, and the client fleet are separate OS processes, so on
    # >=2 cores the combined window must clear 1.7x primary-alone. On
    # a single core three processes time-slice one CPU and wall-clock
    # scaling is physically impossible — gate capacity instead: the
    # follower alone must serve >=0.5x the primary's QPS from its own
    # storage, and spanning the fleet across both must not collapse
    # (>=0.75x). The mode is recorded, never silently downgraded.
    cores = os.cpu_count() or 1
    if cores >= 2:
        scaling_mode = "multicore-wall-clock"
        scaling_ok = scaling >= 1.7
    else:
        scaling_mode = "single-core-capacity"
        scaling_ok = bool(
            qps_primary > 0
            and qps_follower >= 0.5 * qps_primary
            and qps_combined >= 0.75 * qps_primary)
    result.update({
        "chaos_schedules": chaos["schedules"],
        "chaos_ok": chaos["ok"],
        "chaos_failed_seeds": chaos["failed_seeds"],
        "cdc_mirror_mismatches": chaos["cdc_mirror_mismatches"],
        "cdc_resyncs_total": chaos["cdc_resyncs_total"],
        "cdc_applied_ops_total": chaos["cdc_applied_ops_total"],
        "read_qps_primary": round(qps_primary, 1),
        "read_qps_with_follower": round(qps_combined, 1),
        "read_qps_follower_alone": round(qps_follower, 1),
        "follower_read_scaling": round(scaling, 3),
        "scaling_gate_mode": scaling_mode,
        "cpu_cores": cores,
        "follower_staleness_p99_s": round(stale_p99, 4),
        "staleness_samples": len(staleness),
        "feed_writes_during_load": writes[0],
        "follower_converged_after_load": converged,
        "staleness_gate_live": gated_ok,
        "asof_points_checked": asof_checked,
        "asof_bit_exact": asof_exact,
        "client_errors": len(errors),
        "error_sample": [str(e)[:160] for e in errors[:5]],
        "wall_s": round(time.time() - t_start, 1),
    })
    result["ok"] = bool(
        chaos["ok"]
        and scaling_ok
        and 0.0 <= stale_p99 < 1.0
        and converged and gated_ok
        and asof_exact and asof_checked >= 13
        and not errors
    )
    return result


def config_elastic(n_clients: int = 6, n_shards: int = 4,
                   phase_s: float = 4.0, n_chaos_schedules: int = 3,
                   seed: int = 0) -> dict:
    """Elastic membership gate (ISSUE 17 — docs/OPERATIONS.md elastic
    operations), three parts:

    **A — scripted grow/shrink under live traffic.** A 3-node
    in-process cluster serves a Zipf read mix plus a ledgered writer
    while the script grows it to 5 (two cold joiners absorb their
    shards) and drains it back to 3 (graceful ``drain`` per departing
    node: groups move, CDC cursors hand off, the target sheds writes
    through the tail and leaves). Gates: ZERO lost acked writes (every
    200-acked Set queryable at the end, through two joins and two
    drains), zero client errors (a 503/429 retried to success is
    backpressure, not an error), and p99 CONTINUITY — no 2s window
    goes dark, and no window's p99 exceeds max(10x the steady-state
    plateau, 1200ms). The absolute floor absorbs the genuine
    double-join resize window on a GIL-shared in-process cluster;
    the real claim is "degraded, never dark": zero dark windows,
    zero errors, zero lost writes, sub-1.2s worst p99.

    **B — hot single shard recovered by a range split.** One index,
    one shard, every byte of its heat on one owner — placement moves
    cannot help (the unsplittable-tenant hole the range table closes).
    With ``autopilot-split-threshold`` armed the planner must mint a
    sub-shard split spreading the shard across >= 2 nodes, every peer
    must adopt the range table, reads must stay byte-correct, and
    remote reads entering through a NON-owner must actually fan out
    across the span owners (measured per-node request deltas).

    **C — chaos mid-drain.** ``run_chaos(with_elastic=True,
    with_cdc=True)``: drain events land in the same bag as kills and
    partitions, so faults hit MID-drain; gated on all six oracles
    (acked writes, quorum deletions, one-coordinator-per-epoch,
    replica identity, CDC mirror, convergence)."""
    import random as _random
    import threading
    import urllib.error
    import urllib.request

    from pilosa_tpu.shardwidth import SHARD_WIDTH

    INDEX = "el"
    ZIPF_S = 1.1
    RETRY_CAP = 300
    N_ROWS = 4

    def req(method, base, path, body=None, timeout=30):
        r = urllib.request.Request(f"{base}{path}", data=body,
                                   method=method)
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return json.loads(resp.read() or b"{}")

    from pilosa_tpu.server import Server, ServerConfig

    def make_server(tmp, name, seeds, **kw):
        cfg = dict(
            data_dir=f"{tmp}/{name}", port=0, name=name, replica_n=2,
            seeds=seeds, anti_entropy_interval=1.0,
            heartbeat_interval=0.1, heartbeat_timeout=0.5,
            use_mesh=False,
        )
        cfg.update(kw)
        return Server(ServerConfig(**cfg)).open()

    t_all = time.time()
    record: dict = {"config": "elastic", "metric": "elastic_membership"}

    # ---- part A: scripted 3 -> 5 -> 3 under live traffic ---------------
    servers: dict = {}
    srv_lock = threading.Lock()

    def live_bases() -> list:
        with srv_lock:
            return [f"http://localhost:{s.port}" for s in servers.values()]

    samples: list = []
    errors: list = []
    ledger: set = set()
    retried = [0]
    stop = threading.Event()
    t_start = [0.0]
    lock = threading.Lock()

    def one_op(rng, body):
        t0 = time.monotonic()
        attempts = 0
        while True:
            bases = live_bases()
            if not bases:
                return None, None, "no live nodes"
            base = bases[rng.randrange(len(bases))]
            try:
                out = req("POST", base, f"/index/{INDEX}/query", body,
                          timeout=10)
                return time.monotonic() - t0, out, None
            except urllib.error.HTTPError as e:
                code = e.code
                e.read()
                attempts += 1
                if code in (429, 503) and attempts <= RETRY_CAP:
                    retried[0] += 1
                    time.sleep(min(0.004 * attempts, 0.04)
                               * (0.5 + rng.random()))
                    continue
                return None, None, f"HTTP {code}"
            except Exception as e:  # noqa: BLE001 — a node mid-close
                attempts += 1      # drops the connection; re-route
                if attempts <= RETRY_CAP:
                    time.sleep(0.01)
                    continue
                return None, None, f"transport: {e}"

    weights = np.array([1.0 / (r + 1) ** ZIPF_S for r in range(N_ROWS)])
    cum = np.cumsum(weights / weights.sum()).tolist()

    def reader(tid: int):
        import bisect as _bisect

        rng = _random.Random(seed * 1000 + tid)
        while not stop.is_set():
            row = 1 + min(_bisect.bisect_left(cum, rng.random()),
                          N_ROWS - 1)
            lat, _out, err = one_op(rng, f"Count(Row(f={row}))".encode())
            with lock:
                if err is not None:
                    errors.append(err)
                elif lat is not None:
                    samples.append((time.monotonic() - t_start[0], lat))

    def writer():
        rng = _random.Random(seed * 1000 + 777)
        col = 0
        while not stop.is_set():
            col += 1
            c = (col % n_shards) * SHARD_WIDTH + col
            _lat, out, err = one_op(rng, f"Set({c}, f=9)".encode())
            with lock:
                if err is not None:
                    errors.append(f"write: {err}")
                elif out is not None and out.get("results") == [True]:
                    ledger.add(c)
            time.sleep(0.01)

    with tempfile.TemporaryDirectory() as tmp:
        seeds: list = []
        for i in range(3):
            s = make_server(f"{tmp}/a", f"e{i}", seeds)
            servers[f"e{i}"] = s
            if not seeds:
                seeds = [f"http://localhost:{s.port}"]
        for s in servers.values():
            assert s.api.cluster.wait_until_normal(30)
        entry = f"http://localhost:{servers['e0'].port}"
        req("POST", entry, f"/index/{INDEX}", b"{}")
        req("POST", entry, f"/index/{INDEX}/field/f", b"{}")
        for shard in range(n_shards):
            for row in range(1, N_ROWS + 1):
                req("POST", entry, f"/index/{INDEX}/query",
                    f"Set({shard * SHARD_WIDTH + row}, f={row})".encode())

        t_start[0] = time.monotonic()
        threads = [threading.Thread(target=reader, args=(t,), daemon=True)
                   for t in range(n_clients)]
        threads.append(threading.Thread(target=writer, daemon=True))
        for t in threads:
            t.start()
        script_log: list = []
        time.sleep(phase_s)  # steady-state plateau at 3 nodes

        # grow 3 -> 5: two cold joiners warm from the live heatmap
        for name in ("e3", "e4"):
            with srv_lock:
                servers[name] = make_server(f"{tmp}/a", name, seeds)
            script_log.append(
                {"t": round(time.monotonic() - t_start[0], 2),
                 "event": f"join {name}"})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with srv_lock:
                views = [set(s.api.cluster.nodes)
                         for s in servers.values()]
            if all(v == {"e0", "e1", "e2", "e3", "e4"} for v in views):
                break
            time.sleep(0.2)
        else:
            script_log.append({"event": "membership never reached 5"})
        time.sleep(phase_s)  # serve at 5

        # snapshot join-warm counters NOW: they live on the joiners,
        # which the shrink below drains and closes
        warm = {k: 0 for k in ("elastic_warm_heat_ordered_total",
                               "elastic_warm_verified_total",
                               "elastic_warm_verify_failed_total")}
        with srv_lock:
            for s in servers.values():
                m = s.api.cluster.metrics()
                for k in warm:
                    warm[k] += m.get(k, 0)

        # shrink 5 -> 3: graceful drains, one at a time
        drains_ok = True
        for name in ("e3", "e4"):
            done = False
            deadline = time.monotonic() + 45
            while time.monotonic() < deadline:
                with srv_lock:
                    coord = next(
                        (s for s in servers.values()
                         if s.api.cluster.is_acting_coordinator), None)
                if coord is None:
                    time.sleep(0.2)
                    continue
                try:
                    # a CDC tailer pinned to the victim: the drain's
                    # handoff step must re-home its retention and drop
                    # the cursor (counted in elastic_cursor_handoffs)
                    wal = getattr(coord.api.holder, "wal", None)
                    if wal is not None:
                        wal.register_cursor(f"tailer:{name}", 0)
                    coord.api.drain_start(name)
                except Exception:  # noqa: BLE001 — resize in flight /
                    time.sleep(0.3)  # not NORMAL yet: retry
                    continue
                while time.monotonic() < deadline:
                    st = coord.api.cluster.drain_record
                    if st.get("target") == name and st.get("state") in (
                            "done", "failed", "aborted"):
                        done = st["state"] == "done"
                        break
                    time.sleep(0.1)
                break
            drains_ok &= done
            script_log.append(
                {"t": round(time.monotonic() - t_start[0], 2),
                 "event": f"drain {name}",
                 "done": done})
            with srv_lock:
                victim = servers.pop(name, None)
            if victim is not None:
                victim.close()
        time.sleep(phase_s)  # steady state back at 3

        stop.set()
        for t in threads:
            t.join(timeout=30)
        run_s = time.monotonic() - t_start[0]

        # acked-write ledger readback (bounded retries: strays that
        # raced a move converge through the 1s anti-entropy ticker)
        with srv_lock:
            probe = f"http://localhost:{servers['e0'].port}"
        lost: list = []
        for _ in range(8):
            try:
                out = req("POST", probe, f"/index/{INDEX}/query",
                          b"Row(f=9)", timeout=30)
                got = set(out.get("results", [{}])[0].get("columns", []))
            except Exception:  # noqa: BLE001
                got = set()
            lost = sorted(ledger - got)
            if not lost:
                break
            time.sleep(2.0)

        cursor_handoffs = 0
        drains_completed = 0
        with srv_lock:
            for s in servers.values():
                em = s.api.elastic_metrics()
                cursor_handoffs += em.get(
                    "elastic_cursor_handoffs_total", 0)
                drains_completed += em.get(
                    "elastic_drains_completed_total", 0)
            part_a_servers = list(servers.values())
            servers.clear()
        for s in part_a_servers:
            s.close()

        def p99_ms(t_lo, t_hi) -> float:
            lats = [lat for at, lat in samples if t_lo <= at < t_hi]
            if not lats:
                return float("nan")
            return round(float(np.percentile(np.array(lats), 99)) * 1e3,
                         2)

        plateau_p99 = p99_ms(1.0, phase_s)
        timeline = [{"window_s": [w, w + 2],
                     "p99_ms": p99_ms(w, w + 2)}
                    for w in range(0, int(run_s), 2)]
        dark_windows = [w["window_s"] for w in timeline
                        if w["p99_ms"] != w["p99_ms"]]  # NaN = no sample
        p99_worst = max((w["p99_ms"] for w in timeline
                         if w["p99_ms"] == w["p99_ms"]),
                        default=float("nan"))
        continuity_ok = bool(
            not dark_windows and plateau_p99 == plateau_p99
            and p99_worst == p99_worst
            and p99_worst <= max(10 * plateau_p99, 1200.0))

        # ---- part B: hot single shard recovered by a range split -------
        split_rec = _elastic_split_part(tmp, req, make_server, seed)

        # ---- part C: chaos schedules that kill/partition mid-drain -----
        from pilosa_tpu.testing.chaos import run_chaos

        chaos = run_chaos(
            f"{tmp}/chaos", n_schedules=n_chaos_schedules, n_nodes=4,
            replica_n=2, seed=seed, n_events=8,
            with_elastic=True, with_cdc=True,
        )

    record.update({
        "grow_shrink": {
            "script": script_log,
            "drains_ok": drains_ok,
            "drains_completed": drains_completed,
            "cursor_handoffs": cursor_handoffs,
            "acked_writes": len(ledger),
            "lost_acked_writes": len(lost),
            "lost_sample": lost[:5],
            "client_errors": len(errors),
            "error_sample": errors[:5],
            "retries_shed": retried[0],
            "plateau_p99_ms": plateau_p99,
            "worst_window_p99_ms": p99_worst,
            "dark_windows": dark_windows,
            "continuity_ok": continuity_ok,
            "timeline": timeline,
            "join_warm": warm,
        },
        "split": split_rec,
        "chaos": {
            "schedules": chaos["schedules"],
            "drains_total": chaos["drains_total"],
            "lost_acked_writes": chaos["lost_acked_writes"],
            "non_quorum_deletions": chaos["non_quorum_deletions"],
            "coordinator_conflicts": chaos["coordinator_conflicts"],
            "replica_mismatches": chaos["replica_mismatches"],
            "cdc_mirror_mismatches": chaos["cdc_mirror_mismatches"],
            "unconverged": chaos["unconverged"],
            "failed_seeds": chaos["failed_seeds"],
            "failed_diags": chaos["failed_diags"],
            "ok": chaos["ok"],
        },
        "wall_s": round(time.time() - t_all, 1),
        "ok": bool(
            drains_ok and not lost and not errors and continuity_ok
            and split_rec["ok"]
            and chaos["ok"] and chaos["unconverged"] == 0),
    })
    return record


def _elastic_split_part(tmp: str, req, make_server, seed: int) -> dict:
    """Part B of config_elastic: one pathologically hot (index, shard)
    on one owner; the armed splitter must spread it across nodes and
    remote reads entering through a non-owner must fan out over the
    span owners."""
    import urllib.request  # noqa: F401 — req closes over it

    servers: dict = {}
    seeds: list = []
    for i in range(3):
        s = make_server(
            f"{tmp}/b", f"s{i}", seeds, replica_n=1,
            autopilot_enabled=True, autopilot_interval=300.0,
            autopilot_split_threshold=1.5, autopilot_split_ways=2)
        servers[f"s{i}"] = s
        if not seeds:
            seeds = [f"http://localhost:{s.port}"]
    try:
        for s in servers.values():
            assert s.api.cluster.wait_until_normal(30)
        entry = f"http://localhost:{servers['s0'].port}"
        req("POST", entry, "/index/hot", b"{}")
        req("POST", entry, "/index/hot/field/f", b"{}")
        for col in range(64):
            req("POST", entry, "/index/hot/query",
                f"Set({col}, f=1)".encode())
        for _ in range(300):  # all heat on hot/0
            req("POST", entry, "/index/hot/query", b"Count(Row(f=1))")
        coord = next(s for s in servers.values()
                     if s.api.cluster.is_acting_coordinator)
        split_minted = False
        for _ in range(10):  # forced passes: deterministic replay
            rec = coord.api.autopilot.run_pass()
            if rec.get("splits"):
                split_minted = True
                break
            time.sleep(0.5)
        c = coord.api.cluster
        spans = c.placement.get_ranges("hot", 0) or ()
        span_owners = sorted({i for _lo, _hi, ids in spans for i in ids})
        adopted = all(s.api.cluster.placement.range_count >= len(spans)
                      for s in servers.values())
        # reads stay byte-correct through the split
        out = req("POST", entry, "/index/hot/query", b"Count(Row(f=1))")
        count_ok = out.get("results") == [64]
        # fan-out: drive reads through a NON-owner entry and measure
        # which span owners' HTTP listeners absorbed the remote reads
        non_owner = next((s for s in servers.values()
                          if s.config.name not in span_owners), None)
        fanout: dict = {}
        if non_owner is not None and span_owners:
            def served(name):
                base = f"http://localhost:{servers[name].port}"
                return req("GET", base, "/debug/vars")[
                    "serving_fastlane"]["http_requests_total"]

            before = {n: served(n) for n in span_owners}
            nb = f"http://localhost:{non_owner.port}"
            for _ in range(200):
                req("POST", nb, "/index/hot/query", b"Count(Row(f=1))")
            fanout = {n: served(n) - before[n] for n in span_owners}
        spread_ok = (len(span_owners) >= 2
                     and len([n for n, d in fanout.items() if d >= 10])
                     >= 2)
        # write amplification through the split: plain Sets entering
        # through the non-owner must narrow to each column's span owner
        # (one remote send per write), while a range-ineligible write
        # (Clear — union repair cannot remove a bit a narrowed send
        # skipped) keeps the full union fan-out to every span owner.
        # The wire-byte ratio between the two on the same columns IS
        # the write-amp reduction the range-aware fast lane buys.
        write_amp: dict = {}
        if non_owner is not None and span_owners:
            from pilosa_tpu.parallel.cluster import global_route_stats

            rs = global_route_stats()
            nb = f"http://localhost:{non_owner.port}"
            n_writes = 64
            before_w = (rs.range_slices, rs.union_writes, rs.wire_bytes)
            for col in range(n_writes):
                req("POST", nb, "/index/hot/query",
                    f"Set({col}, f=2)".encode())
            mid_w = (rs.range_slices, rs.union_writes, rs.wire_bytes)
            for col in range(n_writes):
                req("POST", nb, "/index/hot/query",
                    f"Clear({col}, f=3)".encode())
            after_w = (rs.range_slices, rs.union_writes, rs.wire_bytes)
            ranged_bytes = mid_w[2] - before_w[2]
            union_bytes = after_w[2] - mid_w[2]
            # zero lost acked writes, two ways: (a) range-aware reads
            # (non-owner entry fans out per span, hitting the exact
            # owner each narrowed Set landed on) see every write NOW;
            # (b) anti-entropy's union repair refills the OTHER union
            # owners, after which a read through any owner sees them
            out2 = req("POST", nb, "/index/hot/query",
                       b"Count(Row(f=2))")
            converged = False
            for _ in range(40):
                out3 = req("POST", entry, "/index/hot/query",
                           b"Count(Row(f=2))")
                if out3.get("results") == [n_writes]:
                    converged = True
                    break
                time.sleep(0.5)
            write_amp = {
                "writes": n_writes,
                "range_sliced": mid_w[0] - before_w[0],
                "union_fallback_writes": after_w[1] - mid_w[1],
                "ranged_bytes_per_write": round(
                    ranged_bytes / n_writes, 1),
                "union_bytes_per_write": round(
                    union_bytes / n_writes, 1),
                "write_amp_reduction": round(
                    union_bytes / ranged_bytes, 2) if ranged_bytes
                else 0.0,
                "acked_writes_readable": out2.get("results")
                == [n_writes],
                "union_repair_converged": converged,
            }
        write_amp_ok = bool(
            write_amp
            and write_amp["range_sliced"] >= 1
            and write_amp["union_fallback_writes"] >= 1
            and write_amp["acked_writes_readable"]
            and write_amp["union_repair_converged"]
            and write_amp["write_amp_reduction"] >= 1.5)
        return {
            "split_minted": split_minted,
            "spans": [[lo, hi, list(ids)] for lo, hi, ids in spans],
            "span_owners": span_owners,
            "adopted_by_all": adopted,
            "count_correct": count_ok,
            "non_owner_fanout": fanout,
            "write_amp": write_amp,
            "splits_executed": coord.api.autopilot_metrics().get(
                "autopilot_splits_total", 0),
            "ok": bool(split_minted and len(spans) >= 2 and adopted
                       and count_ok and spread_ok and write_amp_ok),
        }
    finally:
        for s in servers.values():
            s.close()


# Model-vs-measured wire-byte reconciliation band (docs/OPERATIONS.md
# "Multi-chip mesh"): profiler-attributed transfer bytes must land
# within [0.5x, 2x] of the ReduceStats model. The model counts payload
# bytes only (no headers/retries/fragmentation), and the profiler's
# bytes_accessed includes local buffer traffic — a 2x envelope separates
# "model is honest" from "model is fiction" without chasing either
# artifact. On hosts whose traces lack transfer lanes (CPU-only), the
# reconciliation records a structured skip instead.
RECONCILE_BAND = (0.5, 2.0)


def config_mesh_inner(n_devices: int) -> dict:
    """One mesh size of the hierarchical-reduction gate: the flat 1-D
    mesh (the dense baseline every prior PR certified) vs the 2-D
    groups x shards mesh over the canonical 20 dryrun read shapes.

    Three oracles per size:

    1. byte-identical ``result_to_json`` between the dense and
       hierarchical executors on all 20 shapes (the narrowed inter-group
       lanes are lossless by construction — this proves it end to end);
    2. >=4x fewer reduction-lane wire bytes than the dense equivalent on
       the Row/TopN subset (roaring row frames + narrow scalar lanes);
    3. a cols/sec throughput figure so MULTICHIP records stay comparable
       across mesh sizes;
    4. quantized-ranking mode (EQuARX 8-bit candidate lanes +
       widened-window exact recount) byte-identical to the SINGLE-DEVICE
       executor on all 20 shapes AND a measured additional inter-group
       wire-byte reduction vs the lossless lane on the ranking workload;
    5. model-vs-measured wire-byte reconciliation from the profiler
       trace, within RECONCILE_BAND — or a structured, documented skip
       when the host's traces lack transfer lanes (CPU-only).
    """
    from __graft_entry__ import DRYRUN_QUERY_SHAPES, _ensure_devices
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor.result import result_to_json
    from pilosa_tpu.parallel import DistExecutor, make_mesh, mesh_groups
    from pilosa_tpu.parallel import reduction
    from pilosa_tpu.shardwidth import SHARD_WIDTH
    from pilosa_tpu.storage import FieldOptions, Holder

    _ensure_devices(max(n_devices, 2))
    flat = make_mesh(n_devices)
    hier = make_mesh(n_devices, groups=2)

    with tempfile.TemporaryDirectory() as tmp:
        holder = Holder(tmp).open()
        try:
            idx = holder.create_index("mesh")
            f = idx.create_field("f")
            g = idx.create_field("g")
            # 64-row field: a realistic TopN candidate population for
            # the quantized-ranking leg (f's 3 rows would make the
            # window == the whole set)
            many = idx.create_field("many")
            fare = idx.create_field(
                "fare", FieldOptions(type="int", min=0, max=100))
            idx.create_field("tag", FieldOptions(keys=True))
            rng = np.random.default_rng(1)
            n_shards = n_devices + 3  # deliberately not divisible
            cols = []
            for shard in range(n_shards):
                base = shard * SHARD_WIDTH
                for c in rng.choice(SHARD_WIDTH, 50, replace=False).tolist():
                    f.set_bit(1 + (c % 3), base + c)
                    many.set_bit(c % 64, base + c)
                    if c % 2 == 0:
                        g.set_bit(7, base + c)
                    cols.append(base + c)
            for c in cols[::10]:
                fare.set_value(c, int(rng.integers(0, 100)))
            idx.mark_columns_exist(cols)

            base_ex = Executor(holder)
            for name, key_cols in [("alpha", cols[:7]), ("amber", cols[7:12]),
                                   ("beta", cols[12:15])]:
                for c in key_cols:
                    base_ex.execute("mesh", f'Set({c}, tag="{name}")')

            dense_ex = DistExecutor(holder, flat)
            hier_ex = DistExecutor(holder, hier)
            probe = min(c for c in cols if (c % SHARD_WIDTH) % 3 == 0)
            queries = [q.format(probe=probe) for q in DRYRUN_QUERY_SHAPES]

            mismatches = []
            for pql in queries:
                want = result_to_json(dense_ex.execute("mesh", pql)[0])
                got = result_to_json(hier_ex.execute("mesh", pql)[0])
                if got != want:
                    mismatches.append(pql)

            # reduction-lane wire bytes on the Row/TopN subset: dense
            # equivalent (flat int32 ring) vs what the hierarchical
            # plane actually moves (intra-group ICI psum excluded —
            # reported separately as intra_bytes)
            stats = reduction.global_reduce_stats()
            stats.reset()
            hier_ex.execute("mesh", "Union(Row(f=1), Row(f=2))")
            hier_ex.execute("mesh", "TopN(f, n=2)")
            snap = stats.snapshot()
            row_dense = snap["dense_bytes"] + snap["row_dense_bytes"]
            row_actual = snap["actual_bytes"] + snap["row_actual_bytes"]
            ratio = row_dense / max(row_actual, 1)

            stats.reset()
            for pql in queries:
                hier_ex.execute("mesh", pql)
            all_snap = stats.snapshot()

            # ---- quantized-ranking leg (topn-quantized-ranking) ----
            # byte-identity vs the SINGLE-DEVICE executor on every shape
            # (verify_quantized additionally re-runs the lossless window
            # internally and asserts), then the measured wire delta on
            # the ranking workload: lossless hier vs quantized hier.
            quant_ex = DistExecutor(holder, hier, quantized_ranking=True,
                                    verify_quantized=True)
            q_mismatches = []
            for pql in queries:
                want = result_to_json(base_ex.execute("mesh", pql)[0])
                got = result_to_json(quant_ex.execute("mesh", pql)[0])
                if got != want:
                    q_mismatches.append(pql)
            ranking_queries = [
                "TopN(many, n=3)", "TopN(many, n=8)",
                "TopN(many, n=5, threshold=40)", "TopN(f, n=2)",
            ]
            for pql in ranking_queries:  # warm both program caches
                hier_ex.execute("mesh", pql)
                quant_ex.execute("mesh", pql)
            stats.reset()
            for pql in ranking_queries:
                hier_ex.execute("mesh", pql)
            lossless_snap = stats.snapshot()
            stats.reset()
            for pql in ranking_queries:
                quant_ex.execute("mesh", pql)
            quant_snap = stats.snapshot()
            # verify_quantized re-runs the lossless recount inside the
            # quantized executor — its dispatches are certification
            # overhead, not wire the mode would pay in production:
            # subtract the modeled lossless bytes of the reference pass.
            quant_wire = (quant_snap["actual_bytes"]
                          - lossless_snap["actual_bytes"])
            wire_ratio = lossless_snap["actual_bytes"] / max(quant_wire, 1)
            lane_ratio = (quant_snap["quantized_lossless_bytes"]
                          / max(quant_snap["quantized_actual_bytes"], 1))
            quantized = {
                "identical": not q_mismatches,
                "mismatches": q_mismatches,
                "ranking_queries": len(ranking_queries),
                "wire": {
                    "lossless_inter_bytes": lossless_snap["actual_bytes"],
                    "quantized_inter_bytes": quant_wire,
                    "ratio": round(wire_ratio, 2),
                    "lane_ratio": round(lane_ratio, 2),
                },
                "window": {
                    "candidate_rows": quant_snap["quantized_candidate_rows"],
                    "window_rows": quant_snap["quantized_window_rows"],
                },
                "ok": bool(not q_mismatches and quant_wire
                           and quant_wire
                           < lossless_snap["actual_bytes"]),
            }

            # ---- model-vs-measured wire reconciliation (profiler) ----
            stats.reset()
            trace = profiled_trace_report(
                lambda: hier_ex.execute("mesh", "TopN(many, n=3)"), iters=3
            )
            model_snap = stats.snapshot()
            model_bytes = (model_snap["actual_bytes"]
                           + model_snap["intra_bytes"])
            reconciliation = {
                "model_bytes": model_bytes,
                "band": list(RECONCILE_BAND),
                "device_lane": trace.get("device_lane"),
            }
            tr = trace.get("transfer") or {}
            if tr.get("ok"):
                measured = tr["bytes"]
                rel = measured / max(model_bytes, 1)
                reconciliation.update({
                    "status": "measured",
                    "measured_bytes": measured,
                    "measured_over_model": round(rel, 3),
                    "within_band": RECONCILE_BAND[0] <= rel
                    <= RECONCILE_BAND[1],
                })
            else:
                # structured, documented skip (CPU-only hosts have no
                # transfer lanes in their traces) — never a crash, and
                # never silently dropped from the record
                reconciliation.update({
                    "status": "skipped",
                    "reason": tr.get("reason") or "no-trace",
                    "within_band": None,
                })
            recon_ok = reconciliation.get("within_band") is not False

            count_pql = "Count(Row(f=1))"
            hier_ex.execute("mesh", count_pql)  # warm the program
            dt, _ = _timed(lambda: hier_ex.execute("mesh", count_pql)[0])
        finally:
            holder.close()

    return {
        "n_devices": n_devices,
        "mesh_shape": list(mesh_groups(hier)),
        "n_shards": n_shards,
        "shapes": len(queries),
        "identical": not mismatches,
        "mismatches": mismatches,
        "cols_per_sec": round(n_shards * SHARD_WIDTH / dt),
        "row_topn_reduce_bytes": {
            "dense_equiv": row_dense, "actual": row_actual,
            "ratio": round(ratio, 1),
        },
        "reduce_bytes": all_snap,
        "quantized": quantized,
        "wire_reconciliation": reconciliation,
        "ok": not mismatches and ratio >= 4.0 and quantized["ok"]
        and recon_ok,
    }


def config_mesh() -> dict:
    """Mesh scaling gate: one subprocess per mesh size (2/4/8), each
    pinned to a virtual CPU platform (same env contract as mesh8),
    running config_mesh_inner. Aggregates the per-size records, writes
    MULTICHIP_r07.json next to the prior rounds, and is ``ok`` only when
    every size is byte-identical, clears the >=4x Row/TopN wire-byte
    bar, shows a measured quantized-ranking wire reduction with
    byte-identical results, and reconciles model-vs-measured wire bytes
    (or records a structured skip). Record shape is pinned by
    scripts/check_multichip_schema.py (tier-1
    tests/test_multichip_schema.py)."""
    import os
    import subprocess
    import sys

    env = {
        **os.environ,
        "JAX_PLATFORMS": "",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8").strip(),
    }
    records = []
    for n in (2, 4, 8):
        proc = subprocess.run(
            [sys.executable, __file__, "--mesh-inner", str(n)],
            env=env, capture_output=True, text=True, timeout=600,
        )
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        if proc.returncode != 0 or not lines:
            records.append({
                "n_devices": n, "ok": False,
                "error": (proc.stderr or "no output")[-500:],
            })
        else:
            records.append(json.loads(lines[-1]))
    out = {
        "config": "mesh",
        "metric": "hier_reduction_mesh_scaling",
        "meshes": records,
        "ok": all(r.get("ok") for r in records),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r07.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    return out


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--full", action="store_true",
                        help="billion-column scale (real TPU)")
    parser.add_argument(
        "--configs",
        default="1,2,3,4,5,mesh8,mesh,serving,mp_serving,multitenant,import,"
                "ingest,sync,hostpath,durability,tracing,profiling,chaos,"
                "scrub,autopilot,cdc,elastic",
    )
    parser.add_argument("--cpu-mesh-inner", action="store_true",
                        help=argparse.SUPPRESS)
    parser.add_argument("--mesh-inner", type=int, default=0,
                        help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.cpu_mesh_inner:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(config5_mesh_cpu8()), flush=True)
        return
    if args.mesh_inner:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(config_mesh_inner(args.mesh_inner)), flush=True)
        return
    n_shards = 954 if args.full else 4
    small = 2 if not args.full else 64
    runners = {
        "1": lambda: config1_star_trace(n_shards),
        "2": lambda: config2_taxi_topn_groupby(small),
        "3": lambda: config3_bsi_range_sum(small),
        "4": lambda: config4_time_quantum(1 if not args.full else 8),
        "5": lambda: config5_ssb_4way(n_shards),
        "serving": lambda: config_serving(
            n_shards=64 if args.full else 8,
            n_queries=1024 if args.full else 512,
            client_counts=(16, 64, 128) if args.full else (16, 64),
        ),
        "mp_serving": lambda: config_mp_serving(
            client_counts=(16, 64, 128) if args.full else (8, 32, 96),
            requests_per_client=160 if args.full else 80,
        ),
        "multitenant": lambda: config_multitenant(
            n_indexes=256 if args.full else 120,
            n_clients=16 if args.full else 8,
            requests_per_client=600 if args.full else 300,
        ),
        "readwrite": lambda: config_serving_readwrite(
            n_shards=32 if args.full else 8,
            n_ops=256 if args.full else 64,
        ),
        "import": lambda: config_import(
            n_shards=32 if args.full else 8,
            density=0.2 if args.full else 0.05,
        ),
        "ingest": lambda: config_ingest(
            n_shards=64 if args.full else 16,
            density=0.1 if args.full else 0.02,
        ),
        "sync": lambda: config_sync(
            n_fragments=384 if args.full else 192,
            n_divergent=64 if args.full else 32,
        ),
        "hostpath": lambda: config_hostpath(n_shards=8),
        "tracing": lambda: config_tracing(
            n_queries=512 if args.full else 256,
            repeats=5 if args.full else 4,
        ),
        "profiling": lambda: config_profiling(
            n_queries=768 if args.full else 512,
            repeats=5,
        ),
        "durability": lambda: config_durability(
            n_ops=1600 if args.full else 800,
            n_clients=32 if args.full else 16,
        ),
        "chaos": lambda: config_chaos(
            n_schedules=30 if args.full else 20,
            n_nodes=5 if args.full else 3,
            n_events=8 if args.full else 6,
        ),
        "scrub": lambda: config_scrub(
            n_chaos_schedules=4 if args.full else 2,
            queries_per_client=240 if args.full else 120,
        ),
        "autopilot": lambda: config_autopilot(
            hot_run_s=32.0 if args.full else 24.0,
            n_chaos_schedules=6 if args.full else 3,
        ),
        "cdc": lambda: config_cdc(
            n_chaos_schedules=6 if args.full else 3,
            read_s=8.0 if args.full else 5.0,
            n_clients=8 if args.full else 6,
        ),
        "elastic": lambda: config_elastic(
            n_clients=8 if args.full else 6,
            phase_s=6.0 if args.full else 4.0,
            n_chaos_schedules=6 if args.full else 3,
        ),
        "mesh": config_mesh,
    }
    floor = None  # lazy: touching the device backend can BLOCK when the
    # relay is down, and mesh8/serving don't need the floor measurement
    for c in args.configs.split(","):
        if c == "mesh8":
            _spawn_cpu_mesh_entry()
            continue
        out = runners[c]()
        if c in "12345":
            if floor is None:
                floor = dispatch_floor_ms()
            out["dispatch_floor_ms"] = floor
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
