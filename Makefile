# Test targets. Tier-1 (the CI gate) runs the whole suite minus
# @pytest.mark.slow stress cases; the qos-smoke target runs the serving
# QoS fault-injection suite in isolation (fast feedback while tuning
# admission/deadline/hedge knobs — see docs/QOS.md).

PYTEST := env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider

.PHONY: test test-slow qos-smoke

test:
	$(PYTEST) tests/ -m "not slow"

test-slow:
	$(PYTEST) tests/ -m slow

qos-smoke:
	$(PYTEST) tests/test_qos.py -m "not slow"
