# Test targets. Tier-1 (the CI gate) runs the whole suite minus
# @pytest.mark.slow stress cases; the qos-smoke target runs the serving
# QoS fault-injection suite in isolation (fast feedback while tuning
# admission/deadline/hedge knobs — see docs/QOS.md); ingest-smoke pushes
# a small CSV through `cli.py import` against an in-process server and
# exercises the routed-import suite (docs/INGEST.md); serving-smoke
# gates the host-path fast lane — keep-alive reuse via the
# connection-count oracle, and /internal/query-batch returning
# byte-identical results vs per-query dispatch (docs/OPERATIONS.md);
# sync-smoke gates the anti-entropy/resize fast path — batched-manifest
# repair byte-identical to the per-fragment path, the ≤2-RTT diff
# oracle, compression negotiation, and pacer bounds. bench-sync runs the
# seeded-divergence repair benchmark (control RTTs, wall, wire bytes).
# durability-smoke gates the write-path durability subsystem — group
# commit batching, torn-tail fuzz, the SIGKILL crash-recovery oracle
# (group + per-op modes), and the backup/restore round trip;
# bench-durability measures group vs per-op write QPS at 25% write
# fraction plus the crash and restore oracles (docs/OPERATIONS.md).

PYTEST := env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider

.PHONY: test test-slow qos-smoke ingest-smoke serving-smoke sync-smoke \
	durability-smoke obs-smoke cost-smoke chaos-smoke scrub-smoke \
	mp-smoke multitenant-smoke mesh-smoke autopilot-smoke bench-ingest \
	bench-serving bench-sync bench-durability bench-tracing \
	bench-profiling bench-chaos bench-scrub bench-mp bench-multitenant \
	bench-mesh bench-mesh-quantized bench-autopilot cdc-smoke bench-cdc \
	elastic-smoke bench-elastic hostpath-smoke bench-hostpath \
	ingest-kernel-smoke

test:
	$(PYTEST) tests/ -m "not slow"

test-slow:
	$(PYTEST) tests/ -m slow

qos-smoke:
	$(PYTEST) tests/test_qos.py -m "not slow"

ingest-smoke:
	$(PYTEST) tests/test_ingest.py -m "not slow"

serving-smoke:
	$(PYTEST) tests/test_fastlane.py -m "not slow"

sync-smoke:
	$(PYTEST) tests/test_sync_fastpath.py -m "not slow"

durability-smoke:
	$(PYTEST) tests/test_durability.py -m "not slow"

# obs-smoke: start a node, run a traced query, assert /debug/traces
# renders the span tree, /debug/queries shows-then-clears, and /metrics
# is stock-Prometheus parseable (docs/OBSERVABILITY.md)
obs-smoke:
	$(PYTEST) tests/test_tracing.py -m "not slow"

# cost-smoke: the query cost plane — PQL PROFILE single-node + 3-node
# stitching, /debug/tenants accounting, /debug/heatmap skew ranking,
# SLO burn-rate flips, knob roundtrips, and the stats quantile edge
# cases (docs/OBSERVABILITY.md)
cost-smoke:
	$(PYTEST) tests/test_cost.py tests/test_stats_quantiles.py -m "not slow"

# chaos-smoke: the partition-tolerance gate — fault-plane semantics,
# symmetric/asymmetric partition scenarios (minority read-only
# degradation, corroborated death, epoch fencing, rejoin) and one
# seeded chaos schedule through the four oracles
# (docs/OPERATIONS.md failure model)
chaos-smoke:
	$(PYTEST) tests/test_faults.py tests/test_partition.py -m "not slow"

# scrub-smoke: the storage-integrity gate — checksum sidecars +
# verified loads, quarantine at open, every-offset corruption fuzz,
# scrubber detection / read-repair / self-heal, ENOSPC degraded mode
# with auto-recovery, epoch-file hardening, restore read-back verify,
# and the CLI check verb (docs/OPERATIONS.md integrity runbook)
scrub-smoke:
	$(PYTEST) tests/test_integrity.py -m "not slow"

# mp-smoke: the multi-process serving tier — shm-ring framing/fuzz/
# backpressure/reclaim units, the end-to-end worker+owner contract
# (byte-identical responses, WAL ACK barrier under owner SIGKILL,
# tenant/trace attribution over the ring, degraded shedding, worker
# respawn, owner-restart re-handshake, single-process fallback), and
# one kill-a-worker chaos schedule (docs/OPERATIONS.md deployment
# shapes)
mp-smoke:
	$(PYTEST) tests/test_shmring.py tests/test_mpserve.py -m "not slow"

# multitenant-smoke: the skewed-traffic actuators — result-cache unit
# semantics (per-field invalidation, the fill-race version fence,
# heat-weighted eviction), read-your-writes through the HTTP cache path
# (sequential, concurrent, and across mp-serving workers' rings),
# PROFILE/ledger satellites, /debug/rescache + heatmap tier view,
# tiering demote/promote/hysteresis/pacing, and knob roundtrips
# (docs/OPERATIONS.md skewed traffic)
multitenant-smoke:
	$(PYTEST) tests/test_multitenant.py -m "not slow"

# mesh-smoke: the hierarchical reduction plane — byte-identical results
# vs single-device across mesh sizes 1/2/4/8 incl. 2-D groups x shards
# factorizations at non-divisible shard counts, the narrowed-lane wire
# model + PROFILE reduceBytes, the roaring row-frame roundtrip, the
# quantized candidate-ranking lane (error-bound/window properties +
# verify_quantized byte-identity + wire counters), the MULTICHIP record
# schema + hardened trace parse, the experimental-fallback multi-mesh
# serialization guard, and the query_raw vs cache-hit envelope mirror
# contract (docs/OPERATIONS.md multi-chip mesh)
mesh-smoke:
	$(PYTEST) tests/test_mesh_reduction.py tests/test_envelope_contract.py \
		tests/test_multichip_schema.py -m "not slow"

# autopilot-smoke: the placement plane — planner properties (uniform ⇒
# zero moves, hot-spot drain, dwell freezing), placement-table fencing/
# persistence/fallback byte-identity vs the hash ring, the end-to-end
# forced-move resize, and the knob-parity contract across every config
# surface (TOML / env / snake / kebab / generated template)
autopilot-smoke:
	$(PYTEST) tests/test_autopilot.py tests/test_config_parity.py \
		-m "not slow"

# cdc-smoke: the CDC backbone — WAL tail cursor semantics (resume,
# rotation survival, segment-GC pinning, 410 on truncation AND on
# unknown-cursor restart detection), frame codec torn-frame fuzz,
# follower attach/apply/resync convergence, the staleness QoS header,
# and restore --as-of point-in-time bit-exactness
cdc-smoke:
	$(PYTEST) tests/test_cdc.py -m "not slow"

# elastic-smoke: the membership plane — graceful drain state machine
# (shed-writes latch, cursor handoff, clean leave, coordinator-failover
# resume), heat-ordered byte-verified join warm-up, the range-keyed
# placement table (byte-identity fallback, mixed-version gossip,
# persistence round-trip), sub-shard split/merge planning, and the
# autopilot/drain mutual-exclusion contract (docs/OPERATIONS.md
# elastic operations)
elastic-smoke:
	$(PYTEST) tests/test_elastic.py tests/test_placement_ranges.py \
		-m "not slow"

# hostpath-smoke: the vectorized roaring kernel layer — byte-identity
# property tests (random + adversarial + corruption-fuzz fragments) for
# every kernel vs the per-container reference walks, PROFILE
# container-scan accounting parity, and the static lint that keeps
# per-container python loops out of the rewired host paths
# (docs/OPERATIONS.md host-path kernels)
hostpath-smoke:
	$(PYTEST) tests/test_roaring_kernels.py tests/test_hostpath_lint.py \
		-m "not slow"
	env JAX_PLATFORMS=cpu python scripts/check_hostpath_loops.py

# ingest-kernel-smoke: the write-path fast lane — byte-identity
# property/fuzz tests for the whole-batch merge kernels vs the retired
# per-container loop (randomized + adversarial batches, mutex/BSI merge
# rules, batched membership probes, WAL-replay equivalence), plus the
# host-path lint over the write-side consumer modules
# (docs/OPERATIONS.md write-path fast lane)
ingest-kernel-smoke:
	$(PYTEST) tests/test_merge_kernels.py tests/test_hostpath_lint.py \
		-m "not slow"
	env JAX_PLATFORMS=cpu python scripts/check_hostpath_loops.py

bench-ingest:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs ingest

bench-serving:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs serving

bench-sync:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs sync

bench-durability:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs durability

bench-tracing:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs tracing

# overhead gate for the query cost plane: profile-off <= 1%,
# profile-on <= 10% vs the bare fast-lane plateau
bench-profiling:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs profiling

# >=20 randomized partition/kill/heal schedules against a 3-node
# cluster under mixed read+write load, gated on the four
# partition-safety oracles (zero lost acked writes, no non-quorum
# deletion, <=1 coordinator per epoch, byte-identical replicas)
bench-chaos:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs chaos

# multi-process serving scaling gate: single-process fast-lane plateau
# vs 1/2/4 SO_REUSEPORT-worker plateaus (subprocess clients, best-of-3
# interleaved), byte-identical responses across shapes, ring round-trip
# quantiles, and the kill-a-worker chaos schedule
bench-mp:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs mp_serving

# host-path gate: the three rewired roaring host paths (row decode,
# scrub digesting, sync manifest diff) timed against in-bench copies of
# the retired per-container loops — byte-identical and >= 2x each —
# plus the Executor.submit host-cost number
bench-hostpath:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs hostpath

# storage-integrity gate: scrubber serving overhead >= 0.97x off,
# detection-latency bound, the corruption-heal + ENOSPC oracles, and
# randomized storage-fault chaos schedules
bench-scrub:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs scrub

# skewed-traffic gate: 120 indexes under Zipf traffic with QoS quotas
# active — hot-tenant p99 within 1.3x the single-index plateau, bounded
# cold-tenant tail, >50% result-cache hit rate on the Zipf hot set,
# read-your-writes through the cache path (single-process + mp-serving),
# and a heat-driven demote/promote cycle with zero serving errors
bench-multitenant:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs multitenant

# multi-chip reduction-plane gate: per-mesh-size (2/4/8, 2-D
# factorizations) subprocesses over the canonical 20 dryrun shapes —
# byte-identical vs the dense 1-D path, >=4x reduction-lane wire-byte
# reduction on Row/TopN, a measured quantized-ranking net wire
# reduction with byte-identical results (verify_quantized), and
# model-vs-measured wire reconciliation (or a structured skip on
# CPU-only hosts); records written to MULTICHIP_r07.json, shape pinned
# by scripts/check_multichip_schema.py
bench-mesh:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs mesh
	python scripts/check_multichip_schema.py

# just the quantized-ranking leg of the gate, per mesh size: the 8-bit
# lane's byte-identity certification + wire delta without the full
# record rewrite (docs/OPERATIONS.md quantized candidate ranking)
bench-mesh-quantized:
	env JAX_PLATFORMS= PALLAS_AXON_POOL_IPS= \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python bench_suite.py --mesh-inner 2
	env JAX_PLATFORMS= PALLAS_AXON_POOL_IPS= \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python bench_suite.py --mesh-inner 4
	env JAX_PLATFORMS= PALLAS_AXON_POOL_IPS= \
		XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python bench_suite.py --mesh-inner 8

# autopilot placement-plane gate: a 3-process cluster under
# hot-spotted Zipf traffic — tail p99 recovers to <=1.5x the
# uniform-placement p99 with zero client errors and zero lost acked
# writes, autopilot-active chaos schedules trip none of the five
# oracles, and the kill-switch-off control cluster stays byte-identical
# to hash placement
bench-autopilot:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs autopilot

# CDC backbone gate: chaos schedules with a live out-of-cluster mirror
# (byte-identical to n0 after heal, restarts driving the
# unknown-cursor 410 → resync path), subprocess follower read scaling
# >= 1.7x primary-alone with staleness p99 under the 1 s budget, the
# X-Pilosa-Max-Staleness gate live, and every WAL seq between two
# backup generations restoring bit-exactly via restore --as-of
bench-cdc:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs cdc

# elastic membership gate: scripted 3->5->3 grow/shrink under live Zipf
# traffic with a ledgered writer (zero lost acked writes, p99
# continuity vs the steady-state plateau), a hot single shard recovered
# by a sub-shard range split spreading reads across >=2 owners, and
# chaos schedules that kill/partition mid-drain without tripping any
# oracle
bench-elastic:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs elastic
