# Test targets. Tier-1 (the CI gate) runs the whole suite minus
# @pytest.mark.slow stress cases; the qos-smoke target runs the serving
# QoS fault-injection suite in isolation (fast feedback while tuning
# admission/deadline/hedge knobs — see docs/QOS.md); ingest-smoke pushes
# a small CSV through `cli.py import` against an in-process server and
# exercises the routed-import suite (docs/INGEST.md).

PYTEST := env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider

.PHONY: test test-slow qos-smoke ingest-smoke bench-ingest

test:
	$(PYTEST) tests/ -m "not slow"

test-slow:
	$(PYTEST) tests/ -m slow

qos-smoke:
	$(PYTEST) tests/test_qos.py -m "not slow"

ingest-smoke:
	$(PYTEST) tests/test_ingest.py -m "not slow"

bench-ingest:
	env JAX_PLATFORMS=cpu python bench_suite.py --configs ingest
