#!/usr/bin/env python
"""Static lint: no new per-container host loops in kernel-consumer modules.

PR 18 moved every whole-fragment host-path decode (row decode, block
digests, sync manifest materialization, scrub verification, CDC encode)
onto the batched numpy kernels in ``pilosa_tpu/roaring/kernels.py``.
A per-container ``for`` loop that walks container payloads in any of
those modules re-introduces the exact Python-envelope cost the kernel
layer retired — and it does so silently, because the output stays
byte-identical while throughput quietly regresses.

This lint walks the AST of each consumer module and fails on any loop
or comprehension whose source touches a container-walk marker
(``.container(``, ``._containers``, ``.lows()``, ``contains_low``,
``dense_range_words32``). Point probes that are cheaper than a kernel
dispatch are pinned in ALLOWLIST by (module, enclosing function);
adding an entry is a reviewed decision, not a default.

Run from the repo root:  python scripts/check_hostpath_loops.py
Exit 0 = clean, 1 = violations (one line each), 2 = usage error.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

# The consumer surfaces named by the kernel layers' contracts: the
# five read-side modules from PR 18 plus the write-path consumers the
# merge-kernel rewire (roaring/merge_kernels.py) cleaned — bulk import
# routing, WAL replay, and the routed PQL write path. bitmap.py is
# listed too: its only sanctioned container loops are the pinned
# reference/point-probe functions below, so a per-container merge loop
# cannot quietly grow back beside the kernel dispatcher.
MODULES = [
    "pilosa_tpu/storage/fragment.py",
    "pilosa_tpu/storage/integrity.py",
    "pilosa_tpu/parallel/scrub.py",
    "pilosa_tpu/parallel/cluster.py",
    "pilosa_tpu/cdc/tailer.py",
    "pilosa_tpu/roaring/bitmap.py",
    "pilosa_tpu/server/api.py",
    "pilosa_tpu/storage/wal.py",
    "pilosa_tpu/parallel/cluster_exec.py",
]

# Source substrings that mean "this code is touching container
# internals" — a loop over any of them is a per-container walk.
MARKERS = (
    ".container(",
    "._containers",
    ".lows()",
    "contains_low",
    "dense_range_words32",
)

# (module, enclosing function) pairs allowed to keep a container loop.
ALLOWLIST = {
    # single-position membership probe over candidate keys: O(16)
    # metadata lookups, strictly cheaper than flattening the fragment
    ("pilosa_tpu/storage/fragment.py", "rows_containing"),
    # bitmap.py's sanctioned loops: container assembly/metadata walks
    # with no batched equivalent, point probes cheaper than a kernel
    # dispatch, and _merge_loop — the retired write loop kept verbatim
    # as the small-batch path and the merge kernels' byte-identity
    # reference (tests/test_merge_kernels.py diffs against it)
    ("pilosa_tpu/roaring/bitmap.py", "from_ids"),
    ("pilosa_tpu/roaring/bitmap.py", "count"),
    ("pilosa_tpu/roaring/bitmap.py", "count_range"),
    ("pilosa_tpu/roaring/bitmap.py", "dense_range_words32"),
    ("pilosa_tpu/roaring/bitmap.py", "row_member"),
    ("pilosa_tpu/roaring/bitmap.py", "_merge_loop"),
    ("pilosa_tpu/roaring/bitmap.py", "__eq__"),
}

_LOOP_NODES = (
    ast.For,
    ast.AsyncFor,
    ast.While,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _check_module(root: Path, rel: str) -> list[str]:
    path = root / rel
    src = path.read_text()
    tree = ast.parse(src, filename=rel)
    problems: list[str] = []

    def visit(node: ast.AST, func: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, _LOOP_NODES):
            seg = ast.get_source_segment(src, node) or ""
            hit = next((m for m in MARKERS if m in seg), None)
            if hit is not None and (rel, func) not in ALLOWLIST:
                problems.append(
                    f"{rel}:{node.lineno}: per-container loop in "
                    f"{func}() touches {hit!r} — use the batched "
                    f"kernels in pilosa_tpu/roaring/kernels.py "
                    f"(or pin an ALLOWLIST entry with review)"
                )
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, "<module>")
    return problems


def main(argv: list[str]) -> int:
    root = Path(argv[1]) if len(argv) > 1 else Path(__file__).resolve().parents[1]
    if not root.is_dir():
        print(f"check_hostpath_loops: not a directory: {root}", file=sys.stderr)
        return 2
    problems: list[str] = []
    for rel in MODULES:
        if not (root / rel).exists():
            print(f"check_hostpath_loops: missing module: {rel}", file=sys.stderr)
            return 2
        problems.extend(_check_module(root, rel))
    for p in problems:
        print(p)
    if problems:
        print(f"check_hostpath_loops: {len(problems)} violation(s)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
