#!/usr/bin/env python
"""Pin the MULTICHIP record schema (bench_suite ``mesh`` config).

The mesh bench's per-size records gate three contracts — byte-identical
results, the hierarchical wire-byte ratio, and (since r07) the
quantized-ranking wire reduction + model-vs-measured reconciliation.
Downstream tooling greps these records, so shape drift is a silent
break: this script validates the committed MULTICHIP_r07.json (and any
path given on the command line) field-by-field and exits nonzero with
one line per problem. tests/test_multichip_schema.py runs it in tier-1
against the committed record and synthetic good/bad documents.

Usage: python scripts/check_multichip_schema.py [record.json ...]
"""

from __future__ import annotations

import json
import os
import sys

NUMERIC = (int, float)

# (field name, required types) for each per-mesh record
MESH_FIELDS = [
    ("n_devices", int),
    ("mesh_shape", list),
    ("n_shards", int),
    ("shapes", int),
    ("identical", bool),
    ("mismatches", list),
    ("cols_per_sec", NUMERIC),
    ("row_topn_reduce_bytes", dict),
    ("reduce_bytes", dict),
    ("quantized", dict),
    ("wire_reconciliation", dict),
    ("ok", bool),
]

REDUCE_BYTES_FIELDS = [
    "dispatches", "hier_dispatches", "dense_bytes", "actual_bytes",
    "intra_bytes", "row_gathers", "row_dense_bytes", "row_actual_bytes",
    # the quantized-ranking counters ride the same snapshot (and surface
    # on /metrics as dist_reduce_quantized_*)
    "quantized_dispatches", "quantized_actual_bytes",
    "quantized_lossless_bytes", "quantized_window_rows",
    "quantized_candidate_rows",
]

QUANT_WIRE_FIELDS = [
    "lossless_inter_bytes", "quantized_inter_bytes", "ratio", "lane_ratio",
]

RECON_STATUSES = {"measured", "skipped"}


def _typename(t) -> str:
    if isinstance(t, tuple):
        return "/".join(x.__name__ for x in t)
    return t.__name__


def _need(out, where, obj, field, types=NUMERIC):
    if field not in obj:
        out.append(f"{where}: missing {field!r}")
        return None
    v = obj[field]
    # bool is an int subclass; only accept it where asked for
    if isinstance(v, bool) and types not in (bool,):
        out.append(f"{where}.{field}: expected {_typename(types)}, "
                   f"got bool")
        return None
    if not isinstance(v, types):
        out.append(f"{where}.{field}: expected {_typename(types)}, "
                   f"got {type(v).__name__}")
        return None
    return v


def check_record(rec: dict, where: str = "mesh") -> list[str]:
    """Validate ONE per-mesh record; returns a list of problem strings
    (empty = conforming)."""
    out: list[str] = []
    if not isinstance(rec, dict):
        return [f"{where}: record is {type(rec).__name__}, not dict"]
    for field, types in MESH_FIELDS:
        _need(out, where, rec, field, types)

    rtb = rec.get("row_topn_reduce_bytes")
    if isinstance(rtb, dict):
        for f in ("dense_equiv", "actual", "ratio"):
            _need(out, f"{where}.row_topn_reduce_bytes", rtb, f)

    rb = rec.get("reduce_bytes")
    if isinstance(rb, dict):
        for f in REDUCE_BYTES_FIELDS:
            _need(out, f"{where}.reduce_bytes", rb, f)

    q = rec.get("quantized")
    if isinstance(q, dict):
        _need(out, f"{where}.quantized", q, "identical", bool)
        _need(out, f"{where}.quantized", q, "mismatches", list)
        _need(out, f"{where}.quantized", q, "ranking_queries", int)
        _need(out, f"{where}.quantized", q, "ok", bool)
        wire = _need(out, f"{where}.quantized", q, "wire", dict)
        if wire is not None:
            for f in QUANT_WIRE_FIELDS:
                _need(out, f"{where}.quantized.wire", wire, f)
        window = _need(out, f"{where}.quantized", q, "window", dict)
        if window is not None:
            for f in ("candidate_rows", "window_rows"):
                _need(out, f"{where}.quantized.window", window, f)

    wr = rec.get("wire_reconciliation")
    if isinstance(wr, dict):
        status = _need(out, f"{where}.wire_reconciliation", wr,
                       "status", str)
        _need(out, f"{where}.wire_reconciliation", wr, "band", list)
        _need(out, f"{where}.wire_reconciliation", wr, "model_bytes")
        if status is not None and status not in RECON_STATUSES:
            out.append(f"{where}.wire_reconciliation.status: {status!r} "
                       f"not in {sorted(RECON_STATUSES)}")
        if status == "measured":
            _need(out, f"{where}.wire_reconciliation", wr,
                  "measured_bytes")
            _need(out, f"{where}.wire_reconciliation", wr,
                  "within_band", bool)
        elif status == "skipped":
            # the structured-skip contract: a reason string, never a
            # bare failure
            _need(out, f"{where}.wire_reconciliation", wr, "reason", str)
    return out


def check_document(doc: dict) -> list[str]:
    """Validate a whole MULTICHIP_r07-style document."""
    out: list[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not dict"]
    for field, types in [("config", str), ("metric", str),
                         ("meshes", list), ("ok", bool)]:
        _need(out, "doc", doc, field, types)
    meshes = doc.get("meshes")
    if isinstance(meshes, list):
        if not meshes:
            out.append("doc.meshes: empty")
        for i, rec in enumerate(meshes):
            out.extend(check_record(rec, f"meshes[{i}]"))
    return out


def main(argv: list[str]) -> int:
    paths = argv or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MULTICHIP_r07.json")]
    rc = 0
    for path in paths:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"{path}: unreadable: {e}")
            rc = 1
            continue
        problems = check_document(doc)
        for p in problems:
            print(f"{path}: {p}")
        if problems:
            rc = 1
        else:
            print(f"{path}: ok ({len(doc['meshes'])} mesh records)")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
