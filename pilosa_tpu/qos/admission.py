"""Admission control: a token gate in front of the serving pipeline.

Nothing may queue unboundedly between an HTTP request and the executor:
under overload the wave dispatcher (server/pipeline.py) convoys and every
queued request pays the whole backlog's dispatch floors. The gate bounds
concurrent in-flight queries — globally and per tenant (header-derived) —
and sheds the excess with 429 + Retry-After instead of letting the queue
grow. Shedding is strictly cheaper than queueing here: a shed client
retries after backoff against a drained server, a queued one waits out a
convoy and usually times out anyway.

Only edge requests are gated: internal fan-out hops (``remote=true``)
were admitted once at their root — shedding them mid-query would fail an
already-admitted request and amplify load with client retries.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class AdmissionError(Exception):
    """Request shed at admission (HTTP 429). ``retry_after`` is the
    client backoff hint in seconds (Retry-After header)."""

    def __init__(self, message: str, retry_after: float = 1.0,
                 tenant: str = "default"):
        super().__init__(message)
        self.retry_after = retry_after
        self.tenant = tenant


class AdmissionSlot:
    """One admitted request's token; release exactly once."""

    __slots__ = ("_controller", "tenant", "_released")

    def __init__(self, controller: "AdmissionController", tenant: str):
        self._controller = controller
        self.tenant = tenant
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._controller._release(self.tenant)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    """Concurrent in-flight gate with per-tenant quotas.

    ``max_inflight`` bounds the whole node (0 = unlimited, gate off);
    ``tenant_max`` bounds one tenant (0 = inherit the global limit), so a
    single hot tenant cannot starve the rest even when the node as a
    whole has headroom. In-flight counts are tracked either way, so
    /metrics shows queue pressure before an operator turns the gate on.
    """

    def __init__(self, max_inflight: int = 0, tenant_max: int = 0,
                 retry_after: float = 1.0, stats=None):
        self.max_inflight = max_inflight
        self.tenant_max = tenant_max
        self.retry_after = retry_after
        self._stats = stats
        self._lock = threading.Lock()
        self._inflight = 0
        self._by_tenant: dict[str, int] = defaultdict(int)
        self.admitted = 0
        self.shed = 0

    def admit(self, tenant: str = "default") -> AdmissionSlot:
        """Take one in-flight token or raise AdmissionError (→ 429)."""
        with self._lock:
            if 0 < self.max_inflight <= self._inflight:
                self.shed += 1
                reason = (f"server at admission limit "
                          f"({self._inflight}/{self.max_inflight} in flight)")
            else:
                limit = self.tenant_max or self.max_inflight
                if 0 < limit <= self._by_tenant[tenant]:
                    self.shed += 1
                    reason = (f"tenant {tenant!r} at admission limit "
                              f"({self._by_tenant[tenant]}/{limit} in flight)")
                else:
                    self._inflight += 1
                    self._by_tenant[tenant] += 1
                    self.admitted += 1
                    return AdmissionSlot(self, tenant)
        if self._stats is not None:
            self._stats.count("qos_shed", 1, {"tenant": tenant})
        raise AdmissionError(reason, retry_after=self.retry_after,
                             tenant=tenant)

    def _release(self, tenant: str) -> None:
        with self._lock:
            self._inflight -= 1
            n = self._by_tenant[tenant] - 1
            if n <= 0:
                self._by_tenant.pop(tenant, None)
            else:
                self._by_tenant[tenant] = n

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def metrics(self) -> dict:
        with self._lock:
            return {
                "admitted_total": self.admitted,
                "shed_total": self.shed,
                "inflight": self._inflight,
            }
