"""Hedged replica reads with budgets, and per-node circuit breaking.

A replicated fragment read that routes to a slow or freshly-dead node
stalls for the full transport timeout before the error-path replica
fallback fires (parallel/cluster_exec.py). Hedging converts that tail
into ~p95: when the primary has not answered within the p95-tracked
hedge delay, the same shard read fires at the next replica and the first
answer wins. Two safety rails keep hedging from amplifying an overload:

- a global hedge BUDGET (hedges ≤ ``budget_fraction`` of primary reads,
  "The Tail at Scale" §Hedged requests) — when the whole cluster is slow,
  hedging everything would double the load precisely when there is no
  spare capacity;
- per-node CIRCUIT BREAKING on repeated transport faults — a dead node's
  connect timeouts stop being paid per-query once its breaker opens, and
  a half-open probe discovers recovery without a thundering herd.

Transport invariant (serving fast lane): a hedge leg always rides its
OWN pooled connection — the connection pool's checkout is exclusive
(parallel/connpool.py), so the duplicate read can never queue behind, or
share a socket with, the very primary it is racing. Hedge and fallback
legs also bypass the remote wave batcher (cluster_exec._remote_query:
depth ≥ 1 goes direct) for the same reason. Pinned by
tests/test_fastlane.py::test_concurrent_requests_use_distinct_connections.
"""

from __future__ import annotations

import threading
import time

# Breaker states.
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class LatencyTracker:
    """Ring buffer of recent primary-read latencies; p95 over the window.

    A fixed window (not decaying buckets) is enough here: the quantile
    steers only the hedge delay, and a 256-sample window re-centers
    within a few seconds of traffic at serving rates.
    """

    def __init__(self, size: int = 256):
        self._size = size
        self._samples: list[float] = []
        self._next = 0
        self._lock = threading.Lock()

    def add(self, seconds: float) -> None:
        with self._lock:
            if len(self._samples) < self._size:
                self._samples.append(seconds)
            else:
                self._samples[self._next] = seconds
                self._next = (self._next + 1) % self._size

    def count(self) -> int:
        with self._lock:
            return len(self._samples)

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if not self._samples:
                return None
            ordered = sorted(self._samples)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]


class HedgePolicy:
    """When and whether to hedge: p95-tracked delay + global budget."""

    # Samples before the tracked p95 replaces the configured initial
    # delay — quantiles over a handful of samples whipsaw the delay.
    MIN_SAMPLES = 20

    def __init__(self, initial_delay: float = 0.25,
                 budget_fraction: float = 0.05,
                 min_delay: float = 0.005, tracker_size: int = 256):
        self.initial_delay = initial_delay
        self.budget_fraction = budget_fraction
        self.min_delay = min_delay
        self.tracker = LatencyTracker(tracker_size)
        self._lock = threading.Lock()
        self.primaries = 0
        self.hedges = 0
        self.wins = 0
        self.budget_denied = 0

    def delay(self) -> float:
        """Hedge trigger delay: tracked p95 once warmed up, else the
        configured initial delay; floored so a microsecond-fast backend
        cannot hedge every single read."""
        p95 = (self.tracker.quantile(0.95)
               if self.tracker.count() >= self.MIN_SAMPLES else None)
        return max(self.min_delay, p95 if p95 is not None
                   else self.initial_delay)

    def note_primary(self) -> None:
        with self._lock:
            self.primaries += 1

    def record(self, seconds: float) -> None:
        self.tracker.add(seconds)

    def try_hedge(self) -> bool:
        """Spend one unit of hedge budget, or refuse (≤ fraction of
        primary reads may hedge; the +1 seat lets the very first slow
        read hedge instead of dividing by zero)."""
        with self._lock:
            if self.budget_fraction <= 0:
                self.budget_denied += 1
                return False
            if self.hedges + 1 > self.budget_fraction * self.primaries + 1:
                self.budget_denied += 1
                return False
            self.hedges += 1
            return True

    def note_win(self) -> None:
        with self._lock:
            self.wins += 1

    def metrics(self) -> dict:
        with self._lock:
            return {
                "hedges_total": self.hedges,
                "hedge_wins_total": self.wins,
                "hedge_budget_denied_total": self.budget_denied,
            }


class CircuitBreaker:
    """Per-node breaker: closed → open after ``threshold`` consecutive
    transport faults; open → half-open after ``cooldown`` seconds (one
    probe allowed); half-open → closed on success, → open on failure."""

    def __init__(self, threshold: int = 5, cooldown: float = 5.0):
        self.threshold = threshold
        self.cooldown = cooldown
        self._lock = threading.Lock()
        self.state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self.opened_total = 0

    def allow(self) -> bool:
        """May a request be sent to this node right now? Open returns
        False (callers route around); after the cooldown exactly one
        caller gets True as the half-open probe."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown:
                    self.state = HALF_OPEN
                    self._probing = True
                    return True
                return False
            # HALF_OPEN: one probe in flight; hold other traffic
            if not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state == OPEN:
                # a stale pre-open in-flight success: the node flapped
                # after this request departed, so it says nothing about
                # health NOW — only the half-open probe may close an
                # open breaker, or the cooldown discipline is lost
                return
            self.state = CLOSED
            self._failures = 0
            self._probing = False

    def record_inconclusive(self) -> None:
        """The request ended with no verdict on the NODE — its deadline
        expired, or a deterministic 4xx every replica would repeat.
        Releases a half-open probe seat WITHOUT moving the state: if the
        seat were never released, allow() would return False forever and
        the node would be locked out until process restart."""
        with self._lock:
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self.state == HALF_OPEN or self._failures >= self.threshold:
                if self.state != OPEN:
                    self.opened_total += 1
                self.state = OPEN
                self._opened_at = time.monotonic()
                self._probing = False


class ServingQos:
    """The serving-QoS bundle one node carries: admission gate, hedge
    policy, per-node breakers, and the deadline-expiry counter. Wired by
    Server.open from ServerConfig; a default instance (gate off, hedging
    on with stock knobs) backs bare ``API()`` construction so every code
    path can assume it exists."""

    def __init__(self, max_inflight: int = 0, tenant_max: int = 0,
                 retry_after: float = 1.0,
                 hedge_delay: float = 0.25, hedge_budget: float = 0.05,
                 breaker_threshold: int = 5, breaker_cooldown: float = 5.0,
                 stats=None):
        from pilosa_tpu.qos.admission import AdmissionController

        self.admission = AdmissionController(
            max_inflight=max_inflight, tenant_max=tenant_max,
            retry_after=retry_after, stats=stats,
        )
        self.hedge = HedgePolicy(initial_delay=hedge_delay,
                                 budget_fraction=hedge_budget)
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self.deadline_expired = 0

    def breaker(self, node_id: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(node_id)
            if br is None:
                br = self._breakers[node_id] = CircuitBreaker(
                    self.breaker_threshold, self.breaker_cooldown
                )
            return br

    def note_deadline_expired(self) -> None:
        with self._lock:
            self.deadline_expired += 1

    def metrics(self) -> dict:
        """Flat series for /metrics — all keys present from scrape one so
        rate() windows never see a series appear mid-flight."""
        out = self.admission.metrics()
        out.update(self.hedge.metrics())
        with self._lock:
            out["deadline_expired_total"] = self.deadline_expired
            breakers = list(self._breakers.values())
        out["breaker_opened_total"] = sum(b.opened_total for b in breakers)
        out["breaker_open"] = sum(1 for b in breakers if b.state == OPEN)
        return out
