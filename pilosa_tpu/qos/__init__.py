"""Serving QoS: admission control, deadline propagation, hedged reads.

The request lifecycle (docs/QOS.md): a query is ADMITTED (or shed 429)
at the HTTP edge, carries a DEADLINE through every layer and every
inter-node hop, and replicated remote reads are HEDGED to a sibling
replica when the primary outlives the p95-tracked hedge delay — all
within a global hedge budget and behind per-node circuit breakers.
"""

from pilosa_tpu.qos.admission import (
    AdmissionController,
    AdmissionError,
    AdmissionSlot,
)
from pilosa_tpu.qos.deadline import (
    DEADLINE_HEADER,
    STALENESS_HEADER,
    TENANT_HEADER,
    Deadline,
    DeadlineExceeded,
)
from pilosa_tpu.qos.hedge import (
    CircuitBreaker,
    HedgePolicy,
    LatencyTracker,
    ServingQos,
)
from pilosa_tpu.qos.slo import SLOEngine, SLOObjective

# Canonical ``qos_shed`` reason label for writes refused on a draining
# node (elastic plane): the target of an in-flight drain sheds writes
# 503 while its shard groups move off, so no acked write can land on a
# fragment mid-departure; reads keep serving the tail
# (docs/OBSERVABILITY.md, docs/OPERATIONS.md elastic operations).
SHED_REASON_DRAINING = "draining"

__all__ = [
    "AdmissionController",
    "AdmissionError",
    "AdmissionSlot",
    "CircuitBreaker",
    "DEADLINE_HEADER",
    "STALENESS_HEADER",
    "TENANT_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "HedgePolicy",
    "LatencyTracker",
    "SHED_REASON_DRAINING",
    "SLOEngine",
    "SLOObjective",
    "ServingQos",
]
