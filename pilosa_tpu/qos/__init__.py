"""Serving QoS: admission control, deadline propagation, hedged reads.

The request lifecycle (docs/QOS.md): a query is ADMITTED (or shed 429)
at the HTTP edge, carries a DEADLINE through every layer and every
inter-node hop, and replicated remote reads are HEDGED to a sibling
replica when the primary outlives the p95-tracked hedge delay — all
within a global hedge budget and behind per-node circuit breakers.
"""

from pilosa_tpu.qos.admission import (
    AdmissionController,
    AdmissionError,
    AdmissionSlot,
)
from pilosa_tpu.qos.deadline import (
    DEADLINE_HEADER,
    STALENESS_HEADER,
    TENANT_HEADER,
    Deadline,
    DeadlineExceeded,
)
from pilosa_tpu.qos.hedge import (
    CircuitBreaker,
    HedgePolicy,
    LatencyTracker,
    ServingQos,
)
from pilosa_tpu.qos.slo import SLOEngine, SLOObjective

__all__ = [
    "AdmissionController",
    "AdmissionError",
    "AdmissionSlot",
    "CircuitBreaker",
    "DEADLINE_HEADER",
    "STALENESS_HEADER",
    "TENANT_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "HedgePolicy",
    "LatencyTracker",
    "SLOEngine",
    "SLOObjective",
    "ServingQos",
]
