"""SLO engine: declarative latency/error objectives with multi-window
burn-rate evaluation.

Objectives are declared in ServerConfig (``slo-objectives``) as compact
specs:

    "reads:latency:100ms:0.99"   99% of queries complete under 100 ms
    "avail:errors:0.999"         99.9% of queries succeed (no 5xx)

Every edge query feeds one (good | bad) event per objective into
1-second time buckets; burn rates are computed lazily at scrape over the
configured windows (``slo-windows``, default 300s and 3600s — the classic
fast/slow pair), so a latency burst moves the fast-window gauge within
one evaluation window with no sweeper thread. Burn rate is the standard
definition: (bad fraction over the window) / (1 - target) — 1.0 means
consuming error budget exactly at the sustainable rate, >1 means the
budget will be exhausted early. ``slo_breach{objective=}`` is 1 when
EVERY window burns above 1.0 (the multi-window AND that suppresses
blips), exported beside per-window ``slo_burn_rate`` gauges and served
as JSON at ``GET /debug/slo``.
"""

from __future__ import annotations

import threading
import time

DEFAULT_WINDOWS_S = (300.0, 3600.0)

# One duration grammar for every knob: the SLO specs live in the SAME
# TOML file as their sibling knobs and must not reject syntax the
# siblings accept (utils/durations.py is the single implementation —
# server._parse_duration delegates to it too).
from pilosa_tpu.utils.durations import parse_duration as _parse_duration_s


class SLOObjective:
    """One declarative objective. ``kind`` is ``latency`` (good = no
    error AND under threshold) or ``errors`` (good = no server error)."""

    __slots__ = ("name", "kind", "threshold_s", "target")

    def __init__(self, name: str, kind: str, target: float,
                 threshold_s: float | None = None):
        if kind not in ("latency", "errors"):
            raise ValueError(
                f"objective {name!r}: kind must be latency or errors, "
                f"got {kind!r}"
            )
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"objective {name!r}: target must be in (0, 1), "
                f"got {target!r}"
            )
        if kind == "latency" and (threshold_s is None or threshold_s <= 0):
            raise ValueError(
                f"objective {name!r}: latency objectives need a positive "
                "threshold"
            )
        self.name = name
        self.kind = kind
        self.threshold_s = threshold_s
        self.target = target

    def is_bad(self, latency_s: float, error: bool) -> bool:
        if self.kind == "errors":
            return error
        return error or latency_s > self.threshold_s

    @classmethod
    def parse(cls, spec: str) -> "SLOObjective":
        """``name:latency:<threshold>:<target>`` or
        ``name:errors:<target>`` — raises ValueError on malformed specs
        so a typo fails at config load, not silently at runtime."""
        parts = [p.strip() for p in str(spec).split(":")]
        if len(parts) == 4 and parts[1] == "latency":
            return cls(parts[0], "latency", float(parts[3]),
                       threshold_s=_parse_duration_s(parts[2]))
        if len(parts) == 3 and parts[1] == "errors":
            return cls(parts[0], "errors", float(parts[2]))
        raise ValueError(
            f"invalid slo objective {spec!r} (want "
            "'name:latency:100ms:0.99' or 'name:errors:0.999')"
        )

    def to_json(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.threshold_s is not None:
            out["thresholdMs"] = round(self.threshold_s * 1e3, 3)
        return out


class SLOEngine:
    """Bucketed good/bad event stream + lazy multi-window burn rates."""

    def __init__(self, objectives: list[SLOObjective] | None = None,
                 windows_s=DEFAULT_WINDOWS_S):
        self.objectives = list(objectives or [])
        self.windows_s = tuple(float(w) for w in windows_s) or \
            DEFAULT_WINDOWS_S
        if any(w <= 0 for w in self.windows_s):
            raise ValueError("slo windows must be positive seconds")
        self._lock = threading.Lock()
        # per objective: {epoch_second: [total, bad]}
        self._buckets: list[dict[int, list]] = [
            {} for _ in self.objectives
        ]
        self.events_total = 0

    @classmethod
    def from_config(cls, objective_specs, windows_spec=None) -> "SLOEngine":
        objectives = [SLOObjective.parse(s) for s in (objective_specs or [])]
        windows = (tuple(_parse_duration_s(w) for w in windows_spec)
                   if windows_spec else DEFAULT_WINDOWS_S)
        return cls(objectives, windows)

    # ------------------------------------------------------------ recording

    def record(self, latency_s: float, error: bool = False) -> None:
        if not self.objectives:
            return
        sec = int(time.time())
        with self._lock:
            self.events_total += 1
            for i, obj in enumerate(self.objectives):
                buckets = self._buckets[i]
                b = buckets.get(sec)
                if b is None:
                    b = buckets[sec] = [0, 0]
                    self._prune_locked(buckets, sec)
                b[0] += 1
                if obj.is_bad(latency_s, error):
                    b[1] += 1

    def _prune_locked(self, buckets: dict, now_sec: int) -> None:
        horizon = now_sec - int(max(self.windows_s)) - 5
        if len(buckets) > max(self.windows_s) + 16:
            for k in [k for k in buckets if k < horizon]:
                del buckets[k]

    # ----------------------------------------------------------- evaluation

    def _window_stats(self, i: int, window_s: float,
                      now_sec: int) -> tuple[int, int]:
        lo = now_sec - int(window_s)
        total = bad = 0
        for sec, (t, b) in self._buckets[i].items():
            if sec > lo:
                total += t
                bad += b
        return total, bad

    def burn_rates(self) -> list[dict]:
        """One row per objective: per-window burn rates + the breach
        flag (every window burning above 1.0)."""
        now_sec = int(time.time())
        out = []
        with self._lock:
            for i, obj in enumerate(self.objectives):
                budget = 1.0 - obj.target
                row = obj.to_json()
                row["windows"] = {}
                burning = bool(self.windows_s)
                for w in self.windows_s:
                    total, bad = self._window_stats(i, w, now_sec)
                    rate = ((bad / total) / budget) if total else 0.0
                    row["windows"][f"{int(w)}s"] = {
                        "events": total, "bad": bad,
                        "burnRate": round(rate, 4),
                    }
                    if rate < 1.0:
                        burning = False
                row["breach"] = burning
                out.append(row)
        return out

    def max_burn_rate(self, kind: str | None = None,
                      rows: list | None = None) -> float:
        """Worst burn rate across objectives (optionally one ``kind``,
        e.g. "latency") and every window — the single scalar the
        autopilot planner reads: ≥1.0 means an error budget is actively
        burning and rebalancing is urgent rather than routine."""
        if rows is None:
            rows = self.burn_rates()
        worst = 0.0
        for row in rows:
            if kind is not None and row.get("kind") != kind:
                continue
            for w in (row.get("windows") or {}).values():
                try:
                    worst = max(worst, float(w.get("burnRate", 0.0)))
                except (TypeError, ValueError):
                    continue
        return worst

    def to_json(self) -> dict:
        return {
            "windows": [int(w) for w in self.windows_s],
            "eventsTotal": self.events_total,
            "objectives": self.burn_rates(),
        }

    def metrics(self, rows: list | None = None) -> dict:
        """Flat summary for /debug/vars (tagged gauges ride
        prometheus_lines). ``rows`` lets a caller that already computed
        burn_rates() avoid a second bucket walk per scrape."""
        if rows is None:
            rows = self.burn_rates()
        return {
            "objectives": len(self.objectives),
            "events_total": self.events_total,
            "breaching": sum(1 for r in rows if r["breach"]),
        }

    def prometheus_lines(self, prefix: str, seen: set | None = None) -> str:
        from pilosa_tpu.utils.stats import (
            _meta_lines,
            escape_label,
            prometheus_block,
        )

        seen = seen if seen is not None else set()
        rows = self.burn_rates()  # ONE bucket walk per scrape
        text = prometheus_block(self.metrics(rows), prefix, "slo",
                                seen=seen)
        lines: list[str] = []
        burn = f"{prefix}_slo_burn_rate"
        lines.extend(_meta_lines(
            burn, "gauge", "error-budget burn rate per objective per "
            "window (1.0 = budget consumed exactly at the sustainable "
            "rate)", seen,
        ))
        for r in rows:
            for wname, w in r["windows"].items():
                lines.append(
                    f'{burn}{{objective="{escape_label(r["name"])}",'
                    f'window="{wname}"}} {w["burnRate"]:g}'
                )
        breach = f"{prefix}_slo_breach"
        lines.extend(_meta_lines(
            breach, "gauge", "1 when every window burns above 1.0 "
            "(multi-window AND)", seen,
        ))
        for r in rows:
            lines.append(
                f'{breach}{{objective="{escape_label(r["name"])}"}} '
                f'{1 if r["breach"] else 0}'
            )
        return text + "\n".join(lines) + ("\n" if lines else "")
