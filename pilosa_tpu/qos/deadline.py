"""Request deadlines, propagated root → executor → remote shards.

The reference tolerated slow shard owners because goroutines were cheap
and the client timeout (30 s) bounded the damage per request; on a TPU
backend a stalled request holds a dispatch slot, so every request carries
a deadline — header-derived or the server default — threaded through
server/http.py → server/api.py → server/pipeline.py → executor →
parallel/cluster_exec.py and serialized on inter-node hops
(parallel/client.py), so remote shards stop work the moment the root
gives up.

Wire format: the remaining BUDGET in milliseconds (``X-Pilosa-Deadline-Ms``),
not an absolute timestamp — budgets survive clock skew between nodes, and
each hop re-anchors the budget against its own monotonic clock (the same
scheme gRPC uses for ``grpc-timeout``).
"""

from __future__ import annotations

import time

# Remaining request budget in integer milliseconds on inter-node hops.
DEADLINE_HEADER = "X-Pilosa-Deadline-Ms"
# Admission-control tenant identity (header-derived quotas).
TENANT_HEADER = "X-Pilosa-Tenant"
# Stale-bounded reads on CDC followers: the most feed lag the client
# will accept, in the shared Go-duration grammar (utils/durations.py —
# "250ms", "1.5s"; bare numbers are seconds). A follower whose replica
# lag exceeds the budget answers 503 + Retry-After instead of serving
# bytes staler than the client declared it can use.
STALENESS_HEADER = "X-Pilosa-Max-Staleness"


class DeadlineExceeded(Exception):
    """The request's deadline passed before its work completed.

    Deliberately NOT a ClientError: an expired deadline is a property of
    the REQUEST, not of any node — replica fallback must not retry it,
    and no node may be marked DEGRADED for it. Maps to HTTP 504.
    """


class Deadline:
    """Absolute deadline on the local monotonic clock."""

    __slots__ = ("_at",)

    def __init__(self, at: float):
        self._at = at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    @classmethod
    def from_millis(cls, millis: int) -> "Deadline":
        """Re-anchor a wire budget (remaining ms) on this node's clock."""
        return cls(time.monotonic() + millis / 1000.0)

    def remaining(self) -> float:
        return self._at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "request") -> None:
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded ({what}, {-rem * 1e3:.0f}ms past)"
            )

    def to_millis(self) -> int:
        """Remaining budget for the wire; >= 1 so an in-flight hop never
        serializes to a zero budget (expiry is raised locally instead)."""
        return max(1, int(self.remaining() * 1000))

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"
