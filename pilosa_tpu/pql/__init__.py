"""PQL: the Pilosa Query Language.

Reference: pql/ (SURVEY.md §2 #11) — upstream generates a PEG parser
(pigeon) from pql.peg; the grammar is an implementation detail, so this is
a compact hand-written recursive-descent parser (SURVEY.md §7.2 M2)
producing the same AST shape: a Query is a list of Calls, each with a
name, named args (ints/floats/strings/bools/lists/conditions) and child
calls. v0.x-era call names (SetBit/ClearBit/Bitmap) are accepted as
aliases for Set/Clear/Row per SURVEY.md EVIDENCE STATUS §4.
"""

from pilosa_tpu.pql.ast import Call, Condition, Query
from pilosa_tpu.pql.parser import ParseError, parse
