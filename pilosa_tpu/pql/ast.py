"""PQL AST (reference: pql/ast.go — Query / Call / Condition)."""

from __future__ import annotations


class Condition:
    """A comparison argument: ``field <op> value`` inside Range/Row calls.

    op ∈ {'<', '<=', '>', '>=', '==', '!=', '><'}; '><' is between and
    carries a [low, high] pair.
    """

    __slots__ = ("op", "value")

    def __init__(self, op: str, value):
        self.op = op
        self.value = value

    def __repr__(self):
        return f"Condition({self.op!r}, {self.value!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Condition)
            and self.op == other.op
            and self.value == other.value
        )


class Call:
    __slots__ = ("name", "args", "children")

    def __init__(self, name: str, args: dict | None = None, children: list | None = None):
        self.name = name
        self.args = args or {}
        self.children = children or []

    def arg(self, key, default=None):
        return self.args.get(key, default)

    def condition_field(self):
        """The (field, Condition) pair if this call carries a comparison."""
        for k, v in self.args.items():
            if isinstance(v, Condition):
                return k, v
        return None, None

    def __repr__(self):
        parts = [repr(c) for c in self.children]
        parts += [f"{k}={v!r}" for k, v in self.args.items()]
        return f"{self.name}({', '.join(parts)})"

    def to_pql(self) -> str:
        """Serialize back to PQL text (used to forward sub-queries to other
        nodes — the reference ships the protobuf AST; PQL text is our
        canonical wire form)."""
        parts = [c.to_pql() for c in self.children]
        for k, v in self.args.items():
            if k == "_field":
                parts.append(str(v))
            elif k == "_col":
                parts.append(_value_to_pql(v))
            elif isinstance(v, Condition):
                if v.op == "><":
                    parts.append(f"{k} >< {_value_to_pql(v.value)}")
                else:
                    parts.append(f"{k} {v.op} {_value_to_pql(v.value)}")
            else:
                parts.append(f"{k}={_value_to_pql(v)}")
        return f"{self.name}({', '.join(parts)})"

    def __eq__(self, other):
        return (
            isinstance(other, Call)
            and self.name == other.name
            and self.args == other.args
            and self.children == other.children
        )


def _value_to_pql(v) -> str:
    if isinstance(v, Call):
        return v.to_pql()
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(v, list):
        return "[" + ", ".join(_value_to_pql(x) for x in v) + "]"
    return str(v)


class Query:
    __slots__ = ("calls",)

    def __init__(self, calls: list[Call]):
        self.calls = calls

    def __repr__(self):
        return f"Query({self.calls!r})"

    def __eq__(self, other):
        return isinstance(other, Query) and self.calls == other.calls

    def write_calls(self):
        from pilosa_tpu.pql.parser import WRITE_CALLS

        return [c for c in self.calls if c.name in WRITE_CALLS]
