"""Hand-written recursive-descent PQL parser.

Produces the reference AST shape (pql.ParseString → Query of Calls —
SURVEY.md §2 #11) without the PEG/codegen machinery. Accepted surface is
the v1.x call set with v0.x aliases (SetBit/ClearBit/Bitmap — SURVEY.md
EVIDENCE STATUS rename table).

Positional conventions (matching reference PQL usage):
- a bare identifier positional arg is the field: ``TopN(stargazer, n=5)``
  → args['_field'] = 'stargazer'
- a bare number/string positional arg is the column: ``Set(10, f=1)``
  → args['_col'] = 10
- ``field <op> value`` becomes a Condition arg: ``Range(fare > 10)``
"""

from __future__ import annotations

from pilosa_tpu.pql.ast import Call, Condition, Query

ALIASES = {
    "SetBit": "Set",
    "ClearBit": "Clear",
    "Bitmap": "Row",
    "ClearRowBit": "Clear",
    # v0.x-era BSI write spelling; v1.x writes int fields via
    # Set(col, field=value), which Set already implements
    "SetValue": "Set",
}

WRITE_CALLS = {
    "Set", "Clear", "ClearRow", "Store",
    "SetRowAttrs", "SetColumnAttrs", "Delete",
}

CALL_NAMES = {
    "Row", "Union", "Intersect", "Difference", "Xor", "Not", "All", "Shift",
    "Count", "TopN", "Min", "Max", "Sum", "Range", "Rows", "GroupBy",
    "Set", "Clear", "ClearRow", "Store", "SetRowAttrs",
    "SetColumnAttrs", "Options", "IncludesColumn",
    # pseudo-call: appears only as an arg value —
    # GroupBy(..., having=Condition(count > 10))
    "Condition",
} | set(ALIASES)

_CMP_OPS = ("><", "<=", ">=", "==", "!=", "<", ">")


class ParseError(ValueError):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"parse error at offset {pos}: {msg}")
        self.pos = pos


class _Lexer:
    def __init__(self, src: str):
        self.src = src
        self.pos = 0

    def _skip_ws(self):
        while self.pos < len(self.src) and self.src[self.pos] in " \t\r\n;":
            self.pos += 1

    def peek(self) -> str | None:
        self._skip_ws()
        return self.src[self.pos] if self.pos < len(self.src) else None

    def expect(self, ch: str):
        if self.peek() != ch:
            raise ParseError(f"expected {ch!r}", self.pos)
        self.pos += 1

    def try_take(self, ch: str) -> bool:
        if self.peek() == ch:
            self.pos += 1
            return True
        return False

    def take_cmp(self) -> str | None:
        self._skip_ws()
        for op in _CMP_OPS:
            if self.src.startswith(op, self.pos):
                self.pos += len(op)
                return op
        return None

    def peek_cmp(self) -> str | None:
        self._skip_ws()
        for op in _CMP_OPS:
            if self.src.startswith(op, self.pos):
                return op
        return None

    def take_ident(self) -> str:
        self._skip_ws()
        start = self.pos
        if self.pos < len(self.src) and (
            self.src[self.pos].isalpha() or self.src[self.pos] in "_"
        ):
            self.pos += 1
            while self.pos < len(self.src) and (
                self.src[self.pos].isalnum() or self.src[self.pos] in "_-"
            ):
                self.pos += 1
        if start == self.pos:
            raise ParseError("expected identifier", self.pos)
        return self.src[start : self.pos]

    def take_string(self) -> str:
        quote = self.peek()
        self.pos += 1
        out = []
        while self.pos < len(self.src):
            c = self.src[self.pos]
            if c == "\\" and self.pos + 1 < len(self.src):
                out.append(self.src[self.pos + 1])
                self.pos += 2
                continue
            if c == quote:
                self.pos += 1
                return "".join(out)
            out.append(c)
            self.pos += 1
        raise ParseError("unterminated string", self.pos)

    def take_number(self):
        self._skip_ws()
        start = self.pos
        if self.src[self.pos] in "+-":
            self.pos += 1
        while self.pos < len(self.src) and self.src[self.pos].isdigit():
            self.pos += 1
        is_float = False
        if self.pos < len(self.src) and self.src[self.pos] == ".":
            is_float = True
            self.pos += 1
            while self.pos < len(self.src) and self.src[self.pos].isdigit():
                self.pos += 1
        text = self.src[start : self.pos]
        if text in ("", "+", "-"):
            raise ParseError("expected number", start)
        return float(text) if is_float else int(text)


# Parsed-query memo. Call/Query trees are immutable after parse (the
# executor only reads them), so repeated query texts — the common serving
# pattern, and ~130 us/query of the pipelined submit path — share one
# tree. Bounded by wholesale clear: queries with embedded unique literals
# (bulk Set streams) would otherwise grow it without limit, and a clear
# only costs the next parse.
_PARSE_CACHE: dict[str, Query] = {}
_PARSE_CACHE_MAX = 4096


def parse(src: str) -> Query:
    cached = _PARSE_CACHE.get(src)
    if cached is not None:
        return cached
    lex = _Lexer(src)
    calls = []
    while lex.peek() is not None:
        calls.append(_parse_call(lex))
    if not calls:
        raise ParseError("empty query", 0)
    out = Query(calls)
    if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
        _PARSE_CACHE.clear()
    _PARSE_CACHE[src] = out
    return out


def _parse_call(lex: _Lexer) -> Call:
    pos = lex.pos
    name = lex.take_ident()
    name = ALIASES.get(name, name)
    if name not in CALL_NAMES:
        raise ParseError(f"unknown call {name!r}", pos)
    lex.expect("(")
    call = Call(name)
    first = True
    while not lex.try_take(")"):
        if not first:
            lex.expect(",")
        first = False
        _parse_arg(lex, call)
    return call


def _parse_arg(lex: _Lexer, call: Call) -> None:
    c = lex.peek()
    if c is None:
        raise ParseError("unexpected end of input", lex.pos)
    if c.isalpha() or c == "_":
        save = lex.pos
        ident = lex.take_ident()
        nxt = lex.peek()
        if nxt == "(":
            lex.pos = save
            call.children.append(_parse_call(lex))
            return
        if nxt == "=" and lex.peek_cmp() != "==":
            lex.expect("=")
            call.args[ident] = _parse_value(lex)
            return
        op = lex.take_cmp()
        if op is not None:
            if isinstance(call.args.get(ident), Condition):
                # Condition(count > 1, count < 5) would silently keep only
                # the last condition; ranges must use `count >< [lo, hi]`
                raise ParseError(
                    f"duplicate condition on {ident!r} (use >< for ranges)",
                    lex.pos,
                )
            call.args[ident] = Condition(op, _parse_value(lex))
            return
        if ident in ("true", "false"):
            _add_positional(call, ident == "true", lex.pos)
            return
        if ident == "null":
            _add_positional(call, None, lex.pos)
            return
        # bare identifier positional → field name
        if "_field" in call.args:
            raise ParseError(f"duplicate positional field {ident!r}", lex.pos)
        call.args["_field"] = ident
        return
    value = _parse_value(lex)
    _add_positional(call, value, lex.pos)


def _add_positional(call: Call, value, pos: int) -> None:
    if "_col" in call.args:
        raise ParseError("duplicate positional value", pos)
    call.args["_col"] = value


def _parse_value(lex: _Lexer):
    c = lex.peek()
    if c is None:
        raise ParseError("expected value", lex.pos)
    if c in "'\"":
        return lex.take_string()
    if c == "[":
        lex.expect("[")
        out = []
        first = True
        while not lex.try_take("]"):
            if not first:
                lex.expect(",")
            first = False
            out.append(_parse_value(lex))
        return out
    if c.isdigit() or c in "+-":
        return lex.take_number()
    if c.isalpha() or c == "_":
        save = lex.pos
        ident = lex.take_ident()
        if lex.peek() == "(":
            lex.pos = save
            return _parse_call(lex)
        if ident == "true":
            return True
        if ident == "false":
            return False
        if ident == "null":
            return None
        return ident  # bare identifier value → string (e.g. field=fare)
    raise ParseError(f"unexpected character {c!r}", lex.pos)
