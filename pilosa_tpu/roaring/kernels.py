"""Vectorized whole-fragment roaring kernels (host path).

Every host-side roaring consumer used to walk containers in
per-container Python/numpy loops: one ``lows()`` / ``dense_words32()``
/ ``tobytes()`` dispatch per 65536-bit container, so a populated
fragment (hundreds to thousands of containers) paid hundreds of numpy
dispatches where the actual bit work was microseconds. This module is
the batched replacement, after Lemire's vectorized popcount blueprint
(arXiv:1611.07612) and the roaring container design itself
(arXiv:1709.07821): concatenate the fragment's container payloads into
flat arrays with offset tables ONCE (:func:`flatten` — the single
sanctioned per-container metadata loop), then do id materialization,
dense decode, popcount (``np.bitwise_count``), AND/OR/XOR/ANDNOT,
digest feeding, and manifest diffing as single whole-fragment numpy
kernels — one dispatch per *fragment*, not per *container*.

Contract: every kernel is **byte-identical** to the per-container
reference path it replaces (tests/test_roaring_kernels.py pins this
property over randomized array/bitmap/run mixes). Set ops use a
galloping (searchsorted) intersect when the operand sizes are lopsided
and a linear merge otherwise; bitmap containers are only materialized
to ids where the kind combination forces it (bitmap×bitmap stays in
word space).

Consumers (enforced by scripts/check_hostpath_loops.py): fragment row
decode + block digests (storage/fragment.py), verified loads and the
scrubber (storage/integrity.py, parallel/scrub.py), the anti-entropy
sync manifest diffs (parallel/cluster.py, server block serving), and
the CDC bulk-sync path (cdc/tailer.py).
"""

from __future__ import annotations

import bisect
import struct

import numpy as np

from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN, BITMAP_N_WORDS

_U16 = np.uint64(16)
_EMPTY_IDS = np.empty(0, np.uint64)
_EMPTY_IDS.setflags(write=False)


# ------------------------------------------------------------- statistics


class KernelStats:
    """Process-wide host-path kernel counters (``hostpath_*`` series on
    /metrics). Plain int adds, no lock: these feed dashboards, not
    correctness invariants, and the hot paths must not pay a lock."""

    __slots__ = ("kernel_calls", "containers_flattened", "ids_materialized",
                 "dense_decodes", "set_ops")

    def __init__(self):
        self.kernel_calls = 0
        self.containers_flattened = 0
        self.ids_materialized = 0
        self.dense_decodes = 0
        self.set_ops = 0

    def metrics(self) -> dict:
        return {
            "hostpath_kernel_calls_total": self.kernel_calls,
            "hostpath_containers_flattened_total": self.containers_flattened,
            "hostpath_ids_materialized_total": self.ids_materialized,
            "hostpath_dense_decodes_total": self.dense_decodes,
            "hostpath_set_ops_total": self.set_ops,
        }


_STATS = KernelStats()


def global_kernel_stats() -> KernelStats:
    return _STATS


# --------------------------------------------------------------- flatten


class FlatFragment:
    """A fragment's containers concatenated into flat per-kind arrays.

    ``keys``/``kinds``/``cards`` are parallel per-container metadata in
    ascending key order; ``kind_row[i]`` is container *i*'s row within
    its kind's concatenation. Array payloads concatenate into
    ``arr_data`` with ``arr_off`` offsets; bitmap words stack into
    ``bmp_words`` (n, 1024) uint64; run intervals concatenate into
    ``run_data`` (R, 2) int64 with ``run_off`` run-count offsets.
    Containers are immutable once published (bitmap.py swaps whole
    containers atomically), so a flat view taken lock-free is a
    consistent snapshot of every container it captured.
    """

    __slots__ = ("keys", "kinds", "cards", "kind_row",
                 "arr_sel", "arr_data", "arr_off",
                 "bmp_sel", "bmp_words",
                 "run_sel", "run_data", "run_off")

    @property
    def n_containers(self) -> int:
        return int(self.keys.size)

    def total(self) -> int:
        return int(self.cards.sum()) if self.cards.size else 0

    def kind_counts(self) -> tuple[int, int, int]:
        """(array, bitmap, run) container counts — the PROFILE
        container-scan tally, one call per kernel invocation."""
        c = np.bincount(self.kinds, minlength=4)
        return int(c[ARRAY]), int(c[BITMAP]), int(c[RUN])


def _build_flat(pairs) -> FlatFragment:
    """Assemble a FlatFragment from (key, Container) pairs in ascending
    key order. THE one sanctioned per-container loop on the host path:
    it gathers references and metadata only — every bit touch happens
    in the batched kernels below."""
    f = FlatFragment()
    n = len(pairs)
    keys = np.empty(n, np.int64)
    kinds = np.empty(n, np.uint8)
    cards = np.empty(n, np.int64)
    kind_row = np.empty(n, np.int64)
    arr_sel, arr_parts = [], []
    bmp_sel, bmp_parts = [], []
    run_sel, run_parts = [], []
    for i, (key, c) in enumerate(pairs):
        keys[i] = key
        kinds[i] = c.kind
        cards[i] = c.n
        if c.kind == ARRAY:
            kind_row[i] = len(arr_sel)
            arr_sel.append(i)
            arr_parts.append(c.data)
        elif c.kind == BITMAP:
            kind_row[i] = len(bmp_sel)
            bmp_sel.append(i)
            bmp_parts.append(c.data)
        else:
            kind_row[i] = len(run_sel)
            run_sel.append(i)
            run_parts.append(c.data)
    f.keys, f.kinds, f.cards, f.kind_row = keys, kinds, cards, kind_row
    f.arr_sel = np.asarray(arr_sel, np.int64)
    f.arr_data = (np.concatenate(arr_parts) if arr_parts
                  else np.empty(0, np.uint16))
    lens = np.asarray([p.size for p in arr_parts], np.int64)
    f.arr_off = np.concatenate(([0], np.cumsum(lens))).astype(np.int64)
    f.bmp_sel = np.asarray(bmp_sel, np.int64)
    f.bmp_words = (np.stack(bmp_parts) if bmp_parts
                   else np.empty((0, BITMAP_N_WORDS), np.uint64))
    f.run_sel = np.asarray(run_sel, np.int64)
    f.run_data = (np.concatenate(run_parts).astype(np.int64).reshape(-1, 2)
                  if run_parts else np.empty((0, 2), np.int64))
    rlens = np.asarray([p.shape[0] for p in run_parts], np.int64)
    f.run_off = np.concatenate(([0], np.cumsum(rlens))).astype(np.int64)
    _STATS.containers_flattened += n
    return f


def flatten(bitmap, lo_key: int | None = None,
            hi_key: int | None = None) -> FlatFragment:
    """Flatten a RoaringBitmap's containers with keys in
    [lo_key, hi_key] (inclusive; None = unbounded). Lock-free against
    concurrent writers under the same discipline as ``to_ids``: ``.get``
    + skip, empty containers skipped (they contribute nothing and the
    per-container tally never counted them)."""
    keys = bitmap.keys
    lo_i = 0 if lo_key is None else bisect.bisect_left(keys, lo_key)
    hi_i = len(keys) if hi_key is None else bisect.bisect_right(keys, hi_key)
    pairs = []
    for key in keys[lo_i:hi_i]:
        c = bitmap._containers.get(key)
        if c is not None and c.n:
            pairs.append((key, c))
    return _build_flat(pairs)


def _take(f: FlatFragment, idx: np.ndarray) -> FlatFragment:
    """Sub-flatten: the containers at positions ``idx`` (ascending), as
    a new FlatFragment — pure array gathers, no per-container work."""
    arr_pick = idx[f.kinds[idx] == ARRAY]
    bmp_pick = idx[f.kinds[idx] == BITMAP]
    run_pick = idx[f.kinds[idx] == RUN]
    out = FlatFragment()
    out.keys = f.keys[idx]
    out.kinds = f.kinds[idx]
    out.cards = f.cards[idx]
    kind_row = np.empty(idx.size, np.int64)
    kind_row[f.kinds[idx] == ARRAY] = np.arange(arr_pick.size)
    kind_row[f.kinds[idx] == BITMAP] = np.arange(bmp_pick.size)
    kind_row[f.kinds[idx] == RUN] = np.arange(run_pick.size)
    out.kind_row = kind_row
    rows = f.kind_row[arr_pick]
    starts, stops = f.arr_off[rows], f.arr_off[rows + 1]
    out.arr_sel = np.nonzero(out.kinds == ARRAY)[0]
    out.arr_data = _gather_ranges(f.arr_data, starts, stops)
    out.arr_off = np.concatenate(
        ([0], np.cumsum(stops - starts))).astype(np.int64)
    out.bmp_sel = np.nonzero(out.kinds == BITMAP)[0]
    out.bmp_words = f.bmp_words[f.kind_row[bmp_pick]]
    rrows = f.kind_row[run_pick]
    rstarts, rstops = f.run_off[rrows], f.run_off[rrows + 1]
    out.run_sel = np.nonzero(out.kinds == RUN)[0]
    out.run_data = _gather_ranges(f.run_data, rstarts, rstops)
    out.run_off = np.concatenate(
        ([0], np.cumsum(rstops - rstarts))).astype(np.int64)
    return out


def _gather_ranges(data: np.ndarray, starts: np.ndarray,
                   stops: np.ndarray) -> np.ndarray:
    """``data[s0:e0] ++ data[s1:e1] ++ …`` — O(1) slice views plus one
    ``np.concatenate``, never a per-element fancy-index gather (which
    costs an index array as large as the payload)."""
    parts = [data[a:b] for a, b in zip(starts.tolist(), stops.tolist())]
    if not parts:
        return data[:0].copy()
    return np.concatenate(parts) if len(parts) > 1 else parts[0].copy()


# ------------------------------------------------------ id materialization


def _bmp_lows(f: FlatFragment) -> tuple[np.ndarray, np.ndarray]:
    """All set bit positions across the stacked bitmap words: returns
    (global bit index int64 into the (nb×65536)-bit space, counts per
    bitmap container int64). ``flatnonzero`` over a bool view is ~2×
    the uint8 scan, and searchsorted against the 65536-aligned edges
    beats a ``bincount`` over the positions by orders of magnitude."""
    nb = f.bmp_words.shape[0]
    if nb == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    bits = np.unpackbits(
        np.ascontiguousarray(f.bmp_words).view(np.uint8), bitorder="little"
    )
    pos = np.flatnonzero(bits.view(bool))
    edges = np.searchsorted(pos, np.arange(nb + 1, dtype=np.int64) << 16)
    return pos, np.diff(edges)


def _bmp_ids(f: FlatFragment) -> tuple[np.ndarray, np.ndarray]:
    """Global ids of every bitmap container, as one sorted uint64
    stream, plus per-container counts. The container base is folded
    into the stream-local bit index — ``id = pos + ((key - slot) <<
    16)`` — so materialization is one repeat + one add, with no
    low-16-bit mask pass."""
    pos, counts = _bmp_lows(f)
    if pos.size == 0:
        return _EMPTY_IDS, counts
    adj = ((f.keys[f.bmp_sel] - np.arange(f.bmp_sel.size))
           << np.int64(16)).tolist()
    edges = np.concatenate(([0], np.cumsum(counts))).tolist()
    # in-place scalar add per container segment: no repeat() temp the
    # size of the id stream (large temps force mmap churn on busy heaps)
    for c, a in enumerate(adj):
        if a and edges[c] != edges[c + 1]:
            pos[edges[c]:edges[c + 1]] += a
    return pos.view(np.uint64), counts


def _run_ids(f: FlatFragment) -> tuple[np.ndarray, np.ndarray]:
    """Global ids of every run container, as one sorted uint64 stream,
    plus per-container counts. Container bases are folded into the
    (few) run starts *before* expansion, so the expensive per-id work
    is a single repeat + arange over the whole stream."""
    runs = f.run_data
    n_runs = runs.shape[0]
    if n_runs == 0:
        return _EMPTY_IDS, np.zeros(f.run_sel.size, np.int64)
    lengths = np.maximum(runs[:, 1] - runs[:, 0] + 1, 0)
    per_cont = np.add.reduceat(lengths, f.run_off[:-1])
    per_cont[f.run_off[:-1] == f.run_off[1:]] = 0
    total = int(lengths.sum())
    if total == 0:
        return _EMPTY_IDS, per_cont
    runs_per_cont = f.run_off[1:] - f.run_off[:-1]
    gstarts = runs[:, 0] + np.repeat(f.keys[f.run_sel] << np.int64(16),
                                     runs_per_cont)
    keep = lengths > 0
    if not keep.all():
        gstarts, lengths = gstarts[keep], lengths[keep]
    # ones + boundary deltas + one in-place cumsum: two passes over the
    # id stream instead of the four of repeat + arange + add
    gids = np.ones(total, np.int64)
    gids[0] = gstarts[0]
    bounds = np.cumsum(lengths)[:-1]
    if bounds.size:
        gids[bounds] = gstarts[1:] - (gstarts[:-1] + lengths[:-1] - 1)
    np.cumsum(gids, out=gids)
    return gids.view(np.uint64), per_cont


def fragment_ids(f: FlatFragment) -> np.ndarray:
    """Every id in the flat fragment, globally sorted uint64 — the
    whole-fragment ``to_ids`` kernel. Byte-identical to concatenating
    ``container.lows() + (key << 16)`` over sorted keys.

    Per-container output extents come from the PAYLOADS (array sizes,
    bitmap popcounts, run lengths), never the cached cardinalities —
    the reference path materializes whatever the payload holds, and a
    corrupt-but-decodable file can carry a lying cardinality field
    (the integrity fuzz flips every byte; both paths must agree).

    Each kind's stream is already globally sorted, so a single-kind
    fragment returns its stream directly; mixed fragments interleave
    the streams with ONE view per run of consecutive same-kind
    containers (kinds cluster by row, so segments number ~rows, not
    ~containers) into one ``np.concatenate`` — measures ~2× faster
    than a destination-index scatter, with no per-container work."""
    _STATS.kernel_calls += 1
    nc = int(f.keys.size)
    if nc == 0:
        return _EMPTY_IDS
    arr_ids = _EMPTY_IDS
    arr_counts = f.arr_off[1:] - f.arr_off[:-1]
    if f.arr_data.size:
        bases = f.keys[f.arr_sel].astype(np.uint64) << _U16
        arr_ids = np.repeat(bases, arr_counts) + f.arr_data
    bmp_ids, bmp_counts = _bmp_ids(f)
    run_ids, run_counts = _run_ids(f)
    total = arr_ids.size + bmp_ids.size + run_ids.size
    if total == 0:
        return _EMPTY_IDS
    _STATS.ids_materialized += total
    if f.arr_sel.size == nc:
        return arr_ids
    if f.bmp_sel.size == nc:
        return bmp_ids
    if f.run_sel.size == nc:
        return run_ids
    arr_off = f.arr_off.tolist()
    bmp_off = np.concatenate(([0], np.cumsum(bmp_counts))).tolist()
    run_off = np.concatenate(([0], np.cumsum(run_counts))).tolist()
    kinds, rows = f.kinds.tolist(), f.kind_row.tolist()
    seg = [0, *(np.flatnonzero(np.diff(f.kinds)) + 1).tolist(), nc]
    parts = []
    for j in range(len(seg) - 1):
        s = seg[j]
        k, r0, r1 = kinds[s], rows[s], rows[seg[j + 1] - 1] + 1
        if k == ARRAY:
            parts.append(arr_ids[arr_off[r0]:arr_off[r1]])
        elif k == BITMAP:
            parts.append(bmp_ids[bmp_off[r0]:bmp_off[r1]])
        else:
            parts.append(run_ids[run_off[r0]:run_off[r1]])
    return np.concatenate(parts)


def range_ids(f: FlatFragment, start: int, stop: int) -> np.ndarray:
    """Sorted ids in [start, stop) — kernel analog of
    ``RoaringBitmap.range_ids`` over an already key-bounded flat view
    (edge containers trimmed the same way: one vectorized mask)."""
    ids = fragment_ids(f)
    if ids.size == 0:
        return ids
    return ids[(ids >= np.uint64(start)) & (ids < np.uint64(stop))]


# ------------------------------------------------------------ dense decode


def _or_runs_into(words: np.ndarray, starts: np.ndarray,
                  ends: np.ndarray) -> None:
    """OR the inclusive bit ranges [starts[i], ends[i]] into a flat
    uint64 word array, O(runs + words) — never per-bit: head/tail
    partial words via masked ``bitwise_or.at``, interior full words via
    a cumsum coverage count."""
    ok = ends >= starts
    if not ok.all():
        starts, ends = starts[ok], ends[ok]
    if starts.size == 0:
        return
    ones = np.uint64(0xFFFFFFFFFFFFFFFF)
    ws, we = starts >> 6, ends >> 6
    head = ones << (starts & 63).astype(np.uint64)
    tail = ones >> (np.uint64(63) - (ends & 63).astype(np.uint64))
    same = ws == we
    np.bitwise_or.at(words, ws, np.where(same, head & tail, head))
    cross = ~same
    if cross.any():
        np.bitwise_or.at(words, we[cross], tail[cross])
        delta = np.zeros(words.size + 1, np.int64)
        np.add.at(delta, ws[cross] + 1, 1)
        np.add.at(delta, we[cross], -1)
        words[np.cumsum(delta[:-1]) > 0] = ones


def dense_words32(f: FlatFragment, base_key: int,
                  n_containers: int) -> np.ndarray:
    """Materialize ``n_containers`` consecutive containers starting at
    ``base_key`` as packed uint32 words — the whole-row residency-miss
    decode kernel (byte-identical to per-container
    ``Container.dense_words32`` scatters). Bitmap containers copy their
    words straight across (an all-bitmap window is one memcpy); run
    intervals fill whole words via :func:`_or_runs_into` without ever
    expanding to per-bit positions; array set bits go through
    ``np.bitwise_or.at`` word scatters while sparse (~11 ns/bit, no
    window-sized memset) and fall back to one bool write + one
    ``np.packbits`` once they pass ~1/128 of the window, where the
    linear pack wins."""
    _STATS.kernel_calls += 1
    _STATS.dense_decodes += 1
    slots = f.keys - base_key
    n_scatter = int(f.arr_data.size)
    if (n_scatter == 0 and f.run_data.shape[0] == 0
            and f.bmp_sel.size == n_containers):
        w = f.bmp_words
        if w.flags.owndata and w.flags.writeable and w.flags.c_contiguous:
            # flatten() stacked these words into a fresh buffer the
            # FlatFragment owns — hand it over instead of copying again
            return w.reshape(-1).view("<u4")
        return np.ascontiguousarray(w).reshape(-1).view("<u4").copy()
    run_gs = run_ge = None
    if f.run_data.shape[0]:
        runs_per_cont = f.run_off[1:] - f.run_off[:-1]
        rbase = np.repeat(slots[f.run_sel] << 16, runs_per_cont)
        run_gs = rbase + f.run_data[:, 0]
        run_ge = rbase + f.run_data[:, 1]
    if n_scatter >= n_containers << 9:  # window_bits / 128
        bits = np.zeros(n_containers << 16, bool)
        arr_counts = f.arr_off[1:] - f.arr_off[:-1]
        gpos = (np.repeat(slots[f.arr_sel] << 16, arr_counts)
                + f.arr_data.astype(np.int64))
        bits[gpos] = True
        out8 = np.packbits(bits, bitorder="little")
        out64 = out8.view("<u8").reshape(n_containers, BITMAP_N_WORDS)
        if f.bmp_words.shape[0]:
            out64[slots[f.bmp_sel]] = f.bmp_words
        if run_gs is not None:
            _or_runs_into(out64.reshape(-1), run_gs, run_ge)
        return out8.view("<u4").copy()
    out64 = np.zeros((n_containers, BITMAP_N_WORDS), np.uint64)
    if f.bmp_words.shape[0]:
        out64[slots[f.bmp_sel]] = f.bmp_words
    if n_scatter:
        arr_counts = f.arr_off[1:] - f.arr_off[:-1]
        gpos = (np.repeat(slots[f.arr_sel] << 16, arr_counts)
                + f.arr_data.astype(np.int64))
        np.bitwise_or.at(out64.reshape(-1), gpos >> 6,
                         np.uint64(1) << (gpos & 63).astype(np.uint64))
    if run_gs is not None:
        _or_runs_into(out64.reshape(-1), run_gs, run_ge)
    return out64.reshape(-1).view("<u4")


# ---------------------------------------------------------------- popcount


def popcount(f: FlatFragment) -> int:
    """Whole-fragment population count from the raw payloads (one
    ``np.bitwise_count`` over the stacked bitmap words + array sizes +
    run lengths) — does not trust the cached cardinalities."""
    _STATS.kernel_calls += 1
    total = int(f.arr_data.size)
    if f.bmp_words.shape[0]:
        total += int(np.bitwise_count(f.bmp_words).sum(dtype=np.int64))
    if f.run_data.shape[0]:
        total += int((f.run_data[:, 1] - f.run_data[:, 0] + 1).sum())
    return total


# ----------------------------------------------------------------- set ops


def intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-unique uint64 intersection. Galloping when lopsided: probe
    the small side into the big side with one ``searchsorted`` (log per
    probe — the vectorized analog of the galloping intersect in the
    roaring papers); linear merge (``np.intersect1d``) otherwise."""
    if a.size == 0 or b.size == 0:
        return _EMPTY_IDS
    small, big = (a, b) if a.size <= b.size else (b, a)
    if small.size << 5 < big.size:
        i = np.searchsorted(big, small)
        i_c = np.minimum(i, big.size - 1)
        return small[(i < big.size) & (big[i_c] == small)]
    return np.intersect1d(a, b, assume_unique=True)


def setdiff_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-unique a \\ b, galloping when b dwarfs a."""
    if a.size == 0:
        return _EMPTY_IDS
    if b.size == 0:
        return a
    if a.size << 5 < b.size:
        i = np.searchsorted(b, a)
        i_c = np.minimum(i, b.size - 1)
        return a[~((i < b.size) & (b[i_c] == a))]
    return np.setdiff1d(a, b, assume_unique=True)


def _ids_from_word_rows(keys: np.ndarray, words: np.ndarray) -> np.ndarray:
    """ids for (key, 1024-word-row) pairs: one unpack + one nonzero,
    container bases folded in per row (same trick as ``_bmp_ids``)."""
    nb = words.shape[0]
    if nb == 0:
        return _EMPTY_IDS
    bits = np.unpackbits(
        np.ascontiguousarray(words).view(np.uint8), bitorder="little")
    pos = np.flatnonzero(bits.view(bool))
    if pos.size == 0:
        return _EMPTY_IDS
    edges = np.searchsorted(pos, np.arange(nb + 1, dtype=np.int64) << 16)
    adj = (keys.astype(np.int64) - np.arange(nb)) << np.int64(16)
    return (pos + np.repeat(adj, np.diff(edges))).view(np.uint64)


def _as_flat(x) -> FlatFragment:
    return x if isinstance(x, FlatFragment) else flatten(x)


def _setop(a, b, word_op, id_op, keep_a_only: bool,
           keep_b_only: bool) -> np.ndarray:
    fa, fb = _as_flat(a), _as_flat(b)
    _STATS.kernel_calls += 1
    _STATS.set_ops += 1
    common, ia, ib = np.intersect1d(fa.keys, fb.keys, return_indices=True)
    parts = []
    if common.size:
        bb = (fa.kinds[ia] == BITMAP) & (fb.kinds[ib] == BITMAP)
        if bb.any():
            # bitmap×bitmap stays in word space — no materialization
            wa = fa.bmp_words[fa.kind_row[ia[bb]]]
            wb = fb.bmp_words[fb.kind_row[ib[bb]]]
            parts.append(_ids_from_word_rows(common[bb], word_op(wa, wb)))
        if (~bb).any():
            ids_a = fragment_ids(_take(fa, ia[~bb]))
            ids_b = fragment_ids(_take(fb, ib[~bb]))
            parts.append(id_op(ids_a, ids_b))
    if keep_a_only:
        only = np.setdiff1d(np.arange(fa.keys.size), ia)
        if only.size:
            parts.append(fragment_ids(_take(fa, only)))
    if keep_b_only:
        only = np.setdiff1d(np.arange(fb.keys.size), ib)
        if only.size:
            parts.append(fragment_ids(_take(fb, only)))
    parts = [p for p in parts if p.size]
    if not parts:
        return _EMPTY_IDS
    if len(parts) == 1:
        return parts[0]
    return np.sort(np.concatenate(parts))


def fragment_and(a, b) -> np.ndarray:
    """Sorted ids of a ∩ b (whole-fragment AND kernel)."""
    return _setop(a, b, np.bitwise_and, intersect_sorted, False, False)


def fragment_or(a, b) -> np.ndarray:
    """Sorted ids of a ∪ b."""
    return _setop(a, b, np.bitwise_or,
                  lambda x, y: np.union1d(x, y), True, True)


def fragment_xor(a, b) -> np.ndarray:
    """Sorted ids of a △ b."""
    return _setop(a, b, np.bitwise_xor,
                  lambda x, y: np.setxor1d(x, y, assume_unique=True),
                  True, True)


def fragment_andnot(a, b) -> np.ndarray:
    """Sorted ids of a \\ b."""
    return _setop(a, b, lambda x, y: x & ~y, setdiff_sorted, True, False)


def diff_ids(a, b) -> tuple[np.ndarray, np.ndarray]:
    """(only-in-a, only-in-b) sorted id arrays — the content diff the
    anti-entropy block compare speaks."""
    ids_a = fragment_ids(_as_flat(a))
    ids_b = fragment_ids(_as_flat(b))
    return setdiff_sorted(ids_a, ids_b), setdiff_sorted(ids_b, ids_a)


# -------------------------------------------------------- digests / diffs


def block_slices(ids: np.ndarray, blocks, block_rows: int = 100) -> dict:
    """Slice a sorted id array into the requested checksum blocks with
    ONE searchsorted over the block boundaries — replaces the
    per-block full-``to_ids``-and-mask walk (O(blocks × population))
    the sync block server used to pay. Returns {block: ids}."""
    _STATS.kernel_calls += 1
    wanted = np.asarray(sorted(set(int(b) for b in blocks)), np.int64)
    if wanted.size == 0:
        return {}
    width = np.uint64(block_rows) << np.uint64(20)
    los = wanted.astype(np.uint64) * width
    edges = np.searchsorted(ids, np.concatenate((los, los + width)))
    n = wanted.size
    return {int(wanted[i]): ids[edges[i]:edges[n + i]] for i in range(n)}


def diff_digests(local, peer) -> list[int]:
    """Blocks whose digests differ (peer-driven fetch list): every block
    the peer has that the local side lacks or disagrees on — the sync
    manifest diff, one place."""
    local = dict(local)
    return sorted(int(b) for b, checksum in dict(peer).items()
                  if local.get(b) != checksum)


# ------------------------------------------------- snapshot-bytes fast path

_HEADER = struct.Struct("<IHHIQ")
_SNAP_MAGIC = 0x50C4B175
_SNAP_VERSION = 1
_DESCR_DTYPE = np.dtype([("key", "<u8"), ("kind", "<u2"),
                         ("nm1", "<u2"), ("plen", "<u4")])


def flat_from_snapshot(buf) -> tuple[FlatFragment, int]:
    """Parse a roaring/format.py snapshot straight into a FlatFragment —
    no Container objects, no per-container ``np.frombuffer`` — with the
    same structural validation (and error text) as ``deserialize``.
    Returns (flat, offset-where-ops-begin). The scrub/verify fast path:
    digesting a fragment file becomes parse → :func:`fragment_ids` →
    ``block_digests`` with zero per-container dispatches.

    Falls back (ValueError) only on inputs ``deserialize`` also
    rejects; irregular-but-accepted payloads (bitmap payload not
    exactly 1024 words) raise :class:`_IrregularSnapshot` so the caller
    can retry through the reference decoder.
    """
    buf = memoryview(buf)
    if len(buf) < _HEADER.size:
        raise ValueError("roaring: truncated header")
    magic, version, _flags, n_containers, payload_bytes = _HEADER.unpack_from(
        buf, 0)
    if magic != _SNAP_MAGIC:
        raise ValueError(f"roaring: bad magic 0x{magic:08X}")
    if version != _SNAP_VERSION:
        raise ValueError(f"roaring: unsupported version {version}")
    descr_end = _HEADER.size + n_containers * _DESCR_DTYPE.itemsize
    if descr_end > len(buf):
        raise ValueError("roaring: truncated container payload")
    descrs = np.frombuffer(buf, dtype=_DESCR_DTYPE, count=n_containers,
                           offset=_HEADER.size)
    kinds = descrs["kind"].astype(np.uint8)
    plens = descrs["plen"].astype(np.int64)
    bad = (kinds < ARRAY) | (kinds > RUN)
    if bad.any():
        k = int(descrs["kind"][np.nonzero(bad)[0][0]])
        raise ValueError(f"roaring: unknown container kind {k}")
    offs = descr_end + np.concatenate(([0], np.cumsum(plens)))
    if int(offs[-1]) > len(buf):
        raise ValueError("roaring: truncated container payload")
    if int(offs[-1]) != descr_end + payload_bytes:
        raise ValueError("roaring: payload length mismatch")
    is_b = kinds == BITMAP
    if ((plens[kinds == ARRAY] & 1).any()
            or (plens[is_b] != BITMAP_N_WORDS * 8).any()
            or (plens[kinds == RUN] & 3).any()):
        raise _IrregularSnapshot()
    keys = descrs["key"].astype(np.int64)
    order = np.argsort(keys, kind="stable")
    if np.unique(keys).size != keys.size:
        # duplicate keys: dict semantics (last wins) — rare, reference path
        raise _IrregularSnapshot()
    buf8 = np.frombuffer(buf, np.uint8)
    f = FlatFragment()
    f.keys = keys[order]
    f.kinds = kinds[order]
    kind_row = np.empty(n_containers, np.int64)
    kind_row[f.kinds == ARRAY] = np.arange(int((f.kinds == ARRAY).sum()))
    kind_row[f.kinds == BITMAP] = np.arange(int((f.kinds == BITMAP).sum()))
    kind_row[f.kinds == RUN] = np.arange(int((f.kinds == RUN).sum()))
    f.kind_row = kind_row
    starts, stops = offs[:-1][order], offs[1:][order]
    a_m, b_m, r_m = (f.kinds == ARRAY), (f.kinds == BITMAP), (f.kinds == RUN)
    f.arr_sel = np.nonzero(a_m)[0]
    f.arr_data = np.ascontiguousarray(
        _gather_ranges(buf8, starts[a_m], stops[a_m])).view("<u2")
    f.arr_off = np.concatenate(
        ([0], np.cumsum((stops[a_m] - starts[a_m]) >> 1))).astype(np.int64)
    f.bmp_sel = np.nonzero(b_m)[0]
    f.bmp_words = np.ascontiguousarray(
        _gather_ranges(buf8, starts[b_m], stops[b_m])
    ).view("<u8").reshape(-1, BITMAP_N_WORDS)
    f.run_sel = np.nonzero(r_m)[0]
    f.run_data = np.ascontiguousarray(
        _gather_ranges(buf8, starts[r_m], stops[r_m])
    ).view("<u2").astype(np.int64).reshape(-1, 2)
    f.run_off = np.concatenate(
        ([0], np.cumsum((stops[r_m] - starts[r_m]) >> 2))).astype(np.int64)
    # cards from the payloads themselves (the reference materializes the
    # full payload regardless of the descriptor cardinality field)
    cards = np.zeros(n_containers, np.int64)
    cards[a_m] = f.arr_off[1:] - f.arr_off[:-1]
    if f.bmp_words.shape[0]:
        cards[b_m] = np.bitwise_count(f.bmp_words).sum(axis=1,
                                                       dtype=np.int64)
    if f.run_data.shape[0]:
        rlens = f.run_data[:, 1] - f.run_data[:, 0] + 1
        per = np.add.reduceat(rlens, f.run_off[:-1])
        per[f.run_off[:-1] == f.run_off[1:]] = 0
        cards[r_m] = per
    f.cards = cards
    _STATS.containers_flattened += n_containers
    return f, int(offs[-1])


class _IrregularSnapshot(Exception):
    """Structurally valid but irregular snapshot (non-canonical payload
    sizes, duplicate keys): take the reference decode path."""


def snapshot_ids(buf) -> tuple[np.ndarray, int]:
    """Sorted ids of a snapshot's payload, straight from the bytes.
    Returns (ids, ops_at). Byte-identical to
    ``deserialize(buf)[0].to_ids()`` — irregular snapshots transparently
    fall back to the reference decoder."""
    try:
        flat, ops_at = flat_from_snapshot(buf)
    except _IrregularSnapshot:
        from pilosa_tpu.roaring.format import deserialize

        bitmap, ops_at = deserialize(buf)
        return bitmap.to_ids(), ops_at
    return fragment_ids(flat), ops_at
