"""64-bit roaring bitmap on the host (numpy-vectorized).

Model follows the reference roaring engine (roaring/roaring.go): values are
uint64, containers are keyed by ``value >> 16`` and hold the low 16 bits in
one of three kinds — sorted uint16 **array**, 1024×uint64 **bitmap**, or
**run** list of inclusive [start, last] uint16 intervals. Unlike the
reference this implementation is vectorized numpy (no per-value loops) and
exists only for durability/interchange; set algebra at query time happens
on device via the fused expression compiler (pilosa_tpu.executor.expr).
"""

from __future__ import annotations

import bisect

import numpy as np

from pilosa_tpu import native

ARRAY = 1
BITMAP = 2
RUN = 3

# Above this cardinality an array container is worse than a bitmap
# (4096 * 2 bytes == 8 KiB == bitmap size), same threshold reasoning as the
# roaring papers (PAPERS.md: Chambi et al.).
ARRAY_MAX = 4096
BITMAP_N_WORDS = 1024  # uint64 words per container (65536 bits)


def _scatter_bits(words8: np.ndarray, lows: np.ndarray) -> None:
    """OR uint16 bit positions into a byte view of a bitmap container."""
    np.bitwise_or.at(
        words8,
        (lows >> np.uint16(3)).astype(np.int64),
        np.uint8(1) << (lows & np.uint16(7)).astype(np.uint8),
    )


class Container:
    __slots__ = ("kind", "data", "n")

    def __init__(self, kind: int, data: np.ndarray, n: int):
        self.kind = kind
        self.data = data
        self.n = n  # cardinality

    # --- constructors ---

    @staticmethod
    def from_lows(lows: np.ndarray) -> "Container":
        """Build the optimal container for sorted unique uint16 lows."""
        n = int(lows.size)
        if n == 0:
            return Container(ARRAY, np.empty(0, np.uint16), 0)
        n_runs = int(np.count_nonzero(np.diff(lows.astype(np.int32)) != 1)) + 1
        # cost in bytes: array 2n, run 4*n_runs, bitmap 8192
        if 4 * n_runs < min(2 * n, 8192):
            d = np.diff(lows.astype(np.int32))
            starts_idx = np.concatenate(([0], np.nonzero(d != 1)[0] + 1))
            ends_idx = np.concatenate((np.nonzero(d != 1)[0], [n - 1]))
            runs = np.stack([lows[starts_idx], lows[ends_idx]], axis=1)
            return Container(RUN, np.ascontiguousarray(runs, np.uint16), n)
        if n <= ARRAY_MAX:
            return Container(ARRAY, np.ascontiguousarray(lows, np.uint16), n)
        words = np.zeros(BITMAP_N_WORDS * 8, np.uint8)
        _scatter_bits(words, lows)
        return Container(BITMAP, words.view("<u8").copy(), n)

    # --- conversions ---

    def lows(self) -> np.ndarray:
        """Sorted unique uint16 values in this container."""
        if self.kind == ARRAY:
            return self.data
        if self.kind == BITMAP:
            bits = np.unpackbits(
                np.ascontiguousarray(self.data).view(np.uint8), bitorder="little"
            )
            return np.nonzero(bits)[0].astype(np.uint16)
        # RUN
        runs = self.data.astype(np.int64)
        if runs.size == 0:
            return np.empty(0, np.uint16)
        lengths = runs[:, 1] - runs[:, 0] + 1
        total = int(lengths.sum())
        out = np.repeat(runs[:, 0] - np.concatenate(([0], np.cumsum(lengths)[:-1])), lengths)
        return (out + np.arange(total)).astype(np.uint16)

    def contains_low(self, low: int) -> bool:
        """O(1)/O(log n) membership for one in-container value — no
        materialization (``lows()`` unpacks all 65536 bits; a bitmap
        container probe must not)."""
        if self.kind == ARRAY:
            i = int(np.searchsorted(self.data, low))
            return i < self.data.size and int(self.data[i]) == low
        if self.kind == BITMAP:
            return bool((int(self.data[low >> 6]) >> (low & 63)) & 1)
        runs = self.data
        if runs.size == 0:
            return False
        i = int(np.searchsorted(runs[:, 0], low, side="right")) - 1
        return i >= 0 and low <= int(runs[i, 1])

    def dense_words32(self) -> np.ndarray:
        """Container as 2048 uint32 words (65536 bits) — device format block.
        Host→device decode hot path: native fastbits when available."""
        if self.kind == BITMAP:
            return np.ascontiguousarray(self.data).view("<u4").copy()
        from pilosa_tpu import native

        if self.kind == RUN:
            fast = native.runs_to_words(self.data)
            if fast is not None:
                return fast
        else:
            fast = native.pack_positions(self.data.astype(np.uint64), 2048)
            if fast is not None:
                return fast
        lows = self.lows()
        words = np.zeros(2048 * 4, np.uint8)
        if lows.size:
            _scatter_bits(words, lows)
        return words.view("<u4").copy()


class RoaringBitmap:
    """Sorted map: container key (high 48 bits) → Container."""

    def __init__(self):
        self.keys: list[int] = []
        self._containers: dict[int, Container] = {}

    # --- constructors ---

    @classmethod
    def from_ids(cls, ids) -> "RoaringBitmap":
        b = cls()
        ids = np.unique(np.asarray(ids, dtype=np.uint64))
        if ids.size == 0:
            return b
        hi = (ids >> np.uint64(16)).astype(np.int64)
        lows = (ids & np.uint64(0xFFFF)).astype(np.uint16)
        boundaries = np.concatenate(
            ([0], np.nonzero(np.diff(hi))[0] + 1, [ids.size])
        )
        for i in range(boundaries.size - 1):
            lo_i, hi_i = int(boundaries[i]), int(boundaries[i + 1])
            key = int(hi[lo_i])
            b._containers[key] = Container.from_lows(lows[lo_i:hi_i])
        b.keys = sorted(b._containers)
        return b

    @classmethod
    def from_dense_words(cls, words: np.ndarray, base: int = 0) -> "RoaringBitmap":
        """From packed uint32 words; bit i → id base + i (base must be
        65536-aligned)."""
        assert base % 65536 == 0
        bits = np.unpackbits(
            np.ascontiguousarray(words, np.uint32).view(np.uint8), bitorder="little"
        )
        ids = np.nonzero(bits)[0].astype(np.uint64) + np.uint64(base)
        return cls.from_ids(ids)

    # --- accessors ---

    def container(self, key: int) -> Container | None:
        return self._containers.get(key)

    def to_ids(self) -> np.ndarray:
        # whole-bitmap materialization rides the vectorized kernel layer:
        # one flatten (lock-free .get + skip, same race discipline as
        # dense_range_words32) then one batched kernel call — the
        # per-container lows() loop lives on only as the test reference
        # (tests/test_roaring_kernels.py pins byte-identity)
        from pilosa_tpu.roaring import kernels

        return kernels.fragment_ids(kernels.flatten(self))

    def count(self) -> int:
        return sum(c.n for c in self._containers.values())

    def count_range(self, start: int, stop: int) -> int:
        if stop <= start:
            return 0
        lo_key, hi_key = start >> 16, (stop - 1) >> 16
        # bisect the sorted key list: count_range is called per written
        # row (ranked-cache refresh), so an O(#containers) scan here turns
        # bulk imports quadratic
        keys = self.keys
        lo_i = bisect.bisect_left(keys, lo_key)
        hi_i = bisect.bisect_right(keys, hi_key)
        total = 0
        for key in keys[lo_i:hi_i]:
            c = self._containers.get(key)
            if c is None:  # lock-free reader racing a remove
                continue
            # fully-covered containers (incl. aligned boundaries — the
            # count_row case) contribute their cardinality without being
            # materialized; only genuinely partial ones unpack
            if key << 16 >= start and (key + 1) << 16 <= stop:
                total += c.n
            else:
                lows = c.lows().astype(np.int64) + (key << 16)
                total += int(((lows >= start) & (lows < stop)).sum())
        return total

    def dense_range_words32(self, start: int, stop: int) -> np.ndarray:
        """Materialize [start, stop) as packed uint32 words (both 65536-aligned).

        This is the host→device decode path: a fragment row (2^20 bits = 16
        containers) becomes uint32[32768] for device_put.
        """
        assert start % 65536 == 0 and stop % 65536 == 0 and stop > start
        n_containers = (stop - start) >> 16
        out = np.zeros((n_containers, 2048), np.uint32)
        base_key = start >> 16
        for i in range(n_containers):
            c = self._containers.get(base_key + i)
            if c is not None:
                out[i] = c.dense_words32()
        return out.reshape(-1)

    def range_ids(self, start: int, stop: int) -> np.ndarray:
        """Sorted ids in [start, stop) — walks only the containers
        overlapping the range. The whole-bitmap ``to_ids()`` is O(total
        population); per-row probes (import_bsi membership, row_columns)
        must not pay that on large fragments."""
        if stop <= start or not self.keys:
            return np.empty(0, np.uint64)
        from pilosa_tpu.roaring import kernels

        # key-bounded flatten + one batched kernel; partial edge
        # containers are trimmed by one vectorized mask inside
        flat = kernels.flatten(self, start >> 16, (stop - 1) >> 16)
        return kernels.range_ids(flat, start, stop)

    def contains_lows(self, key: int, lows: np.ndarray) -> np.ndarray:
        """Vectorized membership of uint16 lows in ONE container, probed
        in place (no decode): ARRAY by searchsorted, BITMAP by word bit
        test, RUN by interval search."""
        c = self._containers.get(key)
        if c is None or c.n == 0:
            return np.zeros(lows.size, bool)
        if c.kind == ARRAY:
            idx = np.searchsorted(c.data, lows)
            idx_c = np.minimum(idx, c.data.size - 1)
            return (idx < c.data.size) & (c.data[idx_c] == lows)
        if c.kind == BITMAP:
            w = c.data  # uint64 words
            word = w[(lows >> np.uint16(6)).astype(np.int64)]
            bit = (lows & np.uint16(63)).astype(np.uint64)
            return ((word >> bit) & np.uint64(1)).astype(bool)
        starts = c.data[:, 0]
        lasts = c.data[:, 1]
        i = np.searchsorted(starts, lows, side="right") - 1
        ok = i >= 0
        i_c = np.maximum(i, 0)
        return ok & (lows <= lasts[i_c])

    def row_member(self, row: int, positions: np.ndarray) -> np.ndarray:
        """Vectorized membership of in-shard positions in one row.
        Probes only the containers the positions land in — O(batch·log)
        per row, independent of the row's population (the import hot
        paths must not decode whole rows to clear a handful of bits)."""
        ids = (np.uint64(row) << np.uint64(20)) + positions
        his = (ids >> np.uint64(16)).astype(np.int64)
        lows = (ids & np.uint64(0xFFFF)).astype(np.uint16)
        out = np.zeros(positions.size, bool)
        for key in np.unique(his).tolist():
            m = his == key
            out[m] = self.contains_lows(int(key), lows[m])
        return out

    # --- mutation (op-log replay + write path) ---

    def add_ids(self, ids) -> int:
        """Set bits; returns number actually changed (reference Add)."""
        return self._merge(ids, remove=False)

    def remove_ids(self, ids) -> int:
        return self._merge(ids, remove=True)

    def _merge(self, ids, remove: bool) -> int:
        """Dispatch a mutation batch: whole-batch merge kernel
        (roaring/merge_kernels.py — single numpy dispatches across ALL
        touched containers, GIL released inside them) above the size
        threshold, the per-container loop below it (a point write must
        not pay batch bookkeeping). Both produce byte-identical
        containers — tests/test_merge_kernels.py pins the property, so
        the threshold is pure performance tuning."""
        ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
        if ids.size == 0:
            return 0
        from pilosa_tpu.roaring import merge_kernels

        if ids.size >= merge_kernels.KERNEL_MIN_IDS:
            return merge_kernels.merge_ids(self, ids, remove)
        merge_kernels.global_merge_stats().loop_fallbacks += 1
        return self._merge_loop(ids, remove)

    def _merge_loop(self, ids: np.ndarray, remove: bool) -> int:
        """The per-container merge loop: small-batch fast path AND the
        byte-identity reference for the whole-batch kernel (the same
        role the retired per-container read paths play in
        tests/test_roaring_kernels.py)."""
        ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
        if ids.size == 0:
            return 0
        # bulk imports arrive pre-sorted ((row<<20)+sorted positions per
        # row); skip np.unique's unconditional O(n log n) sort for them
        # and dedupe sorted input with one vectorized compare
        if ids.size > 1:
            if not bool(np.all(ids[1:] >= ids[:-1])):
                ids = np.sort(ids)
            ids = ids[np.concatenate(([True], ids[1:] != ids[:-1]))]
        hi = (ids >> np.uint64(16)).astype(np.int64)
        lows = (ids & np.uint64(0xFFFF)).astype(np.uint16)
        boundaries = np.concatenate(
            ([0], np.nonzero(np.diff(hi))[0] + 1, [ids.size])
        )
        changed = 0
        dirty = False
        for i in range(boundaries.size - 1):
            lo_i, hi_i = int(boundaries[i]), int(boundaries[i + 1])
            key = int(hi[lo_i])
            batch = lows[lo_i:hi_i]
            c = self._containers.get(key)
            delta = None
            # fast paths: scatter straight into a 1024-word bitmap instead
            # of unpack + sort + rebuild — the bulk-import hot loop
            if c is not None and c.kind == BITMAP:
                delta = self._merge_bitmap_inplace(key, c, batch, remove)
            elif (not remove and c is not None and c.kind == ARRAY
                  and c.n + batch.size > ARRAY_MAX):
                # promote via a temporary (not yet installed) bitmap; the
                # merge helper swaps in the final consistent container
                words = np.zeros(BITMAP_N_WORDS * 8, np.uint8)
                _scatter_bits(words, c.data)
                tmp = Container(BITMAP, words.view("<u8"), c.n)
                delta = self._merge_bitmap_inplace(key, tmp, batch, remove)
            elif not remove and c is None and batch.size > ARRAY_MAX:
                self._containers[key] = Container.from_lows(batch)
                delta = int(batch.size)
            if delta is None:
                existing = c.lows() if c is not None else np.empty(0, np.uint16)
                # both sides are sorted unique (container invariant;
                # batch is a slice of the deduped sorted ids) — the
                # native two-pointer merge beats union1d's concat+sort
                if remove:
                    new = native.diff_sorted_u16(existing, batch)
                    if new is None:
                        new = np.setdiff1d(existing, batch,
                                           assume_unique=True)
                else:
                    new = native.union_sorted_u16(existing, batch)
                    if new is None:
                        new = np.union1d(existing, batch)
                delta = abs(int(new.size) - int(existing.size))
                if delta and new.size == 0:
                    self._containers.pop(key, None)
                elif delta:
                    self._containers[key] = Container.from_lows(new)
            if delta == 0:
                continue
            changed += delta
            dirty = True
        if dirty:
            self.keys = sorted(self._containers)
        return changed

    def _merge_bitmap_inplace(self, key: int, c: Container, batch, remove: bool) -> int:
        """Scatter a unique uint16 batch into a copy of a BITMAP container
        and swap the new container in atomically (readers and snapshots
        always see a self-consistent immutable container — no torn
        data/cardinality under the threaded server). Returns the
        cardinality delta (container removed when emptied)."""
        words8 = np.array(c.data.view(np.uint8))  # 8 KiB copy, writable
        if remove:
            idx = (batch >> np.uint16(3)).astype(np.int64)
            np.bitwise_and.at(
                words8, idx,
                np.uint8(0xFF) ^ (np.uint8(1) << (batch & np.uint16(7)).astype(np.uint8)),
            )
        else:
            _scatter_bits(words8, batch)
        new_n = int(np.bitwise_count(words8).sum(dtype=np.int64))
        delta = abs(new_n - c.n)
        if new_n == 0:
            self._containers.pop(key, None)
        elif delta == 0:
            pass  # unchanged: keep the existing container
        elif new_n <= ARRAY_MAX:
            # shrunk (or overlap-heavy add) below the bitmap break-even:
            # rebuild the optimal array/run form instead of keeping 8 KiB
            new_c = Container(BITMAP, words8.view("<u8"), new_n)
            self._containers[key] = Container.from_lows(new_c.lows())
        else:
            self._containers[key] = Container(BITMAP, words8.view("<u8"), new_n)
        return delta

    def __contains__(self, id_: int) -> bool:
        c = self._containers.get(int(id_) >> 16)
        if c is None:
            return False
        return c.contains_low(int(id_) & 0xFFFF)

    def __eq__(self, other) -> bool:
        if not isinstance(other, RoaringBitmap):
            return NotImplemented
        return self.keys == other.keys and all(
            np.array_equal(self._containers[k].lows(), other._containers[k].lows())
            for k in self.keys
        )
