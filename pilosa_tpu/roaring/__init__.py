"""Host-side roaring bitmap: the durable storage / interchange format.

The reference keeps its entire engine in roaring containers
(roaring/roaring.go); on TPU we deliberately flip the representation
(SURVEY.md §7.1): device bitmaps are dense bit-packed tensors, and roaring
survives only on the host as (a) the on-disk fragment format with an
append-only op log, and (b) the wire format for import-roaring. This
package implements the 64-bit roaring model: containers keyed by the high
48 bits, each holding low-16-bit values as an array / bitmap / run
container, plus serialization and the op log.
"""

from pilosa_tpu.roaring.bitmap import (
    RoaringBitmap,
    ARRAY,
    BITMAP,
    RUN,
)
from pilosa_tpu.roaring.format import (
    serialize,
    deserialize,
    replay_ops,
    OP_ADD,
    OP_REMOVE,
)
