"""Vectorized whole-batch roaring MERGE kernels (write path).

The read side went through this refactor first: roaring/kernels.py
turned per-container decode/digest/diff loops into whole-fragment numpy
dispatches. The write side stayed a per-container Python loop
(``RoaringBitmap._merge_loop``): one union/diff + one ``from_lows``
rebuild per touched container, ~6-10 tiny numpy dispatches each, all
GIL-held — which is why bulk import measured flat at 1/2/4
``ingest-workers`` (docs/INGEST.md). This module is the batched
counterpart, after the same roaring blueprint (arXiv:1709.07821,
arXiv:1611.07612): a sorted id batch merges into ALL touched containers
with a fixed number of whole-batch numpy dispatches —

- **word space**: every touched BITMAP container (and ARRAY containers
  the reference would promote) stacks into one (n, 8192)-byte matrix;
  the batch ORs (or ANDNOT-clears) in with a single scatter, and
  cardinalities come from one vectorized popcount;
- **sorted-id space**: every other touched container's payload gathers
  into one globally sorted stream (arrays are memcpy slices, runs
  expand in one vectorized pass) that merges with the batch in a single
  union/setdiff;
- **density decisions**: the array↔run↔bitmap conversion each rebuilt
  container needs is decided for ALL of them in one vectorized pass
  over per-segment cardinalities and run counts (the exact
  ``Container.from_lows`` cost model), then built from slices.

Contract: **byte-identity** with ``RoaringBitmap._merge_loop`` — the
retired per-container write loop lives on in bitmap.py as the
small-batch fast path and the test reference
(tests/test_merge_kernels.py pins the property over randomized and
adversarial batches, including the reference's non-canonical edges: a
bitmap that stays a bitmap above ARRAY_MAX even where runs would be
cheaper, delta-0 containers kept untouched, and the ARRAY promote
threshold measured against the PRE-dedup segment size).

Also here: the batched membership probes behind the mutex-clear and
BSI-plane merge rules (``set_rows_for_positions``, ``member_matrix``) —
the per-row ``row_member`` loops the import paths used to run. The
per-container loops in THIS module are the sanctioned ones (metadata
gather + slice/memcpy only), mirroring ``kernels.flatten``; consumer
modules are lint-clean (scripts/check_hostpath_loops.py).
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.roaring.bitmap import (
    ARRAY,
    ARRAY_MAX,
    BITMAP,
    BITMAP_N_WORDS,
    RUN,
    Container,
)

_LOW = np.uint64(0xFFFF)
_U16 = np.uint64(16)
_C_BYTES = BITMAP_N_WORDS * 8  # 8192 bytes per container bitmap

_EMPTY_I64 = np.empty(0, np.int64)
_EMPTY_I64.setflags(write=False)

# Below this batch size the per-container loop wins: a point write
# (set_bit/clear_bit) touches one container, and the kernel's global
# bookkeeping (segmenting, group masks, stacked gathers) costs more
# than the handful of dispatches the loop pays. Measured crossover on
# this class of host is well under 64 ids; the exact value is pure
# tuning — both paths are byte-identical.
KERNEL_MIN_IDS = 64


# ------------------------------------------------------------- statistics


class MergeStats:
    """Process-wide write-kernel counters (``ingest_merge_*`` series on
    /metrics). Plain int adds, no lock — dashboards, not invariants,
    same posture as kernels.KernelStats."""

    __slots__ = ("kernel_calls", "ids_merged", "containers_merged",
                 "word_space_merges", "stream_merges", "canonical_builds",
                 "loop_fallbacks", "probe_calls")

    def __init__(self):
        self.kernel_calls = 0       # whole-batch merge invocations
        self.ids_merged = 0         # deduped ids pushed through kernels
        self.containers_merged = 0  # touched containers across all calls
        self.word_space_merges = 0  # containers merged as bitmap OR/ANDNOT
        self.stream_merges = 0      # containers merged in sorted-id space
        self.canonical_builds = 0   # containers rebuilt via the density pass
        self.loop_fallbacks = 0     # small batches served by _merge_loop
        self.probe_calls = 0        # batched mutex/BSI membership probes

    def metrics(self) -> dict:
        return {
            "ingest_merge_kernel_calls_total": self.kernel_calls,
            "ingest_merge_ids_total": self.ids_merged,
            "ingest_merge_containers_total": self.containers_merged,
            "ingest_merge_word_space_total": self.word_space_merges,
            "ingest_merge_stream_total": self.stream_merges,
            "ingest_merge_canonical_builds_total": self.canonical_builds,
            "ingest_merge_loop_fallbacks_total": self.loop_fallbacks,
            "ingest_merge_probe_calls_total": self.probe_calls,
        }


_STATS = MergeStats()


def global_merge_stats() -> MergeStats:
    return _STATS


# ------------------------------------------------------------ the kernel


def merge_ids(bm, ids: np.ndarray, remove: bool = False) -> int:
    """Merge a whole id batch into ``bm``'s containers; returns #bits
    changed. Byte-identical to ``RoaringBitmap._merge_loop`` on the same
    input (the contract every consumer relies on: op-log replay, CDC
    apply, and anti-entropy all route through one of the two).

    ``ids`` may be unsorted/duplicated; it is deduped exactly like the
    reference. The caller holds whatever lock it held for the loop path
    — container installs remain one-at-a-time atomic dict swaps, so
    lock-free readers keep seeing self-consistent containers."""
    ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
    if ids.size == 0:
        return 0
    if ids.size > 1:
        if not bool(np.all(ids[1:] >= ids[:-1])):
            ids = np.sort(ids)
        ids = ids[np.concatenate(([True], ids[1:] != ids[:-1]))]

    his = (ids >> _U16).astype(np.int64)
    bounds = np.concatenate(([0], np.nonzero(np.diff(his))[0] + 1,
                             [ids.size]))
    seg_keys = his[bounds[:-1]]
    seg_sizes = np.diff(bounds)
    nseg = int(seg_keys.size)

    _STATS.kernel_calls += 1
    _STATS.ids_merged += int(ids.size)
    _STATS.containers_merged += nseg

    # the sanctioned metadata gather: container refs + (kind, n) arrays
    conts = [bm._containers.get(int(k)) for k in seg_keys.tolist()]
    kinds = np.fromiter((0 if c is None else c.kind for c in conts),
                        np.int64, nseg)
    # word-space delta accounting uses the maintained cardinality (the
    # reference compares against c.n); stream-space uses actual payload
    # sizes (the reference compares against materialized lows)
    ns_attr = np.fromiter((0 if c is None else c.n for c in conts),
                          np.int64, nseg)

    # the reference's promote rule measures c.n against the PRE-dedup
    # segment size — here segments are already deduped, which is the
    # same value (dedup happens before the loop there too)
    word_like = kinds == BITMAP
    if not remove:
        word_like |= (kinds == ARRAY) & (ns_attr + seg_sizes > ARRAY_MAX)

    installs: dict[int, Container | None] = {}  # None = pop
    changed = 0

    # element -> segment row map, shared by both groups
    seg_of = np.repeat(np.arange(nseg), seg_sizes)

    # ------------------------------------------------ word-space group
    wsel = np.nonzero(word_like)[0]
    if wsel.size:
        _STATS.word_space_merges += int(wsel.size)
        words8 = np.zeros((wsel.size, _C_BYTES), np.uint8)
        arr_rows: list[int] = []
        arr_datas: list[np.ndarray] = []
        for j, i in enumerate(wsel.tolist()):  # memcpy-only gather loop
            c = conts[i]
            if c.kind == BITMAP:
                words8[j] = c.data.view(np.uint8)
            else:  # ARRAY crossing the promote threshold
                arr_rows.append(j)
                arr_datas.append(c.data)
        flat8 = words8.reshape(-1)
        if arr_datas:
            # promote every crossing array with ONE global scatter
            lows = np.concatenate(arr_datas)
            rep = np.repeat(
                np.asarray(arr_rows, np.int64),
                np.fromiter((d.size for d in arr_datas), np.int64,
                            len(arr_datas)))
            np.bitwise_or.at(
                flat8,
                rep * _C_BYTES + (lows >> np.uint16(3)).astype(np.int64),
                np.uint8(1) << (lows & np.uint16(7)).astype(np.uint8))
        # scatter the batch into the stacked words
        row_of = np.full(nseg, -1, np.int64)
        row_of[wsel] = np.arange(wsel.size)
        elem_row = row_of[seg_of]
        m = elem_row >= 0
        blows = (ids[m] & _LOW).astype(np.uint16)
        byte_idx = (elem_row[m] * _C_BYTES
                    + (blows >> np.uint16(3)).astype(np.int64))
        bit = np.uint8(1) << (blows & np.uint16(7)).astype(np.uint8)
        if remove:
            np.bitwise_and.at(flat8, byte_idx, np.uint8(0xFF) ^ bit)
        else:
            np.bitwise_or.at(flat8, byte_idx, bit)
        new_ns = np.bitwise_count(words8).sum(axis=1, dtype=np.int64)
        deltas = np.abs(new_ns - ns_attr[wsel])
        changed += int(deltas.sum())

        moved = deltas > 0
        for j in np.nonzero(moved & (new_ns == 0))[0].tolist():
            installs[int(seg_keys[wsel[j]])] = None
        for j in np.nonzero(moved & (new_ns > ARRAY_MAX))[0].tolist():
            # above the break-even a bitmap STAYS a bitmap (the
            # reference never reconsiders runs here) — non-canonical
            # on purpose, byte-identical to the loop
            installs[int(seg_keys[wsel[j]])] = Container(
                BITMAP, words8[j].copy().view("<u8"), int(new_ns[j]))
        shrunk = np.nonzero(moved & (new_ns > 0)
                            & (new_ns <= ARRAY_MAX))[0]
        if shrunk.size:
            # one batched unpack for every shrunken container, then the
            # shared canonical builder (reference: from_lows(lows()))
            bits = np.unpackbits(words8[shrunk], axis=1,
                                 bitorder="little")
            rows, cols = np.nonzero(bits)
            lows16 = cols.astype(np.uint16)
            los = np.searchsorted(rows, np.arange(shrunk.size))
            his_b = np.append(los[1:], rows.size)
            _canonical_into(installs, seg_keys[wsel[shrunk]],
                            lows16, los, his_b)

    # ------------------------------------------------ sorted-id group
    gsel = np.nonzero(~word_like)[0]
    if gsel.size:
        _STATS.stream_merges += int(gsel.size)
        g_keys = seg_keys[gsel]
        # actual payload sizes (ARRAY: data.size; RUN: expanded length)
        g_ns = np.zeros(gsel.size, np.int64)
        run_dst: list[int] = []
        run_blocks: list[np.ndarray] = []
        for j, i in enumerate(gsel.tolist()):  # metadata gather loop
            c = conts[i]
            if c is None:
                continue
            if c.kind == ARRAY:
                g_ns[j] = c.data.size
            else:  # RUN (BITMAP is always word-space)
                runs = c.data.astype(np.int64)
                g_ns[j] = int((runs[:, 1] - runs[:, 0] + 1).sum())
                run_dst.append(j)
                run_blocks.append(runs)
        off = np.concatenate(([0], np.cumsum(g_ns)))
        ex_lows = np.empty(int(off[-1]), np.uint16)
        for j, i in enumerate(gsel.tolist()):  # memcpy-only fill loop
            c = conts[i]
            if c is not None and c.kind == ARRAY:
                ex_lows[off[j]:off[j + 1]] = c.data
        if run_blocks:
            # expand ALL run payloads in one vectorized pass (the
            # kernels._run_ids idiom), then memcpy each block home
            runs = np.concatenate(run_blocks)
            lens = runs[:, 1] - runs[:, 0] + 1
            base = np.repeat(
                runs[:, 0] - np.concatenate(([0], np.cumsum(lens)[:-1])),
                lens)
            run_lows = (base + np.arange(int(lens.sum()))).astype(
                np.uint16)
            r0 = 0
            for j in run_dst:
                n = int(g_ns[j])
                ex_lows[off[j]:off[j + 1]] = run_lows[r0:r0 + n]
                r0 += n
        ex_ids = (ex_lows.astype(np.uint64)
                  + (np.repeat(g_keys, g_ns).astype(np.uint64) << _U16))

        if wsel.size:
            b_ids = ids[row_of[seg_of] < 0]
        else:
            b_ids = ids
        if remove:
            from pilosa_tpu.roaring.kernels import setdiff_sorted

            merged = setdiff_sorted(ex_ids, b_ids)
        elif ex_ids.size == 0:
            merged = b_ids
        elif b_ids.size == 0:
            merged = ex_ids
        else:
            # both streams are sorted + deduped, so union is a linear
            # two-way merge: scatter the batch into its merged slots
            # instead of re-sorting the concatenation
            out = np.empty(ex_ids.size + b_ids.size, np.uint64)
            bmask = np.zeros(out.size, bool)
            bmask[np.searchsorted(ex_ids, b_ids)
                  + np.arange(b_ids.size)] = True
            out[bmask] = b_ids
            out[~bmask] = ex_ids
            merged = out[np.concatenate(([True], out[1:] != out[:-1]))]

        key_base = g_keys.astype(np.uint64) << _U16
        mlo = np.searchsorted(merged, key_base)
        mhi = np.searchsorted(merged, key_base + np.uint64(1 << 16))
        new_ns = (mhi - mlo).astype(np.int64)
        deltas = np.abs(new_ns - g_ns)
        changed += int(deltas.sum())
        moved = deltas > 0
        for j in np.nonzero(moved & (new_ns == 0))[0].tolist():
            installs[int(g_keys[j])] = None
        bsel = np.nonzero(moved & (new_ns > 0))[0]
        if bsel.size:
            _canonical_into(installs, g_keys[bsel],
                            (merged & _LOW).astype(np.uint16),
                            mlo[bsel].astype(np.int64),
                            mhi[bsel].astype(np.int64))

    if changed:
        for key, c in installs.items():
            if c is None:
                bm._containers.pop(key, None)
            else:
                bm._containers[key] = c
        bm.keys = sorted(bm._containers)
    return changed


def _canonical_into(installs: dict, keys: np.ndarray, lows16: np.ndarray,
                    los: np.ndarray, his: np.ndarray) -> None:
    """Build the canonical (``Container.from_lows``-identical) container
    for each segment ``[los[j], his[j])`` of the shared ``lows16``
    stream, installing under ``keys[j]``. The kind decision — the
    density-driven array↔run↔bitmap conversion — is computed for ALL
    segments in one vectorized pass over cardinalities and run counts;
    the per-segment loop below only slices and wraps. Segments must be
    non-empty and need not be contiguous in the stream."""
    n = (his - los).astype(np.int64)
    _STATS.canonical_builds += int(keys.size)
    if lows16.size > 1:
        gap_idx = np.nonzero(
            (lows16[1:].astype(np.int32)
             - lows16[:-1].astype(np.int32)) != 1)[0]
    else:
        gap_idx = _EMPTY_I64
    # breaks strictly inside each segment; size-1 segments have none
    g_lo = np.searchsorted(gap_idx, los)
    g_hi = np.searchsorted(gap_idx, np.maximum(his - 1, los))
    n_runs = (g_hi - g_lo) + 1
    # the from_lows cost model, verbatim: run 4 bytes/run beats
    # min(array 2n, bitmap 8192)
    run_kind = 4 * n_runs < np.minimum(2 * n, 8192)
    arr_kind = ~run_kind & (n <= ARRAY_MAX)
    bmp_kind = ~run_kind & ~arr_kind

    bsel = np.nonzero(bmp_kind)[0]
    if bsel.size:
        # batch-scatter every bitmap build at once
        words8 = np.zeros((bsel.size, _C_BYTES), np.uint8)
        flat8 = words8.reshape(-1)
        rep = np.repeat(np.arange(bsel.size), n[bsel])
        sel = np.concatenate([np.arange(los[j], his[j])
                              for j in bsel.tolist()])
        blows = lows16[sel]
        np.bitwise_or.at(
            flat8,
            rep * _C_BYTES + (blows >> np.uint16(3)).astype(np.int64),
            np.uint8(1) << (blows & np.uint16(7)).astype(np.uint8))
        for j2, j in enumerate(bsel.tolist()):
            installs[int(keys[j])] = Container(
                BITMAP, words8[j2].view("<u8").copy(), int(n[j]))

    for j in np.nonzero(run_kind)[0].tolist():  # slice/assemble loop
        lo, hi = int(los[j]), int(his[j])
        g = gap_idx[g_lo[j]:g_hi[j]]
        starts = np.empty(g.size + 1, np.int64)
        starts[0] = lo
        starts[1:] = g + 1
        ends = np.empty(g.size + 1, np.int64)
        ends[:-1] = g
        ends[-1] = hi - 1
        runs = np.stack([lows16[starts], lows16[ends]], axis=1)
        installs[int(keys[j])] = Container(
            RUN, np.ascontiguousarray(runs, np.uint16), int(n[j]))

    asel = np.nonzero(arr_kind)[0]
    if asel.size:
        # ONE global gather copies every array payload out of the shared
        # stream; containers hold contiguous views into it (exactly the
        # payload bytes are retained, nothing else)
        ln = n[asel]
        offs = np.concatenate(([0], np.cumsum(ln)))
        idx = (np.repeat(los[asel].astype(np.int64) - offs[:-1], ln)
               + np.arange(int(offs[-1])))
        buf = lows16[idx]
        a_keys = keys[asel]
        for j2, j in enumerate(asel.tolist()):  # slice/wrap-only loop
            installs[int(a_keys[j2])] = Container(
                ARRAY, buf[offs[j2]:offs[j2 + 1]], int(ln[j2]))


# --------------------------------------------------- membership probes


def set_rows_for_positions(bm, positions: np.ndarray
                           ) -> tuple[np.ndarray, np.ndarray]:
    """Every (row, position-index) pair currently set, for one in-shard
    position batch: the batched mutex-clear probe. Replaces the per-row
    ``row_member`` loop over ALL fragment rows — this walks each
    existing container at most once, probing only the batch positions
    that land in its sub-container slot, each probe vectorized
    in place (no decode). Returns ``(rows, pos_idx)`` int64 arrays."""
    pos = np.asarray(positions, np.uint64)
    keys = bm.keys
    if pos.size == 0 or not keys:
        return _EMPTY_I64, _EMPTY_I64
    _STATS.probe_calls += 1
    slots = (pos >> _U16).astype(np.int64)  # sub-container 0..15
    order = np.argsort(slots, kind="stable")
    sorted_slots = slots[order]
    lows = (pos & _LOW).astype(np.uint16)
    hit_rows: list[np.ndarray] = []
    hit_idx: list[np.ndarray] = []
    for key in keys:  # sanctioned probe loop: one vectorized probe each
        lo = int(np.searchsorted(sorted_slots, key & 15, side="left"))
        hi = int(np.searchsorted(sorted_slots, key & 15, side="right"))
        if lo == hi:
            continue
        sel = order[lo:hi]
        m = bm.contains_lows(key, lows[sel])
        if m.any():
            found = sel[m]
            hit_idx.append(found)
            hit_rows.append(np.full(found.size, key >> 4, np.int64))
    if not hit_idx:
        return _EMPTY_I64, _EMPTY_I64
    return (np.concatenate(hit_rows),
            np.concatenate(hit_idx).astype(np.int64))


def member_matrix(bm, rows, positions: np.ndarray) -> np.ndarray:
    """Membership of ``positions`` in each of ``rows``, as one
    (len(rows), len(positions)) bool matrix — the batched BSI-plane
    probe (exists row + every bit plane in one call instead of a
    ``row_member`` pass per plane). Probes only containers that exist,
    one vectorized ``contains_lows`` per (row, slot) pair."""
    pos = np.asarray(positions, np.uint64)
    out = np.zeros((len(rows), pos.size), bool)
    if pos.size == 0 or not bm.keys:
        return out
    _STATS.probe_calls += 1
    slots = (pos >> _U16).astype(np.int64)
    order = np.argsort(slots, kind="stable")
    sorted_slots = slots[order]
    uniq_slots = np.unique(sorted_slots)
    slot_lo = np.searchsorted(sorted_slots, uniq_slots, side="left")
    slot_hi = np.searchsorted(sorted_slots, uniq_slots, side="right")
    lows = (pos & _LOW).astype(np.uint16)
    for i, r in enumerate(rows):  # sanctioned probe loop
        base_key = int(r) << 4
        for s, lo, hi in zip(uniq_slots.tolist(), slot_lo.tolist(),
                             slot_hi.tolist()):
            key = base_key | int(s)
            if bm._containers.get(key) is None:
                continue
            sel = order[lo:hi]
            out[i, sel] = bm.contains_lows(key, lows[sel])
    return out
