"""Roaring file format + append-only op log (host durability layer).

Mirrors the reference's fragment storage file design (roaring/roaring.go
WriteTo/UnmarshalBinary + the op-log section; fragment.go snapshot —
SURVEY.md §2 #1, #3): a serialized container snapshot followed by an
append-only log of add/remove batches, replayed on open and compacted
("snapshot") once the op count crosses a threshold. The byte layout is this
framework's own (the reference mount was empty — see SURVEY.md EVIDENCE
STATUS — so byte-level compatibility is unverifiable; the *model* is kept:
cookie, container descriptors [key, kind, cardinality], offsets, container
payloads, trailing ops).

Layout (little-endian):
  header:  magic uint32 = 0x50C4B175, version uint16, flags uint16,
           container_count uint32, payload_bytes uint64
  descrs:  container_count × (key uint64, kind uint16, n_minus_1 uint16,
           payload_len uint32)
  payload: concatenated container data
           array: n × uint16 | bitmap: 1024 × uint64 | run: n_runs × 2 × uint16
  ops:     sequence of records until EOF:
           op_magic uint16 = 0x4F50, op uint16 (1=add 2=remove),
           id_count uint32, crc32 uint32 (over ids bytes), ids × uint64
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN, Container, RoaringBitmap

MAGIC = 0x50C4B175
VERSION = 1
_HEADER = struct.Struct("<IHHIQ")
_DESCR = struct.Struct("<QHHI")

OP_MAGIC = 0x4F50
OP_ADD = 1
OP_REMOVE = 2
_OP_HEADER = struct.Struct("<HHII")


def serialize(bitmap: RoaringBitmap) -> bytes:
    descrs = []
    payloads = []
    for key in bitmap.keys:
        c = bitmap.container(key)
        data = np.ascontiguousarray(c.data)
        raw = data.astype(
            {ARRAY: "<u2", BITMAP: "<u8", RUN: "<u2"}[c.kind], copy=False
        ).tobytes()
        descrs.append(_DESCR.pack(key, c.kind, c.n - 1, len(raw)))
        payloads.append(raw)
    payload = b"".join(payloads)
    header = _HEADER.pack(MAGIC, VERSION, 0, len(descrs), len(payload))
    return header + b"".join(descrs) + payload


def deserialize(buf: bytes | memoryview) -> tuple[RoaringBitmap, int]:
    """Parse a snapshot; returns (bitmap, offset-where-ops-begin)."""
    buf = memoryview(buf)
    if len(buf) < _HEADER.size:
        raise ValueError("roaring: truncated header")
    magic, version, _flags, n_containers, payload_bytes = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"roaring: bad magic 0x{magic:08X}")
    if version != VERSION:
        raise ValueError(f"roaring: unsupported version {version}")
    pos = _HEADER.size
    b = RoaringBitmap()
    descr_end = pos + n_containers * _DESCR.size
    data_pos = descr_end
    for _ in range(n_containers):
        key, kind, n_minus_1, payload_len = _DESCR.unpack_from(buf, pos)
        pos += _DESCR.size
        raw = buf[data_pos : data_pos + payload_len]
        if len(raw) != payload_len:
            raise ValueError("roaring: truncated container payload")
        data_pos += payload_len
        n = n_minus_1 + 1
        if kind == ARRAY:
            data = np.frombuffer(raw, dtype="<u2").copy()
        elif kind == BITMAP:
            data = np.frombuffer(raw, dtype="<u8").copy()
        elif kind == RUN:
            data = np.frombuffer(raw, dtype="<u2").copy().reshape(-1, 2)
        else:
            raise ValueError(f"roaring: unknown container kind {kind}")
        b._containers[int(key)] = Container(kind, data, n)
    b.keys = sorted(b._containers)
    expected_end = descr_end + payload_bytes
    if data_pos != expected_end:
        raise ValueError("roaring: payload length mismatch")
    return b, data_pos


def encode_op(op: int, ids) -> bytes:
    ids = np.ascontiguousarray(np.asarray(ids, dtype=np.uint64))
    raw = ids.astype("<u8", copy=False).tobytes()
    return _OP_HEADER.pack(OP_MAGIC, op, ids.size, zlib.crc32(raw)) + raw


def replay_ops(bitmap: RoaringBitmap, buf: bytes | memoryview, offset: int) -> int:
    """Apply trailing op records onto the snapshot; returns op count.

    A torn final record (crash mid-append) is tolerated and ignored,
    matching the reference's crash model for the op log.
    """
    buf = memoryview(buf)
    n_ops = 0
    pos = offset
    while pos + _OP_HEADER.size <= len(buf):
        magic, op, id_count, crc = _OP_HEADER.unpack_from(buf, pos)
        if magic != OP_MAGIC:
            break
        body_end = pos + _OP_HEADER.size + id_count * 8
        if body_end > len(buf):
            break  # torn write
        raw = bytes(buf[pos + _OP_HEADER.size : body_end])
        if zlib.crc32(raw) != crc:
            break  # torn/corrupt tail
        ids = np.frombuffer(raw, dtype="<u8")
        if op == OP_ADD:
            bitmap.add_ids(ids)
        elif op == OP_REMOVE:
            bitmap.remove_ids(ids)
        n_ops += 1
        pos = body_end
    return n_ops


# --------------------------------------------------------- upstream layout
#
# Best-effort reader/writer for the REFERENCE's own roaring file layout
# (pilosa roaring.go, 64-bit variant), reconstructed from knowledge of the
# upstream code because the reference mount was empty at survey time
# (SURVEY.md EVIDENCE STATUS) — confidence MED, unverified byte-for-byte:
#   cookie  uint32 = 12348 | storage_version<<16
#   keyN    uint32
#   descrs  keyN × (key uint64, container_type uint16 (1=array 2=bitmap
#           3=run), cardinality-1 uint16)
#   offsets keyN × uint32 (absolute file offset of container data)
#   data    array: n×uint16 | bitmap: 1024×uint64 |
#           run: run_count uint16, then run_count×(start,last) uint16
#   ops     records: type uint8 (0=add 1=remove), value uint64,
#           fnv1a32(first 9 bytes) uint32   (upstream uses fnv.New32a,
#           NOT CRC-32 — ADVICE r1)
# import-roaring sniffs this cookie and falls back to our own layout.

PILOSA_MAGIC = 12348
_P_HEADER = struct.Struct("<II")
_P_DESCR = struct.Struct("<QHH")
_P_OFFSET = struct.Struct("<I")
_P_OP = struct.Struct("<BQI")


def serialize_pilosa(bitmap: RoaringBitmap) -> bytes:
    """Write the upstream layout (export interop; confidence MED)."""
    n = len(bitmap.keys)
    header_len = _P_HEADER.size + n * (_P_DESCR.size + _P_OFFSET.size)
    descrs, offsets, payloads = [], [], []
    pos = header_len
    for key in bitmap.keys:
        c = bitmap.container(key)
        if c.kind == RUN:
            body = struct.pack("<H", len(c.data)) + np.ascontiguousarray(
                c.data
            ).astype("<u2", copy=False).tobytes()
        else:
            dtype = "<u2" if c.kind == ARRAY else "<u8"
            body = np.ascontiguousarray(c.data).astype(dtype, copy=False).tobytes()
        descrs.append(_P_DESCR.pack(key, c.kind, c.n - 1))
        offsets.append(_P_OFFSET.pack(pos))
        payloads.append(body)
        pos += len(body)
    return (_P_HEADER.pack(PILOSA_MAGIC, n) + b"".join(descrs)
            + b"".join(offsets) + b"".join(payloads))


def deserialize_pilosa(buf: bytes | memoryview) -> tuple[RoaringBitmap, int]:
    """Parse the upstream layout; returns (bitmap, offset-where-ops-begin).
    Truncated/malformed input raises ValueError (never struct.error)."""
    try:
        return _deserialize_pilosa(memoryview(buf))
    except struct.error as e:
        raise ValueError(f"roaring: truncated pilosa layout: {e}") from None


def _deserialize_pilosa(buf: memoryview) -> tuple[RoaringBitmap, int]:
    cookie, n = _P_HEADER.unpack_from(buf, 0)
    if cookie & 0xFFFF != PILOSA_MAGIC:
        raise ValueError(f"roaring: bad pilosa cookie 0x{cookie:08X}")
    pos = _P_HEADER.size
    descrs = []
    for _ in range(n):
        descrs.append(_P_DESCR.unpack_from(buf, pos))
        pos += _P_DESCR.size
    offsets = []
    for _ in range(n):
        offsets.append(_P_OFFSET.unpack_from(buf, pos)[0])
        pos += _P_OFFSET.size
    b = RoaringBitmap()
    end = pos
    for (key, kind, n_minus_1), off in zip(descrs, offsets):
        card = n_minus_1 + 1
        if kind == ARRAY:
            data = np.frombuffer(buf, dtype="<u2", count=card, offset=off).copy()
            end = max(end, off + 2 * card)
        elif kind == BITMAP:
            data = np.frombuffer(buf, dtype="<u8", count=1024, offset=off).copy()
            end = max(end, off + 8192)
        elif kind == RUN:
            (run_count,) = struct.unpack_from("<H", buf, off)
            data = np.frombuffer(
                buf, dtype="<u2", count=2 * run_count, offset=off + 2
            ).copy().reshape(-1, 2)
            end = max(end, off + 2 + 4 * run_count)
        else:
            raise ValueError(f"roaring: unknown pilosa container kind {kind}")
        b._containers[int(key)] = Container(int(kind), data, card)
    b.keys = sorted(b._containers)
    return b, end


def fnv1a32(data: bytes) -> int:
    """FNV-1a 32-bit — the hash upstream pilosa uses for op-log record
    checksums (fnv.New32a over the 9 type+value bytes), NOT CRC-32."""
    h = 0x811C9DC5
    for byte in data:
        h = ((h ^ byte) * 0x01000193) & 0xFFFFFFFF
    return h


def replay_pilosa_ops(bitmap: RoaringBitmap, buf: bytes | memoryview,
                      offset: int, *, strict: bool = False) -> int:
    """Single-value add/remove op records (upstream op log; FNV-1a-checked,
    torn tail tolerated).

    With strict=True (the import path, as opposed to crash recovery) a
    checksum mismatch that leaves a full well-formed record's worth of
    bytes unread raises instead of being treated as a clean torn tail —
    silently importing only the snapshot would be silent data loss.
    """
    buf = memoryview(buf)
    pos, n_ops = offset, 0
    pending_typ, pending = None, []

    def flush():
        if pending:
            ids = np.asarray(pending, np.uint64)
            (bitmap.add_ids if pending_typ == 0 else bitmap.remove_ids)(ids)
            pending.clear()

    while pos + _P_OP.size <= len(buf):
        typ, value, crc = _P_OP.unpack_from(buf, pos)
        if typ > 1 or fnv1a32(bytes(buf[pos:pos + 9])) != crc:
            if strict:
                reason = (f"unsupported op type {typ}" if typ > 1
                          else "checksum mismatch")
                raise ValueError(
                    f"roaring: pilosa op log {reason} at byte {pos} with "
                    f"{len(buf) - pos} bytes remaining; refusing to "
                    "silently drop unsnapshotted ops on import"
                )
            break
        if typ != pending_typ:  # batch consecutive same-type records
            flush()
            pending_typ = typ
        pending.append(value)
        n_ops += 1
        pos += _P_OP.size
    flush()
    return n_ops


def load_any(buf: bytes | memoryview, *, strict_ops: bool = True
             ) -> tuple[RoaringBitmap, int]:
    """Sniff our layout vs the upstream layout; returns (bitmap, op count).

    strict_ops applies to the upstream op log only: load_any's callers are
    import paths (import-roaring, fragment merge), where dropping
    unsnapshotted upstream ops must be an error, not a quiet torn tail.
    """
    buf = memoryview(buf)
    if len(buf) >= 4:
        (magic,) = struct.unpack_from("<I", buf, 0)
        if magic & 0xFFFF == PILOSA_MAGIC and magic != MAGIC:
            bitmap, ops_at = deserialize_pilosa(buf)
            return bitmap, replay_pilosa_ops(bitmap, buf, ops_at,
                                             strict=strict_ops)
    return load(buf)


def load(buf: bytes | memoryview) -> tuple[RoaringBitmap, int]:
    """Snapshot + op replay in one call; returns (bitmap, op_count)."""
    bitmap, ops_at = deserialize(buf)
    n_ops = replay_ops(bitmap, buf, ops_at)
    return bitmap, n_ops
