"""Roaring file format + append-only op log (host durability layer).

Mirrors the reference's fragment storage file design (roaring/roaring.go
WriteTo/UnmarshalBinary + the op-log section; fragment.go snapshot —
SURVEY.md §2 #1, #3): a serialized container snapshot followed by an
append-only log of add/remove batches, replayed on open and compacted
("snapshot") once the op count crosses a threshold. The byte layout is this
framework's own (the reference mount was empty — see SURVEY.md EVIDENCE
STATUS — so byte-level compatibility is unverifiable; the *model* is kept:
cookie, container descriptors [key, kind, cardinality], offsets, container
payloads, trailing ops).

Layout (little-endian):
  header:  magic uint32 = 0x50C4B175, version uint16, flags uint16,
           container_count uint32, payload_bytes uint64
  descrs:  container_count × (key uint64, kind uint16, n_minus_1 uint16,
           payload_len uint32)
  payload: concatenated container data
           array: n × uint16 | bitmap: 1024 × uint64 | run: n_runs × 2 × uint16
  ops:     sequence of records until EOF:
           op_magic uint16 = 0x4F50, op uint16 (1=add 2=remove),
           id_count uint32, crc32 uint32 (over ids bytes), ids × uint64
"""

from __future__ import annotations

import io
import struct
import zlib

import numpy as np

from pilosa_tpu.roaring.bitmap import ARRAY, BITMAP, RUN, Container, RoaringBitmap

MAGIC = 0x50C4B175
VERSION = 1
_HEADER = struct.Struct("<IHHIQ")
_DESCR = struct.Struct("<QHHI")

OP_MAGIC = 0x4F50
OP_ADD = 1
OP_REMOVE = 2
_OP_HEADER = struct.Struct("<HHII")


def serialize(bitmap: RoaringBitmap) -> bytes:
    descrs = []
    payloads = []
    for key in bitmap.keys:
        c = bitmap.container(key)
        data = np.ascontiguousarray(c.data)
        raw = data.astype(
            {ARRAY: "<u2", BITMAP: "<u8", RUN: "<u2"}[c.kind], copy=False
        ).tobytes()
        descrs.append(_DESCR.pack(key, c.kind, c.n - 1, len(raw)))
        payloads.append(raw)
    payload = b"".join(payloads)
    header = _HEADER.pack(MAGIC, VERSION, 0, len(descrs), len(payload))
    return header + b"".join(descrs) + payload


def deserialize(buf: bytes | memoryview) -> tuple[RoaringBitmap, int]:
    """Parse a snapshot; returns (bitmap, offset-where-ops-begin)."""
    buf = memoryview(buf)
    if len(buf) < _HEADER.size:
        raise ValueError("roaring: truncated header")
    magic, version, _flags, n_containers, payload_bytes = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"roaring: bad magic 0x{magic:08X}")
    if version != VERSION:
        raise ValueError(f"roaring: unsupported version {version}")
    pos = _HEADER.size
    b = RoaringBitmap()
    descr_end = pos + n_containers * _DESCR.size
    data_pos = descr_end
    for _ in range(n_containers):
        key, kind, n_minus_1, payload_len = _DESCR.unpack_from(buf, pos)
        pos += _DESCR.size
        raw = buf[data_pos : data_pos + payload_len]
        if len(raw) != payload_len:
            raise ValueError("roaring: truncated container payload")
        data_pos += payload_len
        n = n_minus_1 + 1
        if kind == ARRAY:
            data = np.frombuffer(raw, dtype="<u2").copy()
        elif kind == BITMAP:
            data = np.frombuffer(raw, dtype="<u8").copy()
        elif kind == RUN:
            data = np.frombuffer(raw, dtype="<u2").copy().reshape(-1, 2)
        else:
            raise ValueError(f"roaring: unknown container kind {kind}")
        b._containers[int(key)] = Container(kind, data, n)
    b.keys = sorted(b._containers)
    expected_end = descr_end + payload_bytes
    if data_pos != expected_end:
        raise ValueError("roaring: payload length mismatch")
    return b, data_pos


def encode_op(op: int, ids) -> bytes:
    ids = np.ascontiguousarray(np.asarray(ids, dtype=np.uint64))
    raw = ids.astype("<u8", copy=False).tobytes()
    return _OP_HEADER.pack(OP_MAGIC, op, ids.size, zlib.crc32(raw)) + raw


def replay_ops(bitmap: RoaringBitmap, buf: bytes | memoryview, offset: int) -> int:
    """Apply trailing op records onto the snapshot; returns op count.

    A torn final record (crash mid-append) is tolerated and ignored,
    matching the reference's crash model for the op log.
    """
    buf = memoryview(buf)
    n_ops = 0
    pos = offset
    while pos + _OP_HEADER.size <= len(buf):
        magic, op, id_count, crc = _OP_HEADER.unpack_from(buf, pos)
        if magic != OP_MAGIC:
            break
        body_end = pos + _OP_HEADER.size + id_count * 8
        if body_end > len(buf):
            break  # torn write
        raw = bytes(buf[pos + _OP_HEADER.size : body_end])
        if zlib.crc32(raw) != crc:
            break  # torn/corrupt tail
        ids = np.frombuffer(raw, dtype="<u8")
        if op == OP_ADD:
            bitmap.add_ids(ids)
        elif op == OP_REMOVE:
            bitmap.remove_ids(ids)
        n_ops += 1
        pos = body_end
    return n_ops


class OpLogWriter:
    """Appends op records to an open binary file and fsyncs."""

    def __init__(self, fileobj: io.BufferedWriter):
        self.f = fileobj

    def append(self, op: int, ids) -> None:
        self.f.write(encode_op(op, ids))
        self.f.flush()


def load(buf: bytes | memoryview) -> tuple[RoaringBitmap, int]:
    """Snapshot + op replay in one call; returns (bitmap, op_count)."""
    bitmap, ops_at = deserialize(buf)
    n_ops = replay_ops(bitmap, buf, ops_at)
    return bitmap, n_ops
