"""Serializer between executor results and protobuf wire messages.

Reference: encoding/proto Serializer (SURVEY.md §2 #16). The JSON path
(result_to_json) stays canonical; this maps the same result objects to
QueryResponse protos for clients negotiating application/x-protobuf.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.executor.result import GroupCount, Pair, RowResult, ValCount
from pilosa_tpu.utils import as_int_list
from pilosa_tpu.wire import pb2

RESULT_NIL = 0
RESULT_ROW = 1
RESULT_PAIRS = 2
RESULT_COUNT = 3
RESULT_CHANGED = 4
RESULT_VALCOUNT = 5
RESULT_GROUPS = 6
RESULT_ROW_IDS = 7
RESULT_ROW_KEYS = 8


def _attrs_to_proto(m, attrs: dict) -> None:
    for k, v in sorted(attrs.items()):
        a = m.add()
        a.key = k
        if isinstance(v, bool):
            a.type, a.bool_value = 3, v
        elif isinstance(v, int):
            a.type, a.int_value = 2, v
        elif isinstance(v, float):
            a.type, a.float_value = 4, v
        else:
            a.type, a.string_value = 1, str(v)


def attrs_from_proto(attrs) -> dict:
    out = {}
    for a in attrs:
        out[a.key] = {
            1: a.string_value, 2: a.int_value, 3: a.bool_value, 4: a.float_value,
        }.get(a.type, a.string_value)
    return out


def encode_results(results, trace: dict | None = None) -> bytes:
    """``trace``: a finished span subtree (dict) from a traced remote
    sub-query, carried back to the coordinator as QueryResponse.trace_json
    (silently dropped against a pre-trace generated schema)."""
    import json as _json

    p = pb2()
    resp = p.QueryResponse()
    for res in results:
        qr = resp.results.add()
        _encode_result(qr, res)
    if trace is not None:
        try:
            resp.trace_json = _json.dumps(trace, separators=(",", ":"))
        except AttributeError:  # stale internal_pb2 without the field
            pass
    return resp.SerializeToString()


def _encode_result(qr, res) -> None:
    if res is None:
        qr.type = RESULT_NIL
    elif isinstance(res, RowResult):
        qr.type = RESULT_ROW
        if res.keys is not None:
            qr.row.keys.extend(res.keys)
        else:
            qr.row.columns.extend(int(c) for c in res.columns().tolist())
        _attrs_to_proto(qr.row.attrs, res.attrs)
        if res.column_attrs:
            for entry in res.column_attrs:
                cs = qr.column_attrs.add()
                cs.id = int(entry["id"])
                _attrs_to_proto(cs.attrs, entry["attrs"])
    elif isinstance(res, bool):
        qr.type = RESULT_CHANGED
        qr.changed = res
    elif isinstance(res, int):
        qr.type = RESULT_COUNT
        qr.n = res
    elif isinstance(res, ValCount):
        qr.type = RESULT_VALCOUNT
        qr.val_count.value = res.value
        qr.val_count.count = res.count
    elif isinstance(res, list) and res and isinstance(res[0], Pair):
        qr.type = RESULT_PAIRS
        for pair in res:
            pp = qr.pairs.add()
            pp.id = pair.id
            pp.count = pair.count
            if pair.key is not None:
                pp.key = pair.key
    elif isinstance(res, list) and res and isinstance(res[0], GroupCount):
        qr.type = RESULT_GROUPS
        for g in res:
            gg = qr.groups.add()
            gg.count = g.count
            if g.sum is not None:
                gg.has_sum = True
                gg.sum = g.sum
            for entry in g.group:
                fr = gg.group.add()
                fr.field = entry["field"]
                if "rowKey" in entry:
                    fr.row_key = entry["rowKey"]
                else:
                    fr.row_id = entry["rowID"]
    elif isinstance(res, list) and res and isinstance(res[0], str):
        qr.type = RESULT_ROW_KEYS
        qr.row_keys.extend(res)
    elif isinstance(res, list):
        qr.type = RESULT_ROW_IDS
        qr.row_ids.extend(int(r) for r in res)
    else:
        qr.type = RESULT_NIL


def encode_error(message: str) -> bytes:
    p = pb2()
    resp = p.QueryResponse()
    resp.err = message
    return resp.SerializeToString()


def decode_query_request(data: bytes):
    """Returns (pql, shards, remote, opts) — opts holds the true
    request-level result options under their URL-param names."""
    p = pb2()
    req = p.QueryRequest()
    req.ParseFromString(data)
    opts = {}
    if req.column_attrs:
        opts["columnAttrs"] = True
    if req.exclude_columns:
        opts["excludeColumns"] = True
    if req.exclude_row_attrs:
        opts["excludeRowAttrs"] = True
    return (
        req.query,
        list(req.shards) if req.shards else None,
        req.remote,
        opts,
    )


def decode_import_request(data: bytes):
    p = pb2()
    req = p.ImportRequest()
    req.ParseFromString(data)
    # numpy straight from the repeated fields: the import path converts
    # to arrays anyway, and round-tripping 50k-element Python int lists
    # costs more than the protobuf parse itself
    n = len(req.row_ids)
    return (
        np.fromiter(req.row_ids, np.uint64, count=n),
        np.fromiter(req.column_ids, np.uint64, count=len(req.column_ids)),
        list(req.timestamps) or None,
        req.clear,
    )


def decode_import_value_request(data: bytes):
    p = pb2()
    req = p.ImportValueRequest()
    req.ParseFromString(data)
    return (
        np.fromiter(req.column_ids, np.uint64,
                    count=len(req.column_ids)),
        np.fromiter(req.values, np.int64, count=len(req.values)),
        req.clear,
    )


# ------------------------------------------------------- request encoders
#
# The internal client's side of the negotiated wire (reference: every
# node-to-node hop is protobuf — SURVEY.md §2 #16-17). Varint-packed id
# lists are ~2-5x smaller than JSON int lists; bulk set-bit imports go
# smaller still via the octet-stream roaring path (api._route_import).


def encode_import_request(index: str, field: str, rows, columns,
                          timestamps=None, clear: bool = False) -> bytes:
    p = pb2()
    req = p.ImportRequest()
    req.index, req.field, req.clear = index, field, clear
    req.row_ids.extend(as_int_list(rows))
    req.column_ids.extend(as_int_list(columns))
    if timestamps is not None:
        req.timestamps.extend("" if t is None else str(t) for t in timestamps)
    return req.SerializeToString()


def encode_import_value_request(index: str, field: str, columns, values,
                                clear: bool = False) -> bytes:
    p = pb2()
    req = p.ImportValueRequest()
    req.index, req.field, req.clear = index, field, clear
    req.column_ids.extend(as_int_list(columns))
    req.values.extend(as_int_list(values))
    return req.SerializeToString()


def encode_batch_request(items) -> bytes:
    """``items``: [(index, pql, shards), ...] — optionally a 4th element
    carrying the item's X-Pilosa-Trace context — → BatchQueryRequest
    bytes (the wave-batched internal hop — one request per node per
    wave)."""
    p = pb2()
    req = p.BatchQueryRequest()
    for item in items:
        unit = req.queries.add()
        unit.index = item[0]
        unit.query = item[1]
        unit.shards.extend(int(s) for s in item[2])
        if len(item) > 3 and item[3]:
            try:
                unit.trace = item[3]
            except AttributeError:  # stale internal_pb2: hop untraced
                pass
    return req.SerializeToString()


def decode_batch_request(data: bytes) -> list[tuple]:
    p = pb2()
    req = p.BatchQueryRequest()
    req.ParseFromString(data)
    return [(u.index, u.query, list(u.shards),
             getattr(u, "trace", "") or None)
            for u in req.queries]


def encode_batch_responses(outcomes) -> bytes:
    """``outcomes``: one entry per batched sub-query, either
    ``("ok", [raw results])`` (optionally a 3rd element: the item's span
    subtree) or ``("err", message, status)`` → BatchQueryResponse bytes
    (positional with the request)."""
    import json as _json

    p = pb2()
    batch = p.BatchQueryResponse()
    for outcome in outcomes:
        resp = batch.responses.add()
        if outcome[0] == "ok":
            for res in outcome[1]:
                _encode_result(resp.results.add(), res)
            if len(outcome) > 2 and outcome[2] is not None:
                try:
                    resp.trace_json = _json.dumps(outcome[2],
                                                  separators=(",", ":"))
                except AttributeError:
                    pass
        else:
            resp.err = outcome[1]
            resp.status = int(outcome[2])
    return batch.SerializeToString()


def decode_batch_responses(data: bytes) -> list[dict]:
    """BatchQueryResponse bytes → one dict per sub-query, the same
    shapes query_node returns: ``{"results": [...]}`` on success (plus a
    ``"trace"`` key for traced items), ``{"error": ..., "status": ...}``
    on a per-item error."""
    p = pb2()
    batch = p.BatchQueryResponse()
    batch.ParseFromString(data)
    out = []
    for resp in batch.responses:
        if resp.err:
            out.append({"error": resp.err, "status": int(resp.status) or None})
        else:
            out.append(_response_results_json(resp))
    return out


# ------------------------------------------------- anti-entropy fast path
#
# Batched sync manifests + multi-block deltas (docs/OPERATIONS.md). The
# control halves (manifest, block list) negotiate protobuf like every
# other internal hop; the delta payloads themselves ride a raw
# octet-stream of length-prefixed roaring bitmaps — the framing helpers
# below are protobuf-independent so a JSON-only peer still moves binary
# block data.


def encode_sync_manifest(entries) -> bytes:
    """``entries``: [(field, view, shard, [(block, checksum), ...]), ...]
    → SyncManifest bytes (one response for a whole index)."""
    p = pb2()
    manifest = p.SyncManifest()
    for field, view, shard, blocks in entries:
        fm = manifest.fragments.add()
        fm.field, fm.view, fm.shard = field, view, int(shard)
        for block, checksum in blocks:
            bc = fm.blocks.add()
            bc.block, bc.checksum = int(block), checksum
    return manifest.SerializeToString()


def decode_sync_manifest(data: bytes):
    p = pb2()
    manifest = p.SyncManifest()
    manifest.ParseFromString(data)
    return [
        (fm.field, fm.view, int(fm.shard),
         [(int(bc.block), bc.checksum) for bc in fm.blocks])
        for fm in manifest.fragments
    ]


def encode_sync_blocks_request(index: str, fragments) -> bytes:
    """``fragments``: [(field, view, shard, [block, ...]), ...] →
    SyncBlocksRequest bytes (one POST fetches every wanted block)."""
    p = pb2()
    req = p.SyncBlocksRequest()
    req.index = index
    for field, view, shard, blocks in fragments:
        fl = req.fragments.add()
        fl.field, fl.view, fl.shard = field, view, int(shard)
        fl.blocks.extend(int(b) for b in blocks)
    return req.SerializeToString()


def decode_sync_blocks_request(data: bytes):
    p = pb2()
    req = p.SyncBlocksRequest()
    req.ParseFromString(data)
    return req.index, [
        (fl.field, fl.view, int(fl.shard), [int(b) for b in fl.blocks])
        for fl in req.fragments
    ]


def encode_block_frames(payloads) -> bytes:
    """Length-prefixed concatenation of roaring payloads (the delta
    response body): ``!I`` byte length then the payload, in request
    order. Pure struct framing — works without the protobuf runtime."""
    import struct

    parts = []
    for payload in payloads:
        parts.append(struct.pack("!I", len(payload)))
        parts.append(payload)
    return b"".join(parts)


def decode_block_frames(data: bytes) -> list[bytes]:
    """Inverse of encode_block_frames; raises ValueError on a truncated
    or over-long stream (a torn response must not silently drop the tail
    blocks of a repair)."""
    import struct

    out = []
    offset = 0
    n = len(data)
    while offset < n:
        if offset + 4 > n:
            raise ValueError("truncated block frame header")
        (length,) = struct.unpack_from("!I", data, offset)
        offset += 4
        if offset + length > n:
            raise ValueError("truncated block frame payload")
        out.append(data[offset:offset + length])
        offset += length
    return out


def decode_results_json(data: bytes) -> dict:
    """Parse a QueryResponse into the SAME dict shapes the JSON surface
    emits (executor/result.py to_json), so callers reduce remote partials
    identically whichever encoding the hop negotiated."""
    p = pb2()
    resp = p.QueryResponse()
    resp.ParseFromString(data)
    if resp.err:
        return {"error": resp.err}
    return _response_results_json(resp)


def _response_results_json(resp) -> dict:
    """The result-decoding body shared by single and batched responses."""
    import json as _json

    trace = None
    raw_trace = getattr(resp, "trace_json", "")
    if raw_trace:
        try:
            trace = _json.loads(raw_trace)
        except ValueError:
            trace = None  # malformed subtree degrades to untraced
    out = []
    for qr in resp.results:
        t = qr.type
        if t == RESULT_ROW:
            row: dict = {"attrs": attrs_from_proto(qr.row.attrs)}
            if qr.row.keys:
                row["keys"] = list(qr.row.keys)
            else:
                row["columns"] = list(qr.row.columns)
            if qr.column_attrs:
                row["columnAttrs"] = [
                    {"id": cs.id, "attrs": attrs_from_proto(cs.attrs)}
                    for cs in qr.column_attrs
                ]
            out.append(row)
        elif t == RESULT_PAIRS:
            out.append([
                {"id": pp.id, "count": pp.count, **({"key": pp.key} if pp.key else {})}
                for pp in qr.pairs
            ])
        elif t == RESULT_COUNT:
            out.append(int(qr.n))
        elif t == RESULT_CHANGED:
            out.append(bool(qr.changed))
        elif t == RESULT_VALCOUNT:
            out.append({"value": qr.val_count.value, "count": qr.val_count.count})
        elif t == RESULT_GROUPS:
            groups = []
            for gg in qr.groups:
                g: dict = {
                    "group": [
                        {"field": fr.field, "rowKey": fr.row_key}
                        if fr.row_key else {"field": fr.field, "rowID": fr.row_id}
                        for fr in gg.group
                    ],
                    "count": gg.count,
                }
                if gg.has_sum:
                    g["sum"] = gg.sum
                groups.append(g)
            out.append(groups)
        elif t == RESULT_ROW_IDS:
            out.append(list(qr.row_ids))
        elif t == RESULT_ROW_KEYS:
            out.append(list(qr.row_keys))
        else:
            out.append(None)
    envelope = {"results": out}
    if trace is not None:
        envelope["trace"] = trace
    return envelope
