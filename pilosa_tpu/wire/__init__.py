"""Protobuf wire format (generated on demand via protoc).

``pb2()`` returns the generated module, compiling internal.proto on first
use; returns None when protoc or the protobuf runtime is unavailable, in
which case the HTTP layer serves JSON only (content negotiation degrades
gracefully).
"""

from __future__ import annotations

import os
import shutil
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_PROTO = os.path.join(_DIR, "internal.proto")
_GEN = os.path.join(_DIR, "internal_pb2.py")

_pb2 = None
_tried = False


def pb2():
    global _pb2, _tried
    if _pb2 is not None or _tried:
        return _pb2
    _tried = True
    try:
        import google.protobuf  # noqa: F401
    except ImportError:
        return None
    if not os.path.exists(_GEN) or (
        os.path.getmtime(_GEN) < os.path.getmtime(_PROTO)
    ):
        protoc = shutil.which("protoc")
        if protoc is None:
            return None
        try:
            subprocess.run(
                [protoc, f"--python_out={_DIR}", f"--proto_path={_DIR}",
                 "internal.proto"],
                check=True, capture_output=True, timeout=60,
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
            return None
    try:
        from pilosa_tpu.wire import internal_pb2

        _pb2 = internal_pb2
    except Exception:
        _pb2 = None
    return _pb2


def available() -> bool:
    return pb2() is not None
