"""CDC consumers: the peer tailer and the read-replica follower.

Both ride the same resumable feed (``GET /internal/wal/tail`` —
cdc/feed.py) but answer different questions:

``CdcTailer``   runs on every CLUSTER MEMBER with CDC enabled: it tails
                every peer's committed WAL and feeds remote write events
                into the result cache's invalidation path
                (serving/rescache.py), which is what makes caching
                cluster-edge results safe — a remote write invalidates
                this node's dependent entries within one poll interval.
                ``live()`` is the cache's admission gate: true only
                while every current peer's feed is attached and fresh,
                so membership changes or a stalled peer flip the cache
                back to refusing cluster edges (fail closed, never
                stale).

``CdcFollower`` runs on a NON-MEMBER follower (``cdc-follow`` knob): it
                mirrors an upstream node by attaching a cursor, bulk-
                syncing every fragment over the anti-entropy block
                routes, then applying the tail in commit order via the
                WAL's own recovery path (``apply_recovered`` — the op
                semantics, cache invalidation, and residency upkeep all
                come for free). Reads are served under a staleness
                budget (api.check_staleness); writes are refused 403.
                A crash or a 410 costs a full block resync — the feed
                is applied without local WAL logging, so the cursor
                restarts from the upstream's durable seq.

Feed-gap semantics (both consumers): ``FeedGone`` means the producer
reclaimed history past the cursor (retention budget) or restarted (seq
space reset). Everything derived from the feed is dropped — the tailer
clears the result cache, the follower re-syncs blocks — and the cursor
re-attaches at the producer's durable seq.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from pilosa_tpu.cdc.feed import FeedGone
from pilosa_tpu.roaring import kernels
from pilosa_tpu.storage.wal import (
    REC_TOMBSTONE,
    WriteAheadLog,
    decode_op_body,
)


class _PeerState:
    __slots__ = ("cursor", "caught_up_at")

    def __init__(self):
        self.cursor: int | None = None   # None = not attached yet
        self.caught_up_at: float | None = None


class CdcTailer:
    """Tail every cluster peer's WAL; invalidate the local result cache
    on remote write events. One daemon thread polls all peers round-
    robin — the feed is a control plane (keys, not payload bits), so a
    single poller keeps up at any realistic write rate."""

    def __init__(self, api, client, poll_interval: float = 0.05,
                 max_batch_bytes: int = 1 << 20,
                 cursor_name: str = "tailer", logger=None):
        self.api = api
        self.client = client
        self.poll_interval = max(poll_interval, 1e-3)
        self.max_batch_bytes = max_batch_bytes
        self.cursor_name = cursor_name
        self.logger = logger
        # liveness window: a peer whose feed hasn't been seen caught-up
        # within this long makes live() false (the cache refuses cluster
        # edges again) — bounded staleness is the whole contract
        self.live_window = max(1.0, 20 * self.poll_interval)
        self._peers: dict[str, _PeerState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.events_total = 0
        self.invalidations_total = 0
        self.resyncs_total = 0
        self.poll_errors_total = 0

    # --------------------------------------------------------------- control

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cdc-tailer")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self._poll_all()
            except Exception as e:  # noqa: BLE001 — the poller must
                # survive anything a sick peer throws at it
                self.poll_errors_total += 1
                if self.logger is not None:
                    self.logger.info("cdc tailer pass failed: %s", e)

    # --------------------------------------------------------------- polling

    def _peer_uris(self) -> list[str]:
        cluster = self.api.cluster
        if cluster is None:
            return []
        local = cluster.local.id
        return [n.uri for n in cluster.sorted_nodes() if n.id != local]

    def _poll_all(self) -> None:
        uris = self._peer_uris()
        with self._lock:
            # forget departed peers: a removed node must not hold
            # live() false forever
            for gone in set(self._peers) - set(uris):
                del self._peers[gone]
            states = {uri: self._peers.setdefault(uri, _PeerState())
                      for uri in uris}
        for uri, state in states.items():
            try:
                self._poll_peer(uri, state)
            except FeedGone:
                # history gone (retention reclaim or producer restart):
                # nothing derived from this feed is trustworthy — drop
                # the whole cache (the clear fences in-flight fills)
                # and re-attach
                from pilosa_tpu.serving.rescache import global_result_cache

                global_result_cache().clear()
                state.cursor = None
                state.caught_up_at = None
                self.resyncs_total += 1
            except Exception as e:  # noqa: BLE001 — transport faults,
                # sick peers: live() decays via caught_up_at and the
                # cache refuses cluster edges until the peer answers
                self.poll_errors_total += 1
                if self.logger is not None:
                    self.logger.info("cdc poll %s failed: %s", uri, e)

    def _poll_peer(self, uri: str, state: _PeerState) -> None:
        if state.cursor is None:
            _, durable, _ = self.client.wal_tail(
                uri, cursor=self.cursor_name)
            state.cursor = durable
            state.caught_up_at = time.monotonic()
            return
        events, next_seq, durable = self.client.wal_tail(
            uri, since=state.cursor, max_bytes=self.max_batch_bytes,
            cursor=self.cursor_name)
        for _seq, rtype, key, _body in events:
            self.events_total += 1
            self._invalidate(rtype, key)
        state.cursor = next_seq
        if next_seq >= durable:
            state.caught_up_at = time.monotonic()

    def _invalidate(self, rtype: int, key: str) -> None:
        """Feed one remote write event into the PR 12 invalidation
        path. Ops invalidate at (index, field) dependency granularity —
        the same keys local fragment writes touch; tombstones (index/
        field/shard deletes) invalidate the whole index's entries."""
        from pilosa_tpu.serving import rescache

        parts = key.rstrip("/").split("/")
        idx = self.api.holder.index(parts[0]) if parts and parts[0] else None
        if idx is None:
            # unknown index: no local schema, so no cacheable entries
            # reference it — nothing to invalidate
            return
        if rtype == REC_TOMBSTONE or len(parts) < 4:
            rescache.invalidate_index_wide(idx.scope, parts[0])
        else:
            shard = int(parts[3]) if parts[3].isdigit() else None
            rescache.invalidate_write(idx.scope, parts[0], parts[1],
                                      shard)
        self.invalidations_total += 1

    # --------------------------------------------------------------- surface

    def live(self) -> bool:
        """True while EVERY current peer's feed is attached and was
        seen caught-up within the live window — the result cache's
        cluster-edge admission gate. No peers (single node) is live."""
        now = time.monotonic()
        uris = self._peer_uris()
        with self._lock:
            for uri in uris:
                state = self._peers.get(uri)
                if (state is None or state.caught_up_at is None
                        or now - state.caught_up_at > self.live_window):
                    return False
        return True

    def peer_lag(self) -> dict:
        """Seconds since each peer's feed was last seen caught-up
        (-1 = never attached)."""
        now = time.monotonic()
        with self._lock:
            return {
                uri: (round(now - s.caught_up_at, 3)
                      if s.caught_up_at is not None else -1.0)
                for uri, s in self._peers.items()
            }

    def metrics(self) -> dict:
        lag = self.peer_lag()
        finite = [v for v in lag.values() if v >= 0]
        if finite:
            lag_max = max(finite)
        else:
            # -1 = peers exist but at least one never attached;
            # 0 = no peers at all (single node)
            lag_max = -1.0 if lag else 0.0
        return {
            "cdc_live": 1 if self.live() else 0,
            "cdc_peers": len(lag),
            "cdc_peer_lag_seconds_max": lag_max,
            "cdc_events_total": self.events_total,
            "cdc_invalidations_total": self.invalidations_total,
            "cdc_resyncs_total": self.resyncs_total,
            "cdc_poll_errors_total": self.poll_errors_total,
        }


class CdcFollower:
    """Mirror one upstream node and serve stale-bounded reads.

    Lifecycle: attach a cursor (capturing the upstream's durable seq
    BEFORE the bulk copy, so the tail overlaps the copy instead of
    gapping it — replaying an op the block sync already carried is
    idempotent), adopt the upstream schema, bulk-sync every fragment
    over the anti-entropy block routes, then poll the tail forever.
    The overlap means every committed write is either in the synced
    blocks or in the replayed suffix (or harmlessly both)."""

    def __init__(self, api, client, upstream: str,
                 poll_interval: float = 0.05,
                 max_batch_bytes: int = 1 << 20,
                 cursor_name: str = "follower", logger=None):
        self.api = api
        self.client = client
        self.upstream = upstream.rstrip("/")
        self.poll_interval = max(poll_interval, 1e-3)
        self.max_batch_bytes = max_batch_bytes
        self.cursor_name = cursor_name
        self.logger = logger
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._since: int | None = None
        self._caught_up_at: float | None = None
        self.applied_ops_total = 0
        self.events_total = 0
        self.resyncs_total = 0
        self.poll_errors_total = 0

    # --------------------------------------------------------------- control

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cdc-follower")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self._since is None:
                    self._attach_and_sync()
                self._poll_once()
            except FeedGone:
                # cursor fell off the retained tail (or the upstream
                # restarted): the mirror may have a gap — full resync
                self._since = None
                self._caught_up_at = None
                self.resyncs_total += 1
            except Exception as e:  # noqa: BLE001 — upstream down:
                # staleness grows, check_staleness sheds reads, and we
                # keep retrying
                self.poll_errors_total += 1
                if self.logger is not None:
                    self.logger.info("cdc follow %s failed: %s",
                                     self.upstream, e)
                if self._stop.wait(min(1.0, 10 * self.poll_interval)):
                    return
            if self._stop.wait(self.poll_interval):
                return

    # ------------------------------------------------------------- bulk sync

    def _attach_and_sync(self) -> None:
        _, since, _durable = self.client.wal_tail(
            self.upstream, cursor=self.cursor_name)
        self._sync_schema()
        self._sync_blocks()
        self._since = since
        self._caught_up_at = time.monotonic()

    def _sync_schema(self) -> None:
        """Adopt the upstream schema (create-only — deletions arrive as
        feed tombstones, in order, so a schema fetch never races a
        delete backwards). The same dict shapes the cluster join path
        adopts from its seed."""
        from pilosa_tpu.storage import FieldOptions

        holder = self.api.holder
        schema = self.client.schema(self.upstream)
        for idx_schema in schema.get("indexes", []):
            name = idx_schema["name"]
            opts = idx_schema.get("options", {})
            idx = holder.index(name)
            if idx is None:
                idx = holder.create_index(
                    name, keys=opts.get("keys", False),
                    track_existence=opts.get("trackExistence", True),
                )
            for f in idx_schema.get("fields", []):
                if idx.field(f["name"]) is None:
                    idx.create_field(
                        f["name"],
                        FieldOptions.from_dict(f.get("options", {})),
                    )

    def _sync_blocks(self) -> None:
        """Bulk-copy every fragment from the upstream over the batched
        sync routes, merged under the anti-entropy rules (mutex/bool
        and BSI planes must not union stale rows into newer values —
        parallel/cluster.py)."""
        holder = self.api.holder
        for index_name in list(holder.indexes):
            idx = holder.index(index_name)
            if idx is None:
                continue
            entries = self.client.sync_manifest(self.upstream, index_name)
            for field_name, view_name, shard, blocks in entries:
                fld = idx.field(field_name)
                if fld is None:
                    continue
                wanted = [b for b, _checksum in blocks]
                if not wanted:
                    continue
                bitmaps = self.client.sync_blocks(
                    self.upstream, index_name,
                    [(field_name, view_name, shard, wanted)])
                frag = fld.view(view_name, create=True).fragment(
                    shard, create=True)
                # one id kernel per block bitmap, ONE merge per
                # fragment: the upstream blocks are one consistent
                # fragment, so the conflict-aware merges see the whole
                # id set at once (a BSI column's planes span blocks —
                # per-block applies handed add_ids_value partial
                # columns) and the fragment lock is taken once
                parts = [kernels.fragment_ids(kernels.flatten(bm))
                         for bm in bitmaps
                         if bm is not None and bm.count()]
                if not parts:
                    continue
                ids = np.sort(np.concatenate(parts))
                if fld.options.type in ("mutex", "bool"):
                    frag.add_ids_mutex(ids)
                elif view_name == fld.bsi_view_name():
                    frag.add_ids_value(ids)
                else:
                    frag.add_ids(ids)

    # ------------------------------------------------------------- tail loop

    def _poll_once(self) -> None:
        events, next_seq, durable = self.client.wal_tail(
            self.upstream, since=self._since,
            max_bytes=self.max_batch_bytes, cursor=self.cursor_name)
        for _seq, rtype, key, body in events:
            self.events_total += 1
            try:
                if rtype == REC_TOMBSTONE:
                    self._apply_tombstone(key)
                else:
                    self._apply_op(key, body)
            except Exception as e:  # noqa: BLE001 — one undecodable
                # event must not wedge the feed behind it forever
                self.poll_errors_total += 1
                if self.logger is not None:
                    self.logger.info("cdc apply %s failed: %s", key, e)
        self._since = next_seq
        if next_seq >= durable:
            self._caught_up_at = time.monotonic()

    def _apply_op(self, key: str, body: bytes) -> None:
        holder = self.api.holder
        frag = WriteAheadLog._resolve_fragment(holder, key)
        if frag is None:
            # schema raced the feed (the op's field was created after
            # our last schema fetch): refresh and retry once
            self._sync_schema()
            frag = WriteAheadLog._resolve_fragment(holder, key)
        if frag is None:
            raise ValueError(f"no fragment for feed key {key!r}")
        op, ids = decode_op_body(body)
        # the recovery apply path: op semantics + result-cache and
        # residency invalidation, no local WAL logging (a follower
        # crash costs a resync, not divergence)
        frag.apply_recovered(op, ids)
        self.applied_ops_total += 1

    def _apply_tombstone(self, key: str) -> None:
        holder = self.api.holder
        parts = key.rstrip("/").split("/")
        idx = holder.index(parts[0]) if parts and parts[0] else None
        if idx is None:
            return
        if key.endswith("/") and len(parts) == 1:
            holder.delete_index(parts[0])
        elif key.endswith("/") and len(parts) == 2:
            if idx.field(parts[1]) is not None:
                idx.delete_field(parts[1])
        elif len(parts) == 4 and parts[3].isdigit():
            fld = idx.field(parts[1])
            v = fld.view(parts[2]) if fld is not None else None
            if v is not None:
                v.remove_fragment(int(parts[3]))

    # --------------------------------------------------------------- surface

    def staleness_s(self) -> float:
        """Seconds since this replica last observed itself caught up to
        the upstream's durable seq; infinite until the initial sync
        lands (check_staleness sheds every bounded read until then)."""
        if self._caught_up_at is None:
            return float("inf")
        return time.monotonic() - self._caught_up_at

    def metrics(self) -> dict:
        s = self.staleness_s()
        return {
            "cdc_follower_staleness_seconds": (
                round(s, 3) if s != float("inf") else -1.0),
            "cdc_follower_applied_ops_total": self.applied_ops_total,
            "cdc_events_total": self.events_total,
            "cdc_resyncs_total": self.resyncs_total,
            "cdc_poll_errors_total": self.poll_errors_total,
        }
