"""CDC feed wire format: seq-prefixed WAL records.

One tail response body is a concatenation of frames, each

  seq uint64 LE  ·  one WAL record (storage/wal.py layout: magic,
  rtype, keylen, bodylen, crc32 over key+body, key, body)

The record bytes are EXACTLY what the WAL fsynced — op bodies are
roaring/format.py ``encode_op`` records (roaring-compressed container
payloads per Chambi et al. 1402.6407), so a consumer can hand them
straight to ``decode_op_body``/``apply_recovered``, and the CRC the
producer committed under is the CRC the consumer verifies. A torn or
corrupt tail (truncated response, proxy mangling) stops iteration at
the last whole frame, the same crash model as the WAL file itself:
``iter_frames`` never throws on bad input, it just stops, and the
consumer re-polls from its cursor.

Response metadata rides headers, not the body, so the body stays a
pure frame stream the deflate negotiation can wrap:

  X-Pilosa-Cdc-Next-Seq     position to poll from next
  X-Pilosa-Cdc-Durable-Seq  producer's committed high-water mark
"""

from __future__ import annotations

import struct
import zlib

from pilosa_tpu.storage.wal import (  # noqa: F401 (re-export TailGone)
    _REC_HEADER,
    REC_OP,
    REC_TOMBSTONE,
    WAL_MAGIC,
    TailGone,
    encode_wal_record,
)

NEXT_SEQ_HEADER = "X-Pilosa-Cdc-Next-Seq"
DURABLE_SEQ_HEADER = "X-Pilosa-Cdc-Durable-Seq"

_FRAME_SEQ = struct.Struct("<Q")


class FeedGone(Exception):
    """Client-side mirror of the producer's 410: the cursor fell off
    the retained tail (or the producer restarted and its seq space
    reset). The consumer must restart from a snapshot: drop everything
    derived from the feed and re-attach at ``restart_from`` (-1 when
    the producer didn't say — re-attach without a cursor)."""

    def __init__(self, restart_from: int = -1, floor: int = 0):
        super().__init__(
            f"cdc feed gone: restart from {restart_from} (floor {floor})")
        self.restart_from = restart_from
        self.floor = floor


def encode_frame(seq: int, rtype: int, key: str, body: bytes = b"") -> bytes:
    return _FRAME_SEQ.pack(seq) + encode_wal_record(rtype, key, body)


def encode_events(events) -> bytes:
    """Frame a list of ``(seq, rtype, key, body)`` events (the exact
    shape ``WriteAheadLog.read_tail`` returns)."""
    return b"".join(encode_frame(*ev) for ev in events)


def iter_frames(buf: bytes):
    """Yield ``(seq, rtype, key, body)`` from a frame stream; stops at
    the first torn/corrupt frame at ANY byte offset (fuzz discipline:
    truncation mid-seq, mid-header, mid-key, or mid-body must all stop
    cleanly, never raise, never yield a corrupt record)."""
    view = memoryview(buf)
    pos = 0
    while pos + _FRAME_SEQ.size + _REC_HEADER.size <= len(view):
        (seq,) = _FRAME_SEQ.unpack_from(view, pos)
        rpos = pos + _FRAME_SEQ.size
        magic, rtype, keylen, bodylen, crc = _REC_HEADER.unpack_from(
            view, rpos)
        if magic != WAL_MAGIC:
            return
        if rtype not in (REC_OP, REC_TOMBSTONE):
            # the record CRC covers key+body, not the header: an
            # unknown rtype IS the corruption signal for those bytes
            return
        end = rpos + _REC_HEADER.size + keylen + bodylen
        if end > len(view):
            return  # torn frame
        kb = bytes(view[rpos + _REC_HEADER.size : rpos
                        + _REC_HEADER.size + keylen])
        body = bytes(view[rpos + _REC_HEADER.size + keylen : end])
        if zlib.crc32(kb + body) != crc:
            return  # corrupt frame
        yield seq, rtype, kb.decode(errors="replace"), body
        pos = end
