"""Change-data-capture backbone: WAL tail feed, peer tailers, followers.

The write path already totally orders every mutation (group-commit WAL,
storage/wal.py); CDC exposes that order as a resumable change feed
(``GET /internal/wal/tail``) and builds three consumers on it:

  cluster-safe result caching   each node tails its peers and feeds
                                remote write events into the PR 12
                                invalidation path (serving/rescache.py),
                                lifting the single-node-only refusal
  stale-bounded read replicas   follower nodes tail an upstream cluster
                                and serve reads under an
                                ``X-Pilosa-Max-Staleness`` budget
  point-in-time restore         ``restore --as-of <seq>`` replays the
                                feed on top of the nearest backup
                                generation (storage/backup.py)

Wire format and crash model live in cdc/feed.py; the polling consumers
in cdc/tailer.py.
"""

from pilosa_tpu.cdc.feed import (
    DURABLE_SEQ_HEADER,
    NEXT_SEQ_HEADER,
    FeedGone,
    TailGone,
    encode_events,
    encode_frame,
    iter_frames,
)

__all__ = [
    "DURABLE_SEQ_HEADER",
    "NEXT_SEQ_HEADER",
    "FeedGone",
    "TailGone",
    "encode_events",
    "encode_frame",
    "iter_frames",
]
