"""Per-holder write-ahead log with group commit.

The reference (and rounds 1-5 here) made each acked write pay its own
op-log append+flush into the fragment's file — never an fsync, so "per
write durability" was OS-buffer-deep, and making it real would have put
one fsync on every ACK (the measured drag behind the 4.0× mixed
read+write ceiling, BENCH_SUITE.readwrite). This module is the classic
WAL trade instead: concurrent writers append op records into ONE
holder-level log, a commit thread issues ONE flush+fsync for the whole
group, and only then are all the waiting ACKs released — durability at
amortized cost (SURVEY.md §5.4; the same group-commit shape PR 3 used
for remote sub-queries, applied to the disk instead of the wire).

Three durability modes (``durability-mode`` ServerConfig knob):

- ``group`` (default): ops append to the WAL; fragment files hold only
  snapshots. An ACK barrier (server/api.py) releases once the record's
  group has been fsynced. Fragment snapshots (threshold compaction,
  checkpoint, clean close) make WAL segments garbage-collectable.
- ``per-op``: every op record fsyncs the fragment's own file before the
  mutator returns — true per-write durability, the honest version of
  what round 5 only claimed. The baselining mode for the group-commit
  bench.
- ``flush-only``: the round-5 behavior, byte for byte — append+flush,
  no fsync anywhere on the write path. Survives SIGKILL (the OS buffer
  outlives the process) but not power loss. Kept for back-compat
  baselining.

Recovery: ``recover()`` replays surviving segments on holder open. Op
replay is a suffix re-application — each fragment's snapshot state is
some prefix of its op sequence, and re-applying ordered add/remove
records on top of a later state is idempotent (every bit ends at its
LAST op's value) — so replay needs no per-fragment positions, only two
invariants: a segment is deleted when every fragment with ops in it
has snapshotted at or past them, and segments are reclaimed
OLDEST-FIRST so the survivors are always a contiguous tail of the log
(out-of-order reclamation would leave a non-suffix op subset whose
replay resurrects stale bits). Replayed fragments are snapshotted
immediately and the segments dropped, so a restart in any mode starts
from self-contained fragment files.

WAL segment record layout (little-endian):
  magic uint16 = 0x574C ('WL'), rtype uint16 (1=op 2=tombstone),
  keylen uint16, bodylen uint32, crc32 uint32 (over key+body),
  key bytes (utf-8 "index/field/view/shard"; tombstone keys are either
  a "/"-terminated prefix for index/field deletes or an exact fragment
  key for shard deletes — see tombstone_matches),
  body bytes (for ops: one roaring/format.py encode_op record)
A torn tail (crash mid-append) is dropped, exactly like the fragment
op log's crash model.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import weakref
import zlib

_LOG = logging.getLogger("pilosa_tpu.storage.wal")

MODE_GROUP = "group"
MODE_PER_OP = "per-op"
MODE_FLUSH_ONLY = "flush-only"
DURABILITY_MODES = (MODE_GROUP, MODE_PER_OP, MODE_FLUSH_ONLY)

# Group forming window / size bound (ServerConfig group-commit-max-ms /
# group-commit-max-ops): a record never waits longer than the window
# before its group's fsync starts, and a group never exceeds max-ops.
DEFAULT_GROUP_MAX_MS = 2.0
DEFAULT_GROUP_MAX_OPS = 256

# Rotate the active segment past this size; rotation checkpoints the
# fragments still pinning closed segments (snapshot, off the ACK path)
# so the WAL stays bounded by ~2 segments in steady state.
SEGMENT_MAX_BYTES = 16 << 20

WAL_MAGIC = 0x574C
REC_OP = 1
REC_TOMBSTONE = 2
_REC_HEADER = struct.Struct("<HHHII")

# Bench/test instrumentation: serialize op-log fsyncs behind one lock
# and add a fixed delay, modeling a single disk journal — tmpfs/9p
# under-prices the very fsync group commit amortizes (the config_sync
# injected-RTT precedent, applied to the disk). Applied identically to
# group AND per-op fsyncs so mode comparisons stay honest.
_FSYNC_DELAY_S = float(os.environ.get("PILOSA_TPU_FSYNC_DELAY_MS", "0") or 0) / 1e3
_FSYNC_LOCK = threading.Lock()


def wal_fsync(fd: int) -> None:
    """Op-log fsync (group WAL segments and per-op fragment files both
    route here so injected journal latency hits every mode equally)."""
    if _FSYNC_DELAY_S > 0:
        with _FSYNC_LOCK:
            time.sleep(_FSYNC_DELAY_S)
            os.fsync(fd)
        return
    os.fsync(fd)


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync: after os.replace/create, the parent
    directory entry must also reach the platter or a power cut can lose
    the rename. Some filesystems (9p, certain network mounts) reject
    directory fsync — degrade silently rather than fail the write."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def encode_wal_record(rtype: int, key: str, body: bytes = b"") -> bytes:
    kb = key.encode()
    crc = zlib.crc32(kb + body)
    return _REC_HEADER.pack(WAL_MAGIC, rtype, len(kb), len(body), crc) + kb + body


def iter_wal_records(buf: bytes):
    """Yield (rtype, key, body) records; stops at a torn/corrupt tail
    (the crash model: the final group may be partially written)."""
    view = memoryview(buf)
    pos = 0
    while pos + _REC_HEADER.size <= len(view):
        magic, rtype, keylen, bodylen, crc = _REC_HEADER.unpack_from(view, pos)
        if magic != WAL_MAGIC:
            return
        end = pos + _REC_HEADER.size + keylen + bodylen
        if end > len(view):
            return  # torn write
        kb = bytes(view[pos + _REC_HEADER.size : pos + _REC_HEADER.size + keylen])
        body = bytes(view[pos + _REC_HEADER.size + keylen : end])
        if zlib.crc32(kb + body) != crc:
            return  # corrupt tail
        yield rtype, kb.decode(errors="replace"), body
        pos = end


def decode_op_body(body: bytes):
    """Parse one encode_op record back to (op, ids) — the WAL op body is
    exactly a fragment op-log record, checksum and all."""
    import numpy as np

    from pilosa_tpu.roaring.format import OP_MAGIC, _OP_HEADER

    if len(body) < _OP_HEADER.size:
        raise ValueError("wal: truncated op body")
    magic, op, id_count, crc = _OP_HEADER.unpack_from(body, 0)
    if magic != OP_MAGIC:
        raise ValueError("wal: bad op magic")
    raw = body[_OP_HEADER.size : _OP_HEADER.size + id_count * 8]
    if len(raw) != id_count * 8 or zlib.crc32(raw) != crc:
        raise ValueError("wal: corrupt op body")
    return op, np.frombuffer(raw, dtype="<u8")


def tombstone_matches(key: str, tomb: str) -> bool:
    """True when tombstone ``tomb`` deletes fragment ``key``.
    Index/field deletes write "/"-terminated prefixes ("idx/",
    "idx/fld/") and match everything under them; shard deletes write
    the exact fragment key and must match ONLY it — a bare startswith
    would make shard 1's tombstone swallow shards 10-19, 100-199, ..."""
    if tomb.endswith("/"):
        return key.startswith(tomb)
    return key == tomb


class TailGone(Exception):
    """The requested tail position is no longer served: either segment
    GC reclaimed it past the retention budget (``since < floor``) or the
    node restarted and its seq space reset (``since > durable``). The
    consumer must restart from a snapshot — invalidate everything it
    derived from the feed and resume from ``restart_from``."""

    def __init__(self, floor: int, durable: int):
        super().__init__(
            f"wal tail gone: floor={floor} durable={durable}")
        self.floor = floor
        self.restart_from = durable


class _Segment:
    __slots__ = ("path", "start_seq", "last_seq", "nbytes", "groups",
                 "end_seq")

    def __init__(self, path: str, start_seq: int):
        self.path = path
        self.start_seq = start_seq
        self.last_seq: dict[str, int] = {}  # op key -> last seq written
        self.nbytes = 0
        # CDC tail index: one (first_seq, byte_offset, byte_len, count)
        # entry per fsynced GROUP. Seqs within a group are consecutive
        # (append_op/tombstone each take exactly one seq and the batch
        # is a contiguous buffer slice), so the tail reader recovers
        # every record's seq from the group's first_seq alone. Offsets
        # cover durable bytes only — a group that failed its fsync is
        # never indexed, and the faulted segment is abandoned.
        self.groups: list[tuple[int, int, int, int]] = []
        self.end_seq = 0


class WriteAheadLog:
    """Holder-scoped op durability: group-commit segments in
    ``<data-dir>/.wal/`` plus the mode switch the fragment write path
    consults. One instance per Holder; fragments receive it down the
    storage tree and call ``append_op``/``note_snapshot``/``tombstone``;
    the API façade calls ``barrier()`` at every write ACK point."""

    def __init__(self, dir_path: str, mode: str = MODE_GROUP,
                 group_max_ms: float = DEFAULT_GROUP_MAX_MS,
                 group_max_ops: int = DEFAULT_GROUP_MAX_OPS,
                 fsync_fn=None):
        if mode not in DURABILITY_MODES:
            raise ValueError(
                f"invalid durability mode {mode!r} "
                f"(want one of {', '.join(DURABILITY_MODES)})"
            )
        self.dir = dir_path
        self.mode = mode
        self.group_max_ms = max(0.0, float(group_max_ms))
        self.group_max_ops = max(1, int(group_max_ops))
        self._fsync = fsync_fn or wal_fsync
        self._cond = threading.Condition()
        # (key, encoded record, seq, fragment) pending the next group
        self._buffer: list = []
        self._seq = 0
        self._durable_seq = 0
        self._group_open_t = 0.0
        self._last_group_size = 0
        self._error: BaseException | None = None
        # highest seq whose group's fsync FAILED: those records are
        # gone (torn tail of the poisoned segment), so a barrier for
        # them must raise forever — even after the disk recovers and
        # newer groups commit past them (clear_fault)
        self._failed_seq = 0
        self._closing = False
        # holder's StorageHealth latch (storage/integrity.py): a commit
        # fault trips the node read-only; its probe calls clear_fault()
        # when the disk answers again
        self.health = None
        self._thread: threading.Thread | None = None
        self._started = False
        # segment bookkeeping (commit/checkpoint threads + note_snapshot)
        self._seg_lock = threading.Lock()
        self._segments: list[_Segment] = []
        self._active: _Segment | None = None
        self._file = None
        self._snap_seq: dict[str, int] = {}
        self._tombstones: list[tuple[str, int]] = []
        self._dirty: dict[str, weakref.ref] = {}
        self._checkpointing = False
        # CDC cursor registry (storage for the /internal/wal/tail
        # plane): name -> highest seq the consumer has acknowledged.
        # Segment GC keeps covered segments the oldest cursor still
        # needs, up to cdc_retention_bytes; past the budget it reclaims
        # oldest-first anyway and advances _tail_floor so the laggard's
        # next read raises TailGone (restart-from-snapshot).
        self._cursors: dict[str, int] = {}
        self._tail_floor = 0
        self.cdc_retention_bytes = 64 << 20
        self.cdc_forced_reclaims = 0
        self.tail_reads = 0
        self.tail_bytes = 0
        self.cursors_dropped = 0
        # observability (metrics() exports zeros from scrape one)
        self.groups = 0
        self.fsyncs = 0
        self.appended_ops = 0
        self.wal_bytes = 0
        self.max_group_ops = 0
        self.checkpoints = 0
        self.recovered_ops = 0
        self.commit_recoveries = 0

    # ------------------------------------------------------------ lifecycle

    @property
    def grouped(self) -> bool:
        """True when ops should ride the WAL instead of fragment files."""
        return self.mode == MODE_GROUP and self._started

    def configure(self, mode: str | None = None,
                  group_max_ms: float | None = None,
                  group_max_ops: int | None = None) -> None:
        """Apply knobs before ``start()`` (Server.open wiring)."""
        if self._started:
            raise RuntimeError("wal already started")
        if mode is not None:
            if mode not in DURABILITY_MODES:
                raise ValueError(
                    f"invalid durability mode {mode!r} "
                    f"(want one of {', '.join(DURABILITY_MODES)})"
                )
            self.mode = mode
        if group_max_ms is not None:
            self.group_max_ms = max(0.0, float(group_max_ms))
        if group_max_ops is not None:
            self.group_max_ops = max(1, int(group_max_ops))

    def start(self) -> None:
        """Open the active segment and the commit thread (group mode
        only; the other modes need no WAL machinery)."""
        if self.mode != MODE_GROUP or self._started:
            return
        os.makedirs(self.dir, exist_ok=True)
        self._open_segment()
        self._started = True
        self._thread = threading.Thread(
            target=self._commit_loop, daemon=True, name="wal-commit"
        )
        self._thread.start()

    def _open_segment(self) -> None:
        with self._seg_lock:
            numbers = [int(os.path.basename(s.path).split(".")[0])
                       for s in self._segments]
            if os.path.isdir(self.dir):
                numbers += [
                    int(e.split(".")[0]) for e in os.listdir(self.dir)
                    if e.endswith(".log") and e.split(".")[0].isdigit()
                ]
            path = os.path.join(self.dir,
                                f"{max(numbers, default=0) + 1:08d}.log")
            if self._file is not None:
                self._file.close()
            self._file = open(path, "ab")
            seg = _Segment(path, self._seq + 1)
            self._segments.append(seg)
            self._active = seg
        fsync_dir(self.dir)

    def close(self) -> None:
        """Flush pending groups, stop the commit thread, and drop every
        segment whose ops are covered by durable snapshots (a clean
        close, where fragments snapshotted on their way down, leaves an
        empty WAL; a failed snapshot leaves its segment for recover())."""
        t = self._thread
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        if t is not None:
            t.join(30)
            if t.is_alive():
                # the commit thread is still draining (or wedged in a
                # slow fsync): closing the segment file under it would
                # truncate the shutdown flush SILENTLY — its next write
                # hits a closed file. Leave the file to the thread,
                # keep every segment on disk for the next open's
                # recover(), and make the condition loud: future
                # barriers fail instead of acking volatile writes.
                with self._cond:
                    if self._error is None:
                        self._error = OSError(
                            "wal close timed out with commit backlog"
                        )
                    self._cond.notify_all()
                _LOG.error(
                    "wal: commit thread did not drain within 30s on "
                    "close; leaving segments in %s for recovery",
                    self.dir,
                )
                self._thread = None
                self._started = False
                return
        self._thread = None
        self._started = False
        with self._seg_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
        self._gc_segments(include_active=True)

    # ------------------------------------------------------------ write path

    def append_op(self, key: str, record: bytes, frag=None) -> int:
        """Queue one op record for the next group; returns its sequence
        number (callers don't wait here — the ACK point's ``barrier()``
        does). Called under the fragment lock; the critical section is a
        list append."""
        with self._cond:
            if self._error is not None:
                raise OSError(f"wal commit failed: {self._error}")
            self._seq += 1
            seq = self._seq
            if not self._buffer:
                self._group_open_t = time.monotonic()
            self._buffer.append(
                (key, encode_wal_record(REC_OP, key, record), seq, frag,
                 REC_OP)
            )
            self._cond.notify_all()
        return seq

    def tombstone(self, prefix: str) -> None:
        """Record a delete: every fragment matched by ``prefix`` (a
        "/"-terminated index/field prefix, or one exact fragment key —
        tombstone_matches) is gone. Replay must not resurrect its ops
        into a later re-creation, and its pending ops stop pinning
        segments."""
        if not self.grouped:
            return
        with self._cond:
            self._seq += 1
            seq = self._seq
            if not self._buffer:
                self._group_open_t = time.monotonic()
            self._buffer.append(
                (prefix, encode_wal_record(REC_TOMBSTONE, prefix), seq, None,
                 REC_TOMBSTONE)
            )
            self._cond.notify_all()
        # _tombstones (consulted by _covered for segment GC) is updated
        # by the commit loop only once the record is DURABLE; callers
        # that need the delete on disk follow up with barrier()

    def note_snapshot(self, key: str, seq: int) -> None:
        """A fragment's snapshot (fsynced file + dir) now covers all its
        ops up to ``seq`` — they no longer pin WAL segments."""
        with self._seg_lock:
            if seq > self._snap_seq.get(key, -1):
                self._snap_seq[key] = seq

    def discard_key(self, key: str) -> None:
        """A deleted fragment's ops need no preserving: release their
        segment pins (coverage only — the durable tombstone still rules
        replay). Closes the delete race where an in-flight writer
        appends between the tombstone record and the fragment's close;
        that late op would otherwise pin its segment — and, with
        oldest-first reclamation, every newer one — until restart."""
        with self._cond:
            seq = self._seq
        with self._seg_lock:
            if seq > self._snap_seq.get(key, -1):
                self._snap_seq[key] = seq
            self._dirty.pop(key, None)

    def current_seq(self) -> int:
        with self._cond:
            return self._seq

    def durable_seq(self) -> int:
        with self._cond:
            return self._durable_seq

    # ------------------------------------------------------------- CDC tail

    def register_cursor(self, name: str, seq: int) -> None:
        """Register (or advance) a named tail cursor: the consumer has
        acknowledged everything up to ``seq``. Registration pins covered
        segments with records past ``seq`` against GC, within the
        retention budget. Cursors only move forward — a stale re-poll
        must not re-pin segments the registry already released."""
        with self._seg_lock:
            if seq >= self._cursors.get(name, -1):
                self._cursors[name] = seq

    def drop_cursor(self, name: str) -> None:
        with self._seg_lock:
            self._cursors.pop(name, None)

    def drop_cursors_for(self, node_id: str) -> int:
        """Drop every cursor a departed member registered here —
        names carry the owner as a ``:<node-id>`` suffix
        (``tailer:<id>``, ``follower:<id>``). A permanently departed
        node's cursor would otherwise pin WAL retention until
        force-reclaim (the cursor-leak satellite of the elastic
        plane). Returns the number dropped; counted in
        ``cdc_cursors_dropped_total``."""
        suffix = f":{node_id}"
        with self._seg_lock:
            names = [n for n in self._cursors if n.endswith(suffix)]
            for n in names:
                del self._cursors[n]
            self.cursors_dropped += len(names)
        return len(names)

    def cursors(self) -> dict[str, int]:
        with self._seg_lock:
            return dict(self._cursors)

    def tail_floor(self) -> int:
        with self._seg_lock:
            return self._tail_floor

    def read_tail(self, since: int, max_bytes: int = 1 << 20):
        """Read committed records after ``since`` in commit order.
        Returns ``(events, next_seq, durable_seq)`` where events is a
        list of ``(seq, rtype, key, body)`` and ``next_seq`` is the
        position to poll from next (== durable_seq when the read
        drained the feed; seqs of groups lost to storage faults are
        skipped over, never replayed). Raises TailGone when ``since``
        predates the retention floor or postdates the durable seq (the
        node restarted and its seq space reset)."""
        with self._cond:
            durable = self._durable_seq
        with self._seg_lock:
            if since < self._tail_floor or since > durable:
                raise TailGone(self._tail_floor, durable)
            plan: list[tuple[str, int, int, int, int]] = []
            planned_bytes = 0
            complete = True
            for seg in self._segments:
                for first, offset, nb, count in seg.groups:
                    if first + count - 1 <= since:
                        continue
                    if plan and planned_bytes + nb > max_bytes:
                        complete = False
                        break
                    plan.append((seg.path, offset, nb, first, count))
                    planned_bytes += nb
                if not complete:
                    break
        events: list[tuple[int, int, str, bytes]] = []
        try:
            for path, offset, nb, first, count in plan:
                with open(path, "rb") as f:
                    f.seek(offset)
                    buf = f.read(nb)
                seq = first
                for rtype, key, body in iter_wal_records(buf):
                    # cap at the durable snapshot: a group indexed
                    # between our durable read and the plan scan would
                    # otherwise emit seqs past next_seq
                    if since < seq <= durable:
                        events.append((seq, rtype, key, body))
                    seq += 1
        except FileNotFoundError:
            # GC raced the read and reclaimed a planned segment: the
            # consumer is behind the (just-advanced) floor
            with self._seg_lock:
                raise TailGone(self._tail_floor, durable) from None
        if complete:
            next_seq = durable
        else:
            next_seq = events[-1][0] if events else since
        self.tail_reads += 1
        self.tail_bytes += sum(nb for _, _, nb, _, _ in plan)
        return events, next_seq, durable

    def barrier(self, seq: int | None = None) -> None:
        """Block until every op appended so far (or up to ``seq``) is
        durable — the write ACK gate. No-op outside group mode (per-op
        fsyncs inline; flush-only promises nothing). Ops whose group's
        fsync FAILED raise forever: their bytes are a torn tail of a
        poisoned segment, and acking them after the disk recovers would
        be acking lost writes."""
        if not self.grouped:
            return
        with self._cond:
            target = self._seq if seq is None else seq
            # the lost-group check comes BEFORE the durable check: a
            # recovered WAL commits newer groups past the failed range,
            # and a late barrier for a lost seq must still raise — not
            # convert a lost write into a late ACK
            if 0 < target <= self._failed_seq:
                raise OSError(
                    "wal commit failed: this write's group was lost "
                    "to a storage fault"
                )
            while self._durable_seq < target:
                if self._error is not None:
                    raise OSError(f"wal commit failed: {self._error}")
                if self._closing and self._thread is None:
                    raise OSError("wal closed with ops pending")
                t = self._thread
                if t is not None and not t.is_alive():
                    # the commit thread died without recording an error
                    # (shouldn't happen — its whole body is guarded —
                    # but a hung barrier would wedge every write
                    # handler server-wide, so fail loudly instead)
                    raise OSError("wal commit thread died")
                self._cond.wait(1.0)

    def flush(self) -> None:
        self.barrier()

    def clear_fault(self) -> bool:
        """The disk answers again (StorageHealth probe succeeded): drop
        the recorded fault and resume committing buffered groups into a
        FRESH segment — the faulted segment's tail may be torn, and
        appending past a tear would bury good records behind it.
        Returns False (stay degraded) when the fresh segment itself
        cannot be opened."""
        with self._cond:
            if self._error is None:
                return True
        # open the fresh segment BEFORE clearing the error: the commit
        # loop only writes while _error is None, so clearing first
        # would let a woken group fsync into the faulted segment PAST
        # its torn tail — recover()'s sequential replay stops at the
        # tear and the acked group behind it would be unreachable
        if self._started:
            try:
                self._open_segment()
            except OSError:
                return False  # probe retries; _error stays set
        with self._cond:
            self._error = None
            self._cond.notify_all()
        self.commit_recoveries += 1
        return True

    # ---------------------------------------------------------- commit loop

    def _commit_loop(self) -> None:
        # any escape — fsync failure is handled inline below, but also
        # segment rotation (open/fsync-dir on a full disk), checkpoint
        # spawn, or a plain bug — must record an error and wake the
        # barrier waiters: a silently dead commit thread would wedge
        # every write ACK in the server forever
        try:
            self._run_commits()
        except BaseException as e:
            with self._cond:
                if self._error is None:
                    self._error = e
                self._cond.notify_all()

    def _run_commits(self) -> None:
        while True:
            with self._cond:
                # with a fault recorded, hold off instead of burning a
                # retry loop against a sick disk: clear_fault() (driven
                # by the health probe) wakes this wait when the disk
                # answers again. The timeout exists ONLY in the faulted
                # state (belt-and-braces vs a missed notify); an idle
                # healthy node sleeps untimed like it always did.
                while ((not self._buffer or self._error is not None)
                       and not self._closing):
                    self._cond.wait(
                        0.5 if self._error is not None else None
                    )
                if self._closing and (not self._buffer
                                      or self._error is not None):
                    break  # shutdown (clean, or still-faulted: the
                    # surviving segments are recover()'s problem)
                # Self-latching forming window (the serving pipeline's
                # gather idiom): hold the group open up to max_ms only
                # when there is evidence of concurrency — this group
                # already has >1 record, or the previous group did. A
                # solo serial writer stays on the zero-wait path; a real
                # burst re-opens the window within one group.
                if (self.group_max_ms > 0 and not self._closing
                        and (len(self._buffer) > 1
                             or self._last_group_size > 1)):
                    deadline = self._group_open_t + self.group_max_ms / 1e3
                    while (len(self._buffer) < self.group_max_ops
                           and not self._closing):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(left)
                batch = self._buffer[:self.group_max_ops]
                self._buffer = self._buffer[self.group_max_ops:]
                self._last_group_size = len(batch)
                if self._buffer:
                    self._group_open_t = time.monotonic()
            end_seq = batch[-1][2]
            data = b"".join(rec for _, rec, _, _, _ in batch)
            try:
                with self._seg_lock:
                    f, seg = self._file, self._active
                    seg_path = seg.path
                    f.write(data)
                    f.flush()
                from pilosa_tpu.testing import faults as _faults

                _faults.disk_check("fsync", seg_path)
                self._fsync(f.fileno())
            except (OSError, ValueError) as e:
                # an fsync/write failure means this GROUP is lost (its
                # bytes are a torn tail): fail its barriers forever,
                # trip the holder into read-only storage_degraded mode,
                # and park the loop until the health probe's
                # clear_fault() says the disk answers again — instead
                # of dying and wedging the node until restart
                with self._cond:
                    self._error = e
                    self._failed_seq = max(self._failed_seq, end_seq)
                    self._cond.notify_all()
                if self.health is not None:
                    self.health.trip(f"wal commit fsync: {e}")
                continue
            with self._seg_lock:
                seg.groups.append(
                    (batch[0][2], seg.nbytes, len(data), len(batch)))
                seg.end_seq = end_seq
                seg.nbytes += len(data)
                for key, _, seq, frag, rtype in batch:
                    if rtype == REC_TOMBSTONE:
                        # register only NOW, post-fsync: _covered must
                        # never GC op segments on the strength of a
                        # tombstone a crash could still erase. And keep
                        # it out of last_seq — a tombstone is not an op
                        # and must not cover or pin anything as one.
                        self._tombstones.append((key, seq))
                        for k in list(self._dirty):
                            if tombstone_matches(k, key):
                                del self._dirty[k]
                        continue
                    seg.last_seq[key] = seq
                    if frag is not None:
                        self._dirty[key] = weakref.ref(frag)
            self.groups += 1
            self.fsyncs += 1
            self.appended_ops += len(batch)
            self.wal_bytes += len(data)
            self.max_group_ops = max(self.max_group_ops, len(batch))
            with self._cond:
                self._durable_seq = max(self._durable_seq, end_seq)
                self._cond.notify_all()
            if seg.nbytes > SEGMENT_MAX_BYTES and not self._closing:
                self._open_segment()
                self._spawn_checkpoint()

    # ------------------------------------------------- checkpoint / segments

    def _covered(self, key: str, last_seq: int) -> bool:
        if self._snap_seq.get(key, -1) >= last_seq:
            return True
        return any(
            ts_seq >= last_seq and tombstone_matches(key, prefix)
            for prefix, ts_seq in self._tombstones
        )

    def _gc_segments(self, include_active: bool = False) -> None:
        """Reclaim covered segments OLDEST-FIRST, stopping at the first
        segment that must stay. In-order reclamation is load-bearing
        twice over: recover() replays every surviving record as a
        suffix re-application, so the survivors must be a contiguous
        tail of the log — deleting a newer covered segment while an
        older one lives would replay stale ops (an add whose later
        remove was reclaimed) on top of a snapshot that already folded
        them in — and it guarantees a tombstone's file outlives every
        older segment still holding ops it must kill on replay."""
        with self._seg_lock:
            keep = list(self._segments)
            min_cursor = (min(self._cursors.values())
                          if self._cursors else None)
            while keep:
                seg = keep[0]
                if not include_active and seg is self._active:
                    break
                if not all(
                    self._covered(k, s) for k, s in seg.last_seq.items()
                ):
                    break
                if (min_cursor is not None and seg.end_seq > min_cursor
                        and not include_active):
                    # a registered CDC cursor still needs this covered
                    # segment. Retain the contiguous covered prefix up
                    # to the retention budget; past it, reclaim
                    # oldest-first anyway and advance the tail floor so
                    # the laggard's next read answers TailGone instead
                    # of the WAL growing without bound.
                    pinned = 0
                    for s in keep:
                        if s is self._active or not all(
                            self._covered(k, q)
                            for k, q in s.last_seq.items()
                        ):
                            break
                        pinned += s.nbytes
                    if pinned <= self.cdc_retention_bytes:
                        break
                    self.cdc_forced_reclaims += 1
                try:
                    os.unlink(seg.path)
                except OSError:
                    break
                if seg.end_seq:
                    self._tail_floor = max(self._tail_floor, seg.end_seq)
                keep.pop(0)
            if len(keep) != len(self._segments):
                self._segments = keep
                fsync_dir(self.dir)
            # prune tombstones that predate every surviving segment:
            # they can never cover another surviving or future op, and
            # _covered scans this list for every key at every
            # checkpoint — unbounded growth under shard churn otherwise
            min_start = keep[0].start_seq if keep else self._seq + 1
            if self._tombstones:
                self._tombstones = [
                    (p, s) for p, s in self._tombstones if s >= min_start
                ]

    def _spawn_checkpoint(self) -> None:
        """Snapshot the fragments pinning closed segments, then GC —
        runs on its own thread so groups keep committing into the fresh
        segment while the checkpoint walks fragment locks."""
        with self._seg_lock:
            if self._checkpointing:
                return
            self._checkpointing = True
        threading.Thread(
            target=self._checkpoint, daemon=True, name="wal-checkpoint"
        ).start()

    def _checkpoint(self) -> None:
        try:
            with self._seg_lock:
                pinned: dict[str, int] = {}
                for seg in self._segments:
                    if seg is self._active:
                        continue
                    for key, seq in seg.last_seq.items():
                        if not self._covered(key, seq):
                            pinned[key] = max(pinned.get(key, 0), seq)
                frags = [(k, self._dirty.get(k)) for k in pinned]
            for key, ref in frags:
                frag = ref() if ref is not None else None
                if frag is None or not getattr(frag, "_open", False):
                    continue
                try:
                    frag.snapshot()  # calls back into note_snapshot
                except OSError:
                    pass  # segment stays pinned; retried next rotation
            self.checkpoints += 1
            self._gc_segments()
        finally:
            with self._seg_lock:
                self._checkpointing = False

    # -------------------------------------------------------------- recovery

    def recover(self, holder) -> int:
        """Replay surviving segments into the holder's fragments (open
        time, single-threaded, any mode — a group-mode crash must heal
        even if the restart is configured differently). Touched
        fragments are snapshotted and the segments deleted, so the
        post-open state is self-contained fragment files and an empty
        WAL regardless of mode history."""
        if not os.path.isdir(self.dir):
            return 0
        paths = sorted(
            os.path.join(self.dir, e) for e in os.listdir(self.dir)
            if e.endswith(".log")
        )
        if not paths:
            return 0
        records = []
        for p in paths:
            with open(p, "rb") as f:
                records.extend(iter_wal_records(f.read()))
        # tombstone pass: an op is dead if a LATER tombstone matches it
        tombs = [
            (i, key) for i, (rtype, key, _) in enumerate(records)
            if rtype == REC_TOMBSTONE
        ]
        # redo shard deletes: an exact-key tombstone whose fragment
        # files survived means the crash landed between the durable
        # tombstone and remove_fragment's unlinks — finish the delete
        # before replay. Safe for a same-key re-creation: oldest-first
        # segment GC means every post-tombstone op is still in the log
        # while its tombstone is, so replay rebuilds the new era in
        # full. (Index/field deletes need no redo: their directory is
        # renamed away atomically before the tombstone is written.)
        for _, tk in tombs:
            if tk.endswith("/"):
                continue
            parts = tk.split("/")
            if len(parts) != 4 or not parts[3].isdigit():
                continue
            idx = holder.index(parts[0])
            fld = idx.field(parts[1]) if idx is not None else None
            view = fld.views.get(parts[2]) if fld is not None else None
            if view is None:
                continue
            stale = view.fragments.pop(int(parts[3]), None)
            if stale is not None:
                stale.close(discard=True)
            frag_path = os.path.join(view.path, "fragments", parts[3])
            for p in (frag_path, frag_path + ".cache"):
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass
            # the unlink must hit the platter BEFORE the segments (and
            # with them the tombstone) are durably erased below — a
            # power cut could otherwise revert the volatile unlink with
            # no tombstone left anywhere to redo it
            fsync_dir(os.path.dirname(frag_path))
        applied = 0
        touched: dict[str, object] = {}
        for i, (rtype, key, body) in enumerate(records):
            if rtype != REC_OP:
                continue
            if any(ti > i and tombstone_matches(key, tk) for ti, tk in tombs):
                continue
            frag = self._resolve_fragment(holder, key)
            if frag is None:
                continue  # index/field deleted out from under the log
            try:
                op, ids = decode_op_body(body)
            except ValueError:
                continue  # corrupt record: skip, keep replaying
            frag.apply_recovered(op, ids)
            touched[key] = frag
            applied += 1
        for frag in touched.values():
            frag.snapshot()           # durable, self-contained file
            frag.recalculate_cache()  # replay bypassed cache upkeep
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        fsync_dir(self.dir)
        self.recovered_ops += applied
        return applied

    @staticmethod
    def _resolve_fragment(holder, key: str):
        parts = key.split("/")
        if len(parts) != 4 or not parts[3].isdigit():
            return None
        index, field, view, shard = parts
        idx = holder.index(index)
        if idx is None:
            return None
        fld = idx.field(field)
        if fld is None:
            return None
        return fld.view(view, create=True).fragment(int(shard), create=True)

    # ---------------------------------------------------------------- stats

    def metrics(self) -> dict:
        with self._seg_lock:
            segments = len(self._segments)
            retained = sum(s.nbytes for s in self._segments)
            cursors = len(self._cursors)
            min_cursor = (min(self._cursors.values())
                          if self._cursors else 0)
            floor = self._tail_floor
        return {
            "cdc_cursors": cursors,
            "cdc_min_cursor_seq": min_cursor,
            "cdc_tail_floor": floor,
            "cdc_retained_bytes": retained,
            "cdc_forced_reclaims_total": self.cdc_forced_reclaims,
            "cdc_tail_reads_total": self.tail_reads,
            "cdc_tail_bytes_total": self.tail_bytes,
            "cdc_cursors_dropped_total": self.cursors_dropped,
            "groups_total": self.groups,
            "fsyncs_total": self.fsyncs,
            "appended_ops_total": self.appended_ops,
            "bytes_total": self.wal_bytes,
            "group_max_ops": self.max_group_ops,
            "checkpoints_total": self.checkpoints,
            "recovered_ops_total": self.recovered_ops,
            "commit_recoveries_total": self.commit_recoveries,
            "segments": segments,
        }
