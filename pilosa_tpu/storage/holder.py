"""Holder: root of the storage tree, owns the data directory.

Reference: holder.go (SURVEY.md §2 #8): opens/walks ``<data-dir>/`` on
startup (restart == checkpoint resume: every fragment reloads snapshot +
op log — SURVEY.md §5.4), caches open fragments, exposes the schema.

Durability (storage/wal.py): the holder owns the write-ahead log every
fragment logs through. ``durability_mode`` selects group commit (one
fsync per wave of concurrent writers; the default), per-op fsync, or the
legacy flush-only path; ``open()`` replays any WAL segments a crash left
behind before serving, so restart always resumes from every acked write.
"""

from __future__ import annotations

import os
import shutil
import threading

from pilosa_tpu.storage.index import Index, _validate_name
from pilosa_tpu.storage.integrity import StorageHealth
from pilosa_tpu.storage.translate import TranslateStore
from pilosa_tpu.storage.wal import (
    DEFAULT_GROUP_MAX_MS,
    DEFAULT_GROUP_MAX_OPS,
    MODE_GROUP,
    WriteAheadLog,
    fsync_dir,
)


class Holder:
    def __init__(self, data_dir: str, durability_mode: str = MODE_GROUP,
                 group_commit_max_ms: float = DEFAULT_GROUP_MAX_MS,
                 group_commit_max_ops: int = DEFAULT_GROUP_MAX_OPS,
                 verify_on_load: bool = True):
        self.data_dir = os.path.expanduser(data_dir)
        self.indexes: dict[str, Index] = {}
        self._create_lock = threading.Lock()
        self.translate: TranslateStore | None = None
        self._open = False
        # Storage integrity plane (storage/integrity.py): verified
        # fragment loads (sidecar digest checks; corrupt files are
        # quarantined at open instead of decoded into serving state)
        # and the disk-fault degradation latch — ENOSPC/EIO on the
        # write paths flips this node read-only until a probe write
        # succeeds, instead of wedging the commit thread.
        self.verify_on_load = bool(verify_on_load)
        self.health = StorageHealth(probe_dir=self.data_dir)
        self.wal = WriteAheadLog(
            os.path.join(self.data_dir, ".wal"),
            mode=durability_mode,
            group_max_ms=group_commit_max_ms,
            group_max_ops=group_commit_max_ops,
        )
        self.wal.health = self.health
        self.health.on_clear(self.wal.clear_fault)

    def open(self) -> "Holder":
        os.makedirs(self.data_dir, exist_ok=True)
        self.translate = TranslateStore(
            os.path.join(self.data_dir, ".translate.log")
        ).open()
        for entry in sorted(os.listdir(self.data_dir)):
            p = os.path.join(self.data_dir, entry)
            if entry.startswith(".trash-"):
                # a delete_index crashed between rename and rmtree
                shutil.rmtree(p, ignore_errors=True)
                continue
            if os.path.isdir(p) and not entry.startswith("."):
                self.indexes[entry] = Index(
                    p, entry, wal=self.wal,
                    verify_on_load=self.verify_on_load,
                ).open()
        # crash recovery: replay acked-but-unsnapshotted ops a previous
        # group-mode run left in the WAL, snapshot the touched fragments,
        # and start this run's log fresh (any-mode safe — see wal.py)
        self.wal.recover(self)
        self.wal.start()
        self._open = True
        return self

    def close(self) -> None:
        for idx in list(self.indexes.values()):
            idx.close()  # group mode: dirty fragments snapshot on close
        if self.translate:
            self.translate.close()
        # after every fragment snapshotted, the WAL truncates to nothing
        # (clean close); a failed snapshot leaves its segment for the
        # next open's recover()
        self.wal.close()
        self.health.close()
        self._open = False

    def create_index(self, name: str, keys: bool = False, track_existence: bool = True) -> Index:
        with self._create_lock:
            if name in self.indexes:
                raise ValueError(f"index {name!r} already exists")
            _validate_name(name)
            idx = Index(
                os.path.join(self.data_dir, name), name, keys=keys,
                track_existence=track_existence, wal=self.wal,
                verify_on_load=self.verify_on_load,
            ).open()
            self.indexes[name] = idx
            return idx

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index {name!r} not found")
        # rename-then-tombstone makes the delete crash-atomic: the
        # rename removes the index from the tree in one step (a restart
        # finding no directory skips its WAL ops — never the half-state
        # of a live index missing acked writes), the DURABLE tombstone
        # then keeps replay from resurrecting its ops into a later
        # same-name re-creation, and only then do the files go away.
        # open() sweeps any .trash-* a crash leaves behind.
        trash = os.path.join(self.data_dir, f".trash-{name}")
        shutil.rmtree(trash, ignore_errors=True)
        try:
            os.rename(idx.path, trash)
        except OSError:
            trash = None  # already gone; nothing on disk to resurrect
        else:
            # the rename must reach the platter before the delete is
            # acked — a power cut would otherwise undo it and resurrect
            # every snapshot file (recover() only suppresses op replay)
            fsync_dir(self.data_dir)
        self.wal.tombstone(f"{name}/")
        self.wal.barrier()
        idx.close(discard=True)
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)

    def schema(self) -> list[dict]:
        return [idx.schema() for _, idx in sorted(self.indexes.items())]

    # --------------------------------------------------------------- backup

    def backup(self, dest: str) -> dict:
        """Incremental manifest backup of this (open) holder into an
        object-store-style directory — see storage/backup.py."""
        from pilosa_tpu.storage.backup import backup_holder

        return backup_holder(self, dest)
