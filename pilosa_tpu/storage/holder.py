"""Holder: root of the storage tree, owns the data directory.

Reference: holder.go (SURVEY.md §2 #8): opens/walks ``<data-dir>/`` on
startup (restart == checkpoint resume: every fragment reloads snapshot +
op log — SURVEY.md §5.4), caches open fragments, exposes the schema.
"""

from __future__ import annotations

import os
import shutil
import threading

from pilosa_tpu.storage.index import Index, _validate_name
from pilosa_tpu.storage.translate import TranslateStore


class Holder:
    def __init__(self, data_dir: str):
        self.data_dir = os.path.expanduser(data_dir)
        self.indexes: dict[str, Index] = {}
        self._create_lock = threading.Lock()
        self.translate: TranslateStore | None = None
        self._open = False

    def open(self) -> "Holder":
        os.makedirs(self.data_dir, exist_ok=True)
        self.translate = TranslateStore(
            os.path.join(self.data_dir, ".translate.log")
        ).open()
        for entry in sorted(os.listdir(self.data_dir)):
            p = os.path.join(self.data_dir, entry)
            if os.path.isdir(p) and not entry.startswith("."):
                self.indexes[entry] = Index(p, entry).open()
        self._open = True
        return self

    def close(self) -> None:
        for idx in list(self.indexes.values()):
            idx.close()
        if self.translate:
            self.translate.close()
        self._open = False

    def create_index(self, name: str, keys: bool = False, track_existence: bool = True) -> Index:
        with self._create_lock:
            if name in self.indexes:
                raise ValueError(f"index {name!r} already exists")
            _validate_name(name)
            idx = Index(
                os.path.join(self.data_dir, name), name, keys=keys,
                track_existence=track_existence,
            ).open()
            self.indexes[name] = idx
            return idx

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def delete_index(self, name: str) -> None:
        idx = self.indexes.pop(name, None)
        if idx is None:
            raise KeyError(f"index {name!r} not found")
        idx.close()
        shutil.rmtree(idx.path, ignore_errors=True)

    def schema(self) -> list[dict]:
        return [idx.schema() for _, idx in sorted(self.indexes.items())]
