"""Storage integrity plane: end-to-end checksums, quarantine, degradation.

PR 5 proved acked writes survive crashes and PR 9 proved the control
plane survives partitions — but nothing detected a fragment whose bytes
rotted ON DISK: a flipped bit in a roaring container would be decoded,
served, replicated by anti-entropy, and snapshotted into backups as if
it were truth. This module is the missing trust boundary between the
disk and everything above it:

- **Checksum sidecars** (``<fragment>.checksums``): every fragment
  snapshot persists its per-BLOCK_ROWS block digests beside the data
  file — the SAME blake2b-over-ids digests the sync manifests (PR 4)
  and backup blobs (PR 5) already speak, so load verification, scrub,
  anti-entropy, and backup all share one checksum language.
- **Verified loads**: ``Fragment.open`` re-derives the snapshot's block
  digests and compares them against the sidecar (``verify-on-load``
  knob); any decode error or digest mismatch raises the typed
  :class:`CorruptFragmentError` instead of a raw ``struct.error`` five
  frames deep. Digests are memoized against the fragment's mutation
  counter (fragment.blocks), so hot read paths pay nothing.
- **Quarantine**: a fragment that fails verification is renamed to
  ``<name>.quarantine-<n>`` (with its sidecars), dropped from the view,
  and NEVER served; the scrubber / anti-entropy then read-repairs it
  from a healthy replica (parallel/scrub.py).
- **StorageHealth**: ENOSPC/EIO on the WAL fsync, snapshot, or
  ``.meta`` write paths flips the node to a read-only
  ``storage_degraded`` state (writes shed 503 on the QoS path,
  ``storageDegraded`` on /status, ``storage_degraded`` gauge on
  /metrics) instead of wedging the commit thread with a traceback —
  and auto-clears once a probe write to the data dir succeeds.

Disk faults are injectable deterministically (testing/faults.py disk
plane: bit-flip-on-read, torn writes, errno on fsync), which is how the
chaos/scrub oracles drive every path here.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
import zlib

import numpy as np

_LOG = logging.getLogger("pilosa_tpu.storage.integrity")

# Sidecar beside every fragment snapshot holding its block digests.
CHECKSUM_SUFFIX = ".checksums"
# Quarantined artifacts: "<fragment>.quarantine-<n>" — never decoded,
# never served, skipped by every directory walk (view open's isdigit()
# filter, backup's fragments-dir skip), kept for forensics.
QUARANTINE_MARK = ".quarantine-"


class CorruptFragmentError(ValueError):
    """A fragment's bytes fail structural decode or digest verification.

    Subclasses ValueError so callers already handling decode errors
    (import paths, restore) keep working; carries the fragment path and
    the best-known byte offset / block so the operator can find the rot
    without a hex editor.
    """

    def __init__(self, path: str, reason: str, offset: int | None = None,
                 block: int | None = None):
        self.path = path
        self.reason = reason
        self.offset = offset
        self.block = block
        where = ""
        if offset is not None:
            where = f" at byte {offset}"
        elif block is not None:
            where = f" in checksum block {block}"
        super().__init__(f"corrupt fragment {path}{where}: {reason}")


# Decode failures that mean "these bytes are not a fragment" — the set
# a flipped byte can produce anywhere in the snapshot region. Anything
# else escaping a decode is a real bug and should surface raw.
DECODE_ERRORS = (ValueError, struct.error, zlib.error, OverflowError,
                 IndexError, MemoryError)


# ------------------------------------------------------------- digests


def block_digests(ids: np.ndarray, block_rows: int = 100
                  ) -> list[tuple[int, str]]:
    """Per-block blake2b digests of a fragment's sorted bit ids — THE
    checksum language (identical to fragment.blocks(), the sync
    manifests, and backup's blob addressing)."""
    out: list[tuple[int, str]] = []
    if ids.size:
        block_of = (ids >> np.uint64(20)) // block_rows
        boundaries = np.concatenate(
            ([0], np.nonzero(np.diff(block_of))[0] + 1, [ids.size])
        )
        for i in range(boundaries.size - 1):
            lo, hi = int(boundaries[i]), int(boundaries[i + 1])
            digest = hashlib.blake2b(
                ids[lo:hi].astype("<u8").tobytes(), digest_size=16
            ).hexdigest()
            out.append((int(block_of[lo]), digest))
    return out


# ------------------------------------------------------------- sidecar


def save_checksums(path: str, blocks) -> None:
    """Atomically persist a fragment's block digests (snapshot-time
    sidecar). Self-checksummed so a torn sidecar reads as absent, not
    as a false corruption verdict against a healthy fragment."""
    body = json.dumps([[int(b), d] for b, d in blocks],
                      separators=(",", ":")).encode()
    payload = json.dumps(
        {"v": 1, "crc": zlib.crc32(body), "blocks": json.loads(body)},
        separators=(",", ":"),
    ).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checksums(path: str) -> list[tuple[int, str]] | None:
    """Read a checksum sidecar; None when absent or torn (verification
    is skipped then — an unreadable sidecar must not condemn a healthy
    fragment)."""
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8", errors="strict"))
        blocks = doc["blocks"]
        body = json.dumps([[int(b), d] for b, d in blocks],
                          separators=(",", ":")).encode()
        if zlib.crc32(body) != doc["crc"]:
            return None
        return [(int(b), str(d)) for b, d in blocks]
    except (OSError, ValueError, KeyError, TypeError):
        return None


def verify_snapshot_blocks(bitmap, sidecar: list[tuple[int, str]],
                           path: str) -> None:
    """Compare a decoded SNAPSHOT bitmap's block digests against its
    sidecar (computed before op replay — the sidecar describes exactly
    the snapshot portion of the file). Raises CorruptFragmentError on
    the first differing block."""
    _check_digests(block_digests(bitmap.to_ids()), sidecar, path)


def _check_digests(live: list[tuple[int, str]],
                   sidecar: list[tuple[int, str]], path: str) -> None:
    if live == sidecar:
        return
    want = dict(sidecar)
    got = dict(live)
    for block in sorted(set(want) | set(got)):
        if want.get(block) != got.get(block):
            raise CorruptFragmentError(
                path,
                f"block digest mismatch (have {got.get(block)}, "
                f"checksum index says {want.get(block)})",
                block=block,
            )
    raise CorruptFragmentError(path, "block digest ordering mismatch")


# ---------------------------------------------------------------- load


def read_file(path: str) -> bytes:
    """Whole-file read routed through the disk fault plane's read hook
    (testing/faults.py) — the one seam bit-flip-on-read injection needs
    to reach every fragment load and scrub pass."""
    from pilosa_tpu.testing import faults

    with open(path, "rb") as f:
        data = f.read()
    return faults.disk_filter_read(path, data)


def load_verified(data: bytes, path: str, verify: bool = False):
    """Decode a fragment file's snapshot portion with every decode
    error wrapped as CorruptFragmentError; with ``verify``, also check
    the snapshot's block digests against the sidecar (when one exists).
    Returns (bitmap, ops_at). Op replay stays with the caller — ops are
    individually CRC'd and follow the torn-tail crash model."""
    from pilosa_tpu.roaring.format import deserialize

    try:
        bitmap, ops_at = deserialize(data)
    except DECODE_ERRORS as e:
        # truncation tears are at EOF by construction; other decode
        # failures carry no reliable offset — report the path and the
        # decoder's own message rather than a misleading byte number
        offset = len(data) if "truncated" in str(e).lower() else None
        raise CorruptFragmentError(
            path, f"snapshot decode failed: {e}", offset=offset,
        ) from e
    if verify:
        sidecar = load_checksums(path + CHECKSUM_SUFFIX)
        if sidecar is not None:
            verify_snapshot_blocks(bitmap, sidecar, path)
            global_integrity().count("verified_loads")
        else:
            global_integrity().count("unverified_loads")
    return bitmap, ops_at


def verify_fragment_file(path: str, build_bitmap: bool = True):
    """THE disk-vs-disk verification recipe, shared by the scrubber,
    the chaos disk-integrity oracle, and the CLI check verb: read the
    file (through the fault plane's read seam), decode the snapshot
    with typed errors, and — when a sidecar exists — compare block
    digests. Raises CorruptFragmentError; returns (bitmap, data,
    ops_at) so callers can replay/weigh the op tail.

    ``build_bitmap=False`` is the scrub fast path: the snapshot's ids
    go straight from the bytes through the vectorized kernel parser
    (roaring/kernels.py) into the digests — no Container objects are
    built — and the returned bitmap is None. Structural validation and
    the digest verdict are identical (the kernel parser raises the
    same errors on the same inputs)."""
    data = read_file(path)
    sidecar = load_checksums(path + CHECKSUM_SUFFIX)
    if not build_bitmap:
        from pilosa_tpu.roaring import kernels

        try:
            ids, ops_at = kernels.snapshot_ids(data)
        except DECODE_ERRORS as e:
            offset = len(data) if "truncated" in str(e).lower() else None
            raise CorruptFragmentError(
                path, f"snapshot decode failed: {e}", offset=offset,
            ) from e
        if sidecar is not None:
            _check_digests(block_digests(ids), sidecar, path)
        return None, data, ops_at
    bitmap, ops_at = load_verified(data, path, verify=False)
    if sidecar is not None:
        verify_snapshot_blocks(bitmap, sidecar, path)
    return bitmap, data, ops_at


# ----------------------------------------------------------- quarantine


def quarantine_paths(path: str, reason: str = "") -> str:
    """Rename a corrupt fragment file (and its .cache/.checksums
    sidecars) to ``<path>.quarantine-<n>`` so it is never decoded or
    served again; the renamed artifacts stay on disk for forensics.
    Returns the quarantine path (or "" when nothing existed)."""
    n = 0
    while os.path.exists(f"{path}{QUARANTINE_MARK}{n}"):
        n += 1
    qpath = f"{path}{QUARANTINE_MARK}{n}"
    moved = ""
    for src, dst in (
        (path, qpath),
        (path + ".cache", f"{qpath}.cache"),
        (path + CHECKSUM_SUFFIX, f"{qpath}{CHECKSUM_SUFFIX}"),
    ):
        try:
            os.replace(src, dst)
            if src == path:
                moved = dst
        except OSError:
            continue
    from pilosa_tpu.storage.wal import fsync_dir

    fsync_dir(os.path.dirname(path) or ".")
    stats = global_integrity()
    stats.count("quarantined")
    _LOG.error("quarantined corrupt fragment %s -> %s (%s)",
               path, qpath, reason)
    return moved


def is_quarantined(name: str) -> bool:
    return QUARANTINE_MARK in name


def list_quarantined(data_dir: str) -> list[str]:
    """Every quarantined artifact under a data dir (CLI check, status
    reporting)."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(data_dir):
        for name in filenames:
            if QUARANTINE_MARK in name and not name.endswith(
                (".cache", CHECKSUM_SUFFIX)
            ):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


# ------------------------------------------------------- process counters


class IntegrityStats:
    """Process-wide integrity counters (the global_stats shape): every
    exporter key present from scrape one, zeros included."""

    KEYS = ("verified_loads", "unverified_loads", "verify_failures",
            "quarantined", "read_repairs", "self_heals")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {k: 0 for k in self.KEYS}

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def metrics(self) -> dict:
        with self._lock:
            return {f"integrity_{k}_total": v
                    for k, v in sorted(self._counts.items())}


_INTEGRITY = IntegrityStats()


def global_integrity() -> IntegrityStats:
    return _INTEGRITY


# ------------------------------------------------------- storage health


class StorageHealth:
    """Per-holder disk-fault degradation latch.

    ``trip(reason)`` flips the node into the read-only
    ``storage_degraded`` state (the write paths consult ``degraded``
    and shed 503 — server/api.py) and starts a probe loop that
    attempts a small fsynced write into the data dir; the first probe
    that succeeds runs the registered recovery callbacks (the WAL's
    ``clear_fault``) and clears the latch. The probe write itself
    routes through the disk fault plane, so an armed ENOSPC/EIO rule
    keeps the node degraded until the rule clears — exactly how a full
    disk behaves."""

    PROBE_INTERVAL_S = 1.0

    def __init__(self, probe_dir: str | None = None):
        self._lock = threading.Lock()
        self._probe_dir = probe_dir
        self.degraded = False
        self.reason = ""
        self.trips = 0
        self.recoveries = 0
        self._on_clear: list = []
        self._probe_thread: threading.Thread | None = None
        self._closed = threading.Event()

    def on_clear(self, fn) -> None:
        """Register a recovery callback run when a probe succeeds
        (before the latch clears)."""
        with self._lock:
            self._on_clear.append(fn)

    def trip(self, reason: str) -> None:
        with self._lock:
            already = self.degraded
            self.degraded = True
            if not already:
                self.reason = reason
                self.trips += 1
            start_probe = (not already and self._probe_dir is not None
                           and not self._closed.is_set())
            if start_probe:
                self._probe_thread = threading.Thread(
                    target=self._probe_loop, daemon=True,
                    name="storage-health-probe",
                )
        if not already:
            _LOG.error(
                "storage degraded (%s): shedding writes read-only until "
                "a probe write succeeds", reason,
            )
        if start_probe:
            self._probe_thread.start()

    def clear(self) -> None:
        with self._lock:
            if not self.degraded:
                return
            self.degraded = False
            self.reason = ""
            self.recoveries += 1
        _LOG.warning("storage recovered: probe write succeeded, "
                     "resuming writes")

    def close(self) -> None:
        self._closed.set()

    # ------------------------------------------------------------- probe

    def probe_write(self) -> None:
        """One small durable write into the data dir; raises OSError
        while the disk is still sick. Routed through the fault plane's
        fsync hook so injected ENOSPC keeps failing it."""
        from pilosa_tpu.testing import faults

        path = os.path.join(self._probe_dir, ".probe")
        with open(path, "wb") as f:
            f.write(b"probe")
            f.flush()
            faults.disk_check("fsync", path)
            os.fsync(f.fileno())
        try:
            os.unlink(path)
        except OSError:
            pass

    def _probe_loop(self) -> None:
        while not self._closed.is_set():
            self._closed.wait(self.PROBE_INTERVAL_S)
            with self._lock:
                if not self.degraded:
                    return
            try:
                self.probe_write()
            except OSError:
                continue
            with self._lock:
                callbacks = list(self._on_clear)
            ok = True
            for fn in callbacks:
                try:
                    if fn() is False:
                        ok = False  # recovery refused (e.g. WAL could
                        # not reopen a segment): stay degraded, reprobe
                except OSError:
                    ok = False
            if ok:
                self.clear()
                return

    # ----------------------------------------------------------- metrics

    def metrics(self) -> dict:
        with self._lock:
            return {
                "storage_degraded": int(self.degraded),
                "storage_degraded_total": self.trips,
                "storage_recoveries_total": self.recoveries,
            }
