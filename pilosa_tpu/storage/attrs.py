"""Attribute storage: arbitrary JSON attributes on rows and columns.

Reference: attr.go + boltdb/ (SURVEY.md §2 #10) — a BoltDB B-tree per
index (column attrs) / per field (row attrs), with content-hashed blocks
for anti-entropy diffing. Here: sqlite3 (stdlib, single-file B-tree — the
same role Bolt plays in Go) storing one JSON blob per id, plus 100-id
checksum blocks.
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import threading

ATTR_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self._conn: sqlite3.Connection | None = None

    def open(self) -> "AttrStore":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT NOT NULL)"
        )
        self._conn.commit()
        return self

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def attrs(self, id_: int) -> dict:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM attrs WHERE id = ?", (int(id_),)
            ).fetchone()
        return json.loads(row[0]) if row else {}

    def set_attrs(self, id_: int, attrs: dict) -> dict:
        """Merge attrs into the existing set (null values delete keys,
        matching the reference's merge semantics)."""
        with self._lock:
            current = self.attrs(id_)
            for k, v in attrs.items():
                if v is None:
                    current.pop(k, None)
                else:
                    current[k] = v
            self._conn.execute(
                "INSERT INTO attrs (id, data) VALUES (?, ?) "
                "ON CONFLICT(id) DO UPDATE SET data = excluded.data",
                (int(id_), json.dumps(current, sort_keys=True)),
            )
            self._conn.commit()
        return current

    def bulk(self, ids) -> dict[int, dict]:
        """One read for many ids, chunked under SQLite's host-parameter
        limit (999 in older builds) so TopN-scale candidate lists work."""
        ids = [int(i) for i in ids]
        out: dict[int, dict] = {}
        with self._lock:
            for lo in range(0, len(ids), 500):
                chunk = ids[lo:lo + 500]
                marks = ",".join("?" * len(chunk))
                rows = self._conn.execute(
                    f"SELECT id, data FROM attrs WHERE id IN ({marks})",
                    chunk,
                ).fetchall()
                out.update((int(i), json.loads(d)) for i, d in rows)
        return out

    def blocks(self) -> list[tuple[int, str]]:
        """Content-hashed ATTR_BLOCK_SIZE-id blocks (anti-entropy diffing)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, data FROM attrs ORDER BY id"
            ).fetchall()
        out = []
        current_block, hasher = None, None
        for id_, data in rows:
            block = int(id_) // ATTR_BLOCK_SIZE
            if block != current_block:
                if current_block is not None:
                    out.append((current_block, hasher.hexdigest()))
                current_block, hasher = block, hashlib.blake2b(digest_size=16)
            hasher.update(f"{id_}={data};".encode())
        if current_block is not None:
            out.append((current_block, hasher.hexdigest()))
        return out

    def block_data(self, block: int) -> dict[int, dict]:
        lo, hi = block * ATTR_BLOCK_SIZE, (block + 1) * ATTR_BLOCK_SIZE
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, data FROM attrs WHERE id >= ? AND id < ?", (lo, hi)
            ).fetchall()
        return {int(i): json.loads(d) for i, d in rows}

    def merge_block(self, data: dict) -> None:
        """Union-merge a peer's block (anti-entropy repair)."""
        for id_, attrs in data.items():
            self.set_attrs(int(id_), attrs)
