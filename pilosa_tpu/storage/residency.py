"""Device residency manager: which fragment rows live in HBM.

The reference mmaps every fragment file and lets the OS page cache decide
residency (fragment.go + syswrap/ — SURVEY.md §2 #3, #26). HBM is orders of
magnitude smaller than a disk page cache, so residency is explicit here: a
byte-budgeted LRU of decoded dense rows (uint32[32768] each = 128 KiB) keyed
by (fragment id, row). Eviction is free — the host roaring file remains the
source of truth and rows are re-decoded on demand (SURVEY.md §7.3 hard part
#1).

Writes invalidate the affected row; queries call ``get_row`` and receive a
device array ready for the bitwise kernels.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

import jax
import numpy as np

from pilosa_tpu.shardwidth import WORDS_PER_SHARD

ROW_BYTES = WORDS_PER_SHARD * 4  # 128 KiB per resident row

# Default budget: 4 GiB of HBM for row residency (v5e has 16 GiB; the rest
# is headroom for query intermediates + XLA workspace). Tests override.
DEFAULT_BUDGET_BYTES = 4 << 30


class DeviceRowCache:
    """Byte-budgeted LRU of device-resident arrays (dense rows, BSI plane
    matrices, mesh-sharded shard stacks — sized by actual nbytes)."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES, device=None):
        self.budget_bytes = budget_bytes
        self.device = device
        self._rows: OrderedDict[tuple, jax.Array] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # bumped on every fragment write; coarse invalidation signal for
        # derived entries (mesh-stacked arrays) whose keys embed it
        self.write_generation = 0

    def __len__(self) -> int:
        return len(self._rows)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def get_row(self, key: tuple, decode: Callable[[], np.ndarray],
                device_put: Callable | None = None) -> jax.Array:
        """Return the device array for ``key``, decoding+uploading on miss.
        ``device_put`` overrides placement (e.g. a NamedSharding put)."""
        row = self._rows.get(key)
        if row is not None:
            self.hits += 1
            self._rows.move_to_end(key)
            return row
        self.misses += 1
        host = decode()
        if device_put is not None:
            arr = device_put(host)
        else:
            arr = jax.device_put(host, self.device)
        self._rows[key] = arr
        self._bytes += arr.nbytes
        self._evict()
        return arr

    def invalidate(self, key: tuple) -> None:
        arr = self._rows.pop(key, None)
        if arr is not None:
            self._bytes -= arr.nbytes

    def invalidate_fragment(self, frag_id: tuple) -> None:
        doomed = [k for k in self._rows if k[: len(frag_id)] == frag_id]
        for k in doomed:
            self.invalidate(k)

    def bump_generation(self) -> None:
        self.write_generation += 1

    def clear(self) -> None:
        self._rows.clear()
        self._bytes = 0

    def _evict(self) -> None:
        while self._bytes > self.budget_bytes and len(self._rows) > 1:
            _, arr = self._rows.popitem(last=False)
            self._bytes -= arr.nbytes
            self.evictions += 1


_global_cache: DeviceRowCache | None = None


def global_row_cache() -> DeviceRowCache:
    global _global_cache
    if _global_cache is None:
        _global_cache = DeviceRowCache()
    return _global_cache


def set_global_row_cache(cache: DeviceRowCache) -> None:
    global _global_cache
    _global_cache = cache
