"""Device residency manager: which fragment rows live in HBM.

The reference mmaps every fragment file and lets the OS page cache decide
residency (fragment.go + syswrap/ — SURVEY.md §2 #3, #26). HBM is orders of
magnitude smaller than a disk page cache, so residency is explicit here: a
byte-budgeted LRU of decoded dense rows (uint32[32768] each = 128 KiB) keyed
by (fragment id, row). The host roaring file remains the source of truth and
rows are re-decoded on demand (SURVEY.md §7.3 hard part #1).

Two tiers. Hot entries are dense, ready for the bitwise kernels. When the
dense tier overflows its budget share, sparse entries are *demoted* instead
of dropped: their nonzero 4 KiB blocks are gathered on device into a compact
``uint32[nb, 1024]`` array (one jitted gather — no host round trip; block
indices were computed from the host array at insert time, so demotion never
blocks on a device→host sync). A hit on a demoted entry scatters the blocks
back into a dense array (one jitted scatter) and promotes it. For bitmap
data at real-world densities this multiplies effective HBM residency by the
inverse block-occupancy, which matters because a re-upload over host↔device
is the slowest path in the system.

Writes invalidate the affected row in both tiers; queries call ``get_row``
and receive a device array ready for the bitwise kernels.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.shardwidth import WORDS_PER_SHARD, next_pow2

ROW_BYTES = WORDS_PER_SHARD * 4  # 128 KiB per resident row

# Default budget: 4 GiB of HBM for row residency (v5e has 16 GiB; the rest
# is headroom for query intermediates + XLA workspace). Tests override.
DEFAULT_BUDGET_BYTES = 4 << 30

# Compression granularity: 4 KiB device blocks. Row = 32 blocks.
COMPRESS_BLOCK_WORDS = 1024

# Demote-as-compressed only when it actually saves memory; denser entries
# are simply dropped (host re-decode is the fallback, as before).
COMPRESS_MAX_OCCUPANCY = 0.5


@partial(jax.jit, static_argnames=("block_words",))
def _gather_blocks(arr, idx, block_words: int):
    """Compact the nonzero blocks of a flattened array: uint32[nb, bw]."""
    return arr.reshape(-1, block_words)[idx]


@partial(jax.jit, static_argnames=("n_blocks", "block_words"))
def _scatter_blocks(blocks, idx, n_blocks: int, block_words: int):
    """Inverse of _gather_blocks. ``idx`` may contain duplicates (padding
    repeats a real index with its real data — identical writes are safe)."""
    out = jnp.zeros((n_blocks, block_words), jnp.uint32)
    return out.at[idx].set(blocks).reshape(-1)


class _DenseEntry:
    __slots__ = ("arr", "block_idx")

    def __init__(self, arr, block_idx):
        self.arr = arr
        self.block_idx = block_idx  # np.int32[nb] or None = incompressible


class _CompressedEntry:
    __slots__ = ("blocks", "idx", "shape", "n_blocks", "block_idx")

    def __init__(self, blocks, idx, shape, n_blocks, block_idx):
        self.blocks = blocks  # device uint32[nb_padded, bw]
        self.idx = idx  # device int32[nb_padded]
        self.shape = shape
        self.n_blocks = n_blocks
        self.block_idx = block_idx  # host copy, for re-demotion

    @property
    def nbytes(self) -> int:
        return self.blocks.nbytes + self.idx.nbytes


class DeviceRowCache:
    """Byte-budgeted two-tier LRU of device-resident arrays (dense rows,
    BSI plane matrices, mesh-sharded shard stacks — sized by actual
    nbytes). Sparse entries compress on demotion instead of dropping."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES, device=None):
        self.budget_bytes = budget_bytes
        self.device = device
        self._rows: OrderedDict[tuple, _DenseEntry] = OrderedDict()
        self._compressed: OrderedDict[tuple, _CompressedEntry] = OrderedDict()
        self._bytes = 0
        self._compressed_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compressions = 0
        self.decompressions = 0
        # bumped on every fragment write; coarse invalidation signal for
        # derived entries (mesh-stacked arrays) whose keys embed it
        self.write_generation = 0

    def __len__(self) -> int:
        return len(self._rows) + len(self._compressed)

    @property
    def bytes_used(self) -> int:
        return self._bytes + self._compressed_bytes

    @property
    def compressed_bytes(self) -> int:
        return self._compressed_bytes

    def get_row(self, key: tuple, decode: Callable[[], np.ndarray],
                device_put: Callable | None = None) -> jax.Array:
        """Return the device array for ``key``, decoding+uploading on miss.
        ``device_put`` overrides placement (e.g. a NamedSharding put);
        entries with custom placement are never compressed."""
        entry = self._rows.get(key)
        if entry is not None:
            self.hits += 1
            self._rows.move_to_end(key)
            return entry.arr
        centry = self._compressed.pop(key, None)
        if centry is not None:
            self.hits += 1
            self.decompressions += 1
            self._compressed_bytes -= centry.nbytes
            flat = _scatter_blocks(
                centry.blocks, centry.idx, centry.n_blocks,
                COMPRESS_BLOCK_WORDS,
            )
            arr = flat.reshape(centry.shape)
            self._insert_dense(key, arr, centry.block_idx)
            return arr
        self.misses += 1
        host = decode()
        if device_put is not None:
            arr = device_put(host)
            block_idx = None  # custom placement (mesh sharding): keep dense
        else:
            arr = jax.device_put(host, self.device)
            block_idx = self._host_block_index(host)
        self._insert_dense(key, arr, block_idx)
        return arr

    @staticmethod
    def _host_block_index(host: np.ndarray):
        """Nonzero-block indices, computed from the host array at insert
        time (free pass over data already in cache) so demotion later
        needs no device→host sync. None = incompressible."""
        if host.dtype != np.uint32 or host.size % COMPRESS_BLOCK_WORDS:
            return None
        mask = np.any(
            host.reshape(-1, COMPRESS_BLOCK_WORDS) != 0, axis=1
        )
        if mask.mean() > COMPRESS_MAX_OCCUPANCY:
            return None
        return np.flatnonzero(mask).astype(np.int32)

    def _insert_dense(self, key: tuple, arr, block_idx) -> None:
        self._rows[key] = _DenseEntry(arr, block_idx)
        self._bytes += arr.nbytes
        self._evict()

    def invalidate(self, key: tuple) -> None:
        entry = self._rows.pop(key, None)
        if entry is not None:
            self._bytes -= entry.arr.nbytes
        centry = self._compressed.pop(key, None)
        if centry is not None:
            self._compressed_bytes -= centry.nbytes

    def invalidate_fragment(self, frag_id: tuple) -> None:
        for store in (self._rows, self._compressed):
            doomed = [k for k in store if k[: len(frag_id)] == frag_id]
            for k in doomed:
                self.invalidate(k)

    def bump_generation(self) -> None:
        """Invalidate generation-keyed derived entries. Keys of the form
        ('stack*', gen, ...) can never be hit again after the bump, so
        purge them now rather than letting them occupy either tier (or
        waste a demotion gather on eviction)."""
        self.write_generation += 1

        def stale(key: tuple) -> bool:
            # ('stackz', block_key) carries no generation and stays valid
            return (isinstance(key[0], str) and key[0].startswith("stack")
                    and len(key) > 1 and isinstance(key[1], int)
                    and key[1] != self.write_generation)

        for store in (self._rows, self._compressed):
            for k in [k for k in store if stale(k)]:
                self.invalidate(k)

    def clear(self) -> None:
        self._rows.clear()
        self._compressed.clear()
        self._bytes = 0
        self._compressed_bytes = 0

    def _evict(self) -> None:
        # Demotion only under real pressure: the dense tier may use the
        # whole budget while it fits (a fully-resident working set stays
        # fully resident, as in the single-tier cache). Over budget, LRU
        # dense entries demote (compressible — shrinks usage) or drop;
        # then LRU compressed entries drop.
        while self.bytes_used > self.budget_bytes and len(self._rows) > 1:
            key, entry = self._rows.popitem(last=False)
            self._bytes -= entry.arr.nbytes
            if entry.block_idx is not None:
                self._demote(key, entry)
            else:
                self.evictions += 1
        while self.bytes_used > self.budget_bytes and self._compressed:
            _, centry = self._compressed.popitem(last=False)
            self._compressed_bytes -= centry.nbytes
            self.evictions += 1

    def _demote(self, key: tuple, entry: _DenseEntry) -> None:
        """Dense → compressed: gather nonzero blocks on device."""
        nb = len(entry.block_idx)
        nb_padded = next_pow2(nb)
        # pad by repeating a real index: scatter rewrites identical data
        idx_host = np.full(nb_padded, entry.block_idx[0] if nb else 0,
                           np.int32)
        idx_host[:nb] = entry.block_idx
        idx = jax.device_put(idx_host, self.device)
        flat = entry.arr.reshape(-1)
        blocks = _gather_blocks(flat, idx, COMPRESS_BLOCK_WORDS)
        centry = _CompressedEntry(
            blocks, idx, entry.arr.shape,
            flat.shape[0] // COMPRESS_BLOCK_WORDS, entry.block_idx,
        )
        self._compressed[key] = centry
        self._compressed_bytes += centry.nbytes
        self.compressions += 1


_global_cache: DeviceRowCache | None = None


def global_row_cache() -> DeviceRowCache:
    global _global_cache
    if _global_cache is None:
        _global_cache = DeviceRowCache()
    return _global_cache


def set_global_row_cache(cache: DeviceRowCache) -> None:
    global _global_cache
    _global_cache = cache
