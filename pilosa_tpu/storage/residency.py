"""Device residency manager: which fragment rows live in HBM.

The reference mmaps every fragment file and lets the OS page cache decide
residency (fragment.go + syswrap/ — SURVEY.md §2 #3, #26). HBM is orders of
magnitude smaller than a disk page cache, so residency is explicit here: a
byte-budgeted LRU of decoded dense rows (uint32[32768] each = 128 KiB) keyed
by (fragment id, row). The host roaring file remains the source of truth and
rows are re-decoded on demand (SURVEY.md §7.3 hard part #1).

Two tiers. Hot entries are dense, ready for the bitwise kernels. When the
dense tier overflows its budget share, sparse entries are *demoted* instead
of dropped: their nonzero 4 KiB blocks are gathered on device into a compact
``uint32[nb, 1024]`` array (one jitted gather — no host round trip; block
indices were computed from the host array at insert time, so demotion never
blocks on a device→host sync). A hit on a demoted entry scatters the blocks
back into a dense array (one jitted scatter) and promotes it. For bitmap
data at real-world densities this multiplies effective HBM residency by the
inverse block-occupancy, which matters because a re-upload over host↔device
is the slowest path in the system.

A third, HOST tier backs heat-driven residency tiering
(storage/tiering.py): cold entries demote to compact nonzero-block
copies in host RAM (own byte budget, ``residency-host-tier-bytes``) and
promote back to dense on access or when the ResidencyTierer's pass sees
their heat recover — so far more indexes than fit in HBM stay one paced
upload away from device residency.

Writes invalidate the affected row in every tier; queries call ``get_row``
and receive a device array ready for the bitwise kernels.

Derived entries (the batched executor's stacked query leaves,
executor/batch.py) register an *updater* instead: a write to one fragment
row becomes an in-place device scatter of the affected shard slot
(SURVEY.md §7.3 hard part #3 — no host round trip for pure bit-adds, one
128 KiB row re-upload otherwise), so a Set() no longer evicts unrelated
resident leaves. Compressed-tier copies of an affected leaf are
invalidated rather than patched (decompress+patch costs more than the
re-decode they were demoted to avoid).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.shardwidth import WORDS_PER_SHARD, next_pow2
from pilosa_tpu.utils.cost import current_cost

ROW_BYTES = WORDS_PER_SHARD * 4  # 128 KiB per resident row

# Default budget: 4 GiB of HBM for row residency (v5e has 16 GiB; the rest
# is headroom for query intermediates + XLA workspace). Tests override.
DEFAULT_BUDGET_BYTES = 4 << 30

# Default compressed host-tier budget (residency-host-tier-bytes knob):
# host RAM parking for cold demoted entries.
DEFAULT_HOST_BUDGET_BYTES = 1 << 30

# Compression granularity: 4 KiB device blocks. Row = 32 blocks.
COMPRESS_BLOCK_WORDS = 1024

# Probe return sentinel: "this write affects the entry but it cannot be
# patched in place — drop it" (multi-host sharded leaves, where a device
# scatter would be a collective program a single host can't run alone).
PURGE = object()

# Demote-as-compressed only when it actually saves memory; denser entries
# are simply dropped (host re-decode is the fallback, as before).
COMPRESS_MAX_OCCUPANCY = 0.5


@partial(jax.jit, static_argnames=("block_words",))
def _gather_blocks(arr, idx, block_words: int):
    """Compact the nonzero blocks of a flattened array: uint32[nb, bw]."""
    return arr.reshape(-1, block_words)[idx]


@partial(jax.jit, static_argnames=("n_blocks", "block_words"))
def _scatter_blocks(blocks, idx, n_blocks: int, block_words: int):
    """Inverse of _gather_blocks. ``idx`` may contain duplicates (padding
    repeats a real index with its real data — identical writes are safe)."""
    out = jnp.zeros((n_blocks, block_words), jnp.uint32)
    return out.at[idx].set(blocks).reshape(-1)


class WriteEvent:
    """One fragment-row mutation, as seen by dependent cache entries.

    positions: in-shard bit positions touched, or None when unknown (bulk
    row replace). added: True = bits only set, False = bits only cleared,
    None = mixed/unknown.
    """

    __slots__ = ("index", "field", "view", "shard", "row", "positions",
                 "added", "scope")

    def __init__(self, index, field, view, shard, row, positions=None,
                 added=None, scope=""):
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.row = row
        self.positions = positions
        self.added = added
        self.scope = scope


class _DenseEntry:
    __slots__ = ("arr", "block_idx", "custom")

    def __init__(self, arr, block_idx, custom=False):
        self.arr = arr
        self.block_idx = block_idx  # np.int32[nb] or None = incompressible
        # custom placement (mesh-sharded device_put): pinned to its
        # sharding — never compressed, never tiered to host
        self.custom = custom


class _CompressedEntry:
    __slots__ = ("blocks", "idx", "shape", "n_blocks", "block_idx")

    def __init__(self, blocks, idx, shape, n_blocks, block_idx):
        self.blocks = blocks  # device uint32[nb_padded, bw]
        self.idx = idx  # device int32[nb_padded]
        self.shape = shape
        self.n_blocks = n_blocks
        self.block_idx = block_idx  # host copy, for re-demotion

    @property
    def nbytes(self) -> int:
        return self.blocks.nbytes + self.idx.nbytes


class _HostEntry:
    """Compressed HOST-tier copy (heat-driven residency tiering): the
    nonzero 4 KiB blocks in host RAM — or the full flat array when the
    entry is incompressible — one paced upload + scatter away from dense
    device residency. Cold fragments park here at roaring-like density
    (Chambi et al. 1402.6407), so 10-100x more indexes stay one promote
    away from HBM than HBM holds dense."""

    __slots__ = ("blocks", "idx", "shape", "n_blocks", "block_idx")

    def __init__(self, blocks, idx, shape, n_blocks, block_idx):
        self.blocks = blocks  # np.uint32[nb_padded, bw], or flat full array
        self.idx = idx  # np.int32[nb_padded], or None = full array
        self.shape = shape
        self.n_blocks = n_blocks
        self.block_idx = block_idx  # original nonzero-block index (or None)

    @property
    def nbytes(self) -> int:
        n = int(self.blocks.nbytes)
        if self.idx is not None:
            n += int(self.idx.nbytes)
        return n


class DeviceRowCache:
    """Byte-budgeted two-tier LRU of device-resident arrays (dense rows,
    BSI plane matrices, mesh-sharded shard stacks — sized by actual
    nbytes). Sparse entries compress on demotion instead of dropping."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES, device=None,
                 host_budget_bytes: int = DEFAULT_HOST_BUDGET_BYTES):
        self.budget_bytes = budget_bytes
        self.host_budget_bytes = int(host_budget_bytes)
        self.device = device
        self._rows: OrderedDict[tuple, _DenseEntry] = OrderedDict()
        self._compressed: OrderedDict[tuple, _CompressedEntry] = OrderedDict()
        # compressed HOST tier (heat-driven tiering): demoted entries in
        # host RAM, own byte budget + LRU, promoted back on access or by
        # the ResidencyTierer pass (storage/tiering.py)
        self._host: OrderedDict[tuple, _HostEntry] = OrderedDict()
        self._bytes = 0
        self._compressed_bytes = 0
        self._host_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compressions = 0
        self.decompressions = 0
        self.host_hits = 0  # host-tier lookups served (inline promotes)
        self.tier_promotions = 0  # host -> dense (lookup or pass)
        self.tier_demotions = 0  # dense/compressed -> host
        self.updates = 0  # in-place scatter updates of derived entries
        self.write_events = 0  # fragment mutations routed through apply_write
        # Snapshot validity counter: bumped whenever an entry is removed
        # or a dense array replaced (write patch, invalidate, evict,
        # demote, clear). Holders of (key -> array) snapshots taken
        # OUTSIDE this cache (the executor's operand memo) may serve
        # them only while generation is unchanged; additions never bump
        # (they cannot stale an existing snapshot). Listeners are
        # weakly-held zero-arg callables invoked on every bump so
        # snapshot holders drop their array references EAGERLY — an
        # eviction must actually free HBM, not wait for the holder's
        # next lazy validity check.
        self.generation = 0
        self._gen_listeners: list = []
        # derived-entry dependency registry: a stacked leaf registers an
        # updater under a (index, field) tag; apply_write routes each
        # fragment mutation to exactly the tagged entries
        self._updaters: dict[tuple, tuple[tuple, Callable]] = {}
        self._tag_index: dict[tuple, set[tuple]] = {}
        # One lock for all bookkeeping. Writers patch entries under it
        # (apply_write), so two concurrent writes to different fragments
        # of one field can't lose each other's read-modify-write of the
        # same leaf. Host decodes happen OUTSIDE the lock (see
        # get_or_build) so query misses don't serialize behind it.
        self._lock = threading.RLock()
        # in-flight builds: key -> buffered write events, replayed onto
        # the entry after its unlocked decode (see get_or_build); the
        # condition lets concurrent builders of one key wait for the first
        self._pending_builds: dict[tuple, list] = {}
        self._build_done = threading.Condition(self._lock)

    def __len__(self) -> int:
        return len(self._rows) + len(self._compressed)

    @property
    def bytes_used(self) -> int:
        return self._bytes + self._compressed_bytes

    @property
    def compressed_bytes(self) -> int:
        return self._compressed_bytes

    @property
    def host_bytes(self) -> int:
        return self._host_bytes

    def touch(self, keys) -> None:
        """Refresh LRU positions without fetching (executor operand-memo
        hits: the leaves are served from the memo, but they must not
        look LRU-cold and become eviction's first victims)."""
        with self._lock:
            for key in keys:
                if key in self._rows:
                    self._rows.move_to_end(key)
                elif key in self._compressed:
                    self._compressed.move_to_end(key)

    def add_generation_listener(self, fn) -> None:
        """Register a bound method invoked (under the cache lock) on
        every generation bump; held via WeakMethod so registrants can be
        garbage-collected. Listeners must be lock-free and cheap (the
        executor's is a dict.clear)."""
        with self._lock:
            self._gen_listeners.append(weakref.WeakMethod(fn))

    def remove_generation_listener(self, fn) -> None:
        """Unregister ``fn`` (and drop dead refs). Re-homing callers
        (the executor when the global cache is swapped) must remove
        themselves from the OLD cache: a still-registered listener
        would keep wholesale-clearing state that now tracks the new
        cache, and a swap-back would stack duplicate registrations."""
        with self._lock:
            live = []
            for ref in self._gen_listeners:
                cb = ref()  # bind once: a second ref() could race GC
                if cb is not None and cb != fn:
                    live.append(ref)
            self._gen_listeners = live

    def _bump_generation(self) -> None:
        """Caller holds the lock. Bump + notify snapshot holders."""
        self.generation += 1
        if self._gen_listeners:
            live = []
            for ref in self._gen_listeners:
                cb = ref()
                if cb is not None:
                    cb()
                    live.append(ref)
            self._gen_listeners = live

    def _lookup_locked(self, key: tuple):
        """Dense hit or compressed→dense promotion; None on miss.
        Caller holds the lock."""
        entry = self._rows.get(key)
        if entry is not None:
            self.hits += 1
            self._rows.move_to_end(key)
            return entry.arr
        centry = self._compressed.pop(key, None)
        if centry is not None:
            self.hits += 1
            self.decompressions += 1
            self._compressed_bytes -= centry.nbytes
            flat = _scatter_blocks(
                centry.blocks, centry.idx, centry.n_blocks,
                COMPRESS_BLOCK_WORDS,
            )
            arr = flat.reshape(centry.shape)
            self._insert_dense(key, arr, centry.block_idx)
            return arr
        hentry = self._host.pop(key, None)
        if hentry is not None:
            # host-tier hit: upload + scatter + promote inline — the
            # access IS the heat (the tiering pass sweeps what queries
            # didn't touch). Updaters stayed registered across the
            # demotion, so the promoted entry keeps its write routing.
            self.hits += 1
            self.host_hits += 1
            self.tier_promotions += 1
            self._host_bytes -= hentry.nbytes
            arr = self._upload_host_entry(hentry)
            self._insert_dense(key, arr, hentry.block_idx)
            return arr
        return None

    def _put_locked(self, key, host, device_put):
        if device_put is not None:
            arr = device_put(host)
            block_idx = None  # custom placement (mesh sharding): keep dense
        else:
            arr = jax.device_put(host, self.device)
            block_idx = self._host_block_index(host)
        self._insert_dense(key, arr, block_idx,
                           custom=device_put is not None)
        cost = current_cost()
        if cost is not None:  # host→device bytes for the active request
            cost.note_upload(int(arr.nbytes))
        return arr

    def get_row(self, key: tuple, decode: Callable[[], np.ndarray],
                device_put: Callable | None = None) -> jax.Array:
        """Return the device array for ``key``, decoding+uploading on miss.
        ``device_put`` overrides placement (e.g. a NamedSharding put);
        entries with custom placement are never compressed."""
        cost = current_cost()
        with self._lock:
            arr = self._lookup_locked(key)
            if arr is not None:
                if cost is not None:
                    cost.note_cache(True)
                return arr
            self.misses += 1
            if cost is not None:
                cost.note_cache(False)
            # decode under the lock: plain get_row keys are per-fragment
            # (invalidated by their writers), so staleness isn't possible,
            # and single-row decodes are cheap
            return self._put_locked(key, decode(), device_put)

    def get_or_build(self, key: tuple, tag: tuple | None,
                     probe: Callable | None,
                     decode: Callable[[], np.ndarray],
                     device_put: Callable | None = None) -> jax.Array:
        """get_row for derived (write-patched) entries.

        Event-buffered build: on a miss, the builder registers the
        probe (produced by the ``probe`` zero-arg factory) and claims the
        key BEFORE decoding, so writes landing during the unlocked host
        decode are buffered (apply_write) and replayed as patches after
        the upload — no write can be missed, the slow decode never holds
        the global lock (queries and writers to other keys proceed), and
        concurrent builders of the SAME key wait on the first instead of
        decoding twice. Delta patches are idempotent, so an event whose
        write the decode already saw replays harmlessly. A buffered
        event the probe cannot patch (PURGE — multi-host sharded leaves)
        forces one re-decode under the lock, which writers then
        serialize behind."""
        cost = current_cost()
        with self._lock:
            while True:
                arr = self._lookup_locked(key)
                if arr is not None:
                    if tag is not None:
                        self._register_locked(key, tag, probe)
                    if cost is not None:
                        cost.note_cache(True)
                    return arr
                if key not in self._pending_builds:
                    break
                self._build_done.wait()  # another thread is building key
            if cost is not None:
                cost.note_cache(False)
            buf: list = []
            self._pending_builds[key] = buf
            if tag is not None:
                # route this tag's writes into the buffer from now on
                self._updaters[key] = (tag, probe())
                self._tag_index.setdefault(tag, set()).add(key)
        try:
            host = decode()  # slow host work, outside the lock
        except BaseException:
            with self._lock:
                self._pending_builds.pop(key, None)
                self._drop_updater(key)
                self._build_done.notify_all()
            raise
        with self._lock:
            try:
                self.misses += 1
                reg = self._updaters.get(key)
                if tag is not None and reg is None:
                    # invalidate_tag raced the build (field delete): the
                    # decode belongs to a dead field — serve it to this
                    # query but don't cache it
                    return (jax.device_put(host, self.device)
                            if device_put is None else device_put(host))
                arr = self._put_locked(key, host, device_put)
                for ev in buf:  # replay writes that landed mid-decode
                    apply = reg[1](ev) if reg is not None else None
                    if apply is None:
                        continue
                    if apply is PURGE:
                        # can't patch: drop the first upload (and its
                        # byte accounting) and re-decode with writers
                        # held off
                        old = self._rows.pop(key, None)
                        if old is not None:
                            self._bytes -= old.arr.nbytes
                        arr = self._put_locked(key, decode(), device_put)
                        break
                    entry = self._rows.get(key)
                    if entry is not None:
                        entry.arr = apply(entry.arr)
                        entry.block_idx = None
                        arr = entry.arr
                return arr
            finally:
                self._pending_builds.pop(key, None)
                self._build_done.notify_all()

    @staticmethod
    def _host_block_index(host: np.ndarray):
        """Nonzero-block indices, computed from the host array at insert
        time (free pass over data already in cache) so demotion later
        needs no device→host sync. None = incompressible."""
        if host.dtype != np.uint32 or host.size % COMPRESS_BLOCK_WORDS:
            return None
        mask = np.any(
            host.reshape(-1, COMPRESS_BLOCK_WORDS) != 0, axis=1
        )
        if mask.mean() > COMPRESS_MAX_OCCUPANCY:
            return None
        return np.flatnonzero(mask).astype(np.int32)

    def _insert_dense(self, key: tuple, arr, block_idx,
                      custom: bool = False) -> None:
        self._rows[key] = _DenseEntry(arr, block_idx, custom)
        self._bytes += arr.nbytes
        self._evict()

    def invalidate(self, key: tuple) -> None:
        with self._lock:
            entry = self._rows.pop(key, None)
            if entry is not None:
                self._bytes -= entry.arr.nbytes
            centry = self._compressed.pop(key, None)
            if centry is not None:
                self._compressed_bytes -= centry.nbytes
            # host copies invalidate like compressed ones: decompress+
            # patch costs more than the re-decode they were demoted to
            # avoid (apply_write's missing-dense branch lands here)
            hentry = self._host.pop(key, None)
            if hentry is not None:
                self._host_bytes -= hentry.nbytes
            if entry is not None or centry is not None \
                    or hentry is not None:
                self._bump_generation()
            self._drop_updater(key)

    def invalidate_fragment(self, frag_id: tuple) -> None:
        with self._lock:
            for store in (self._rows, self._compressed, self._host):
                doomed = [k for k in store if k[: len(frag_id)] == frag_id]
                for k in doomed:
                    self.invalidate(k)

    # --------------------------------------------------- derived-entry updates

    def register_updater(self, key: tuple, tag: tuple,
                         probe: Callable) -> None:
        """Attach a write-routing probe to a resident derived entry.

        ``probe(event)`` returns None when the entry is unaffected by the
        write, else a function ``apply(arr) -> arr`` that patches the
        device array in place (scatter of the affected shard slot).
        Idempotent per key; dropped when the entry leaves both tiers.
        """
        with self._lock:
            self._register_locked(key, tag, lambda: probe)

    def _register_locked(self, key: tuple, tag: tuple, probe_factory) -> None:
        if key in self._rows or key in self._compressed:
            old = self._updaters.get(key)
            if old is not None and old[0] == tag:
                return  # already registered; probes are stateless closures
            if old is not None:
                self._tag_index[old[0]].discard(key)
            self._updaters[key] = (tag, probe_factory())
            self._tag_index.setdefault(tag, set()).add(key)

    def invalidate_tag(self, tag: tuple) -> None:
        """Drop every derived entry registered under a (index, field) tag
        (field close/delete: the durable files are no longer ours)."""
        with self._lock:
            for key in list(self._tag_index.get(tag, ())):
                self.invalidate(key)

    def _drop_updater(self, key: tuple) -> None:
        reg = self._updaters.pop(key, None)
        if reg is not None:
            keys = self._tag_index.get(reg[0])
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._tag_index[reg[0]]

    def apply_write(self, event: WriteEvent) -> None:
        """Route one fragment mutation to the derived entries that depend
        on it: dense entries are patched on device, compressed copies are
        invalidated, everything else is untouched (this replaces the old
        global write-generation purge, which evicted EVERY stacked leaf on
        any write). Runs fully under the lock so concurrent writers can't
        lose each other's read-modify-write of a shared leaf."""
        tag = (event.scope, event.index, event.field)
        with self._lock:
            self.write_events += 1
            for key in list(self._tag_index.get(tag, ())):
                reg = self._updaters.get(key)
                if reg is None:
                    continue
                pending = self._pending_builds.get(key)
                if pending is not None:
                    # key is mid-build: its decode may or may not see this
                    # write — buffer it for replay after the upload
                    pending.append(event)
                    continue
                apply = reg[1](event)
                if apply is None:
                    continue  # unaffected (different row/view/shard)
                if apply is PURGE:
                    self.invalidate(key)
                    continue
                entry = self._rows.get(key)
                if entry is not None:
                    entry.arr = apply(entry.arr)
                    # occupancy may have changed; don't demote later
                    entry.block_idx = None
                    self.updates += 1
                    self._bump_generation()
                else:
                    self.invalidate(key)

# ---------------------------------------------------- host tier (tiering)

    def demote_fragment_to_host(self, scope: str, index: str, field: str,
                                shard: int) -> tuple[int, int]:
        """Host-demote every per-fragment entry of one (scope, index,
        field, shard) — the ResidencyTierer's cold verdict. Returns
        (entries moved, device bytes freed). A reader between tiers
        re-decodes from the roaring file (the miss path): old-resident
        or new-resident, never absent — the scrub read-repair swap
        discipline."""
        with self._lock:
            return self._demote_matching_locked(
                lambda k: self._frag_match(k, scope, index, field, shard))

    def demote_field_stacks_to_host(self, scope: str, index: str,
                                    field: str) -> tuple[int, int]:
        """Host-demote the batched executor's stacked leaves of one
        field (a leaf spans a whole shard block, so stacks tier at
        field granularity — the tiering pass uses the field's MAX shard
        heat). Updaters stay registered: a write routed to a host-tier
        leaf invalidates it (apply_write's missing-dense branch),
        exactly like compressed-tier copies."""
        with self._lock:
            return self._demote_matching_locked(
                lambda k: self._stack_match(k, scope, index, field))

    @staticmethod
    def _frag_match(key: tuple, scope, index, field, shard) -> bool:
        # frag_id + (row,) / frag_id + ("__planes__", depth):
        # (scope, index, field, view, shard, ...) — never a stack key
        # (those lead with a "stack*" tag, not the holder scope)
        return (len(key) >= 6 and key[0] == scope and key[1] == index
                and key[2] == field and isinstance(key[4], int)
                and key[4] == shard
                and not (isinstance(key[0], str)
                         and key[0].startswith("stack")))

    @staticmethod
    def _stack_match(key: tuple, scope, index, field) -> bool:
        # ("stack"/"stackp", scope, index, field, ...); "stackm"
        # (mesh-sharded) and "stackz" (the shared zero leaf) never tier
        return (len(key) >= 4 and key[0] in ("stack", "stackp")
                and key[1] == scope and key[2] == index
                and key[3] == field)

    def _demote_matching_locked(self, match) -> tuple[int, int]:
        moved = 0
        freed = 0
        for key in [k for k, e in self._rows.items()
                    if not e.custom and match(k)]:
            entry = self._rows.pop(key)
            self._bytes -= entry.arr.nbytes
            freed += entry.arr.nbytes
            self._bump_generation()
            host = np.asarray(entry.arr).reshape(-1)
            block_idx = entry.block_idx
            if block_idx is None:
                # write-patched entries lost their block index;
                # recompute from the host copy (occupancy may have
                # changed either way)
                block_idx = self._host_block_index(
                    host.reshape(entry.arr.shape))
            self._host_insert_locked(key, host, entry.arr.shape,
                                     block_idx)
            moved += 1
        for key in [k for k in self._compressed if match(k)]:
            centry = self._compressed.pop(key)
            self._compressed_bytes -= centry.nbytes
            freed += centry.nbytes
            self._bump_generation()
            hentry = _HostEntry(
                np.asarray(centry.blocks), np.asarray(centry.idx),
                centry.shape, centry.n_blocks, centry.block_idx,
            )
            self._host[key] = hentry
            self._host_bytes += hentry.nbytes
            moved += 1
        if moved:
            self.tier_demotions += moved
            self._evict_host_locked()
        return moved, freed

    def _host_insert_locked(self, key: tuple, flat_host: np.ndarray,
                            shape, block_idx) -> None:
        if block_idx is not None and len(block_idx):
            nb = len(block_idx)
            nb_padded = next_pow2(nb)
            idx_host = np.full(nb_padded, block_idx[0], np.int32)
            idx_host[:nb] = block_idx
            blocks = flat_host.reshape(
                -1, COMPRESS_BLOCK_WORDS)[idx_host].copy()
            hentry = _HostEntry(
                blocks, idx_host, shape,
                flat_host.size // COMPRESS_BLOCK_WORDS, block_idx,
            )
        else:
            # incompressible (dense occupancy / odd shape) or all-zero:
            # park the full flat copy — host RAM is the cheap tier
            hentry = _HostEntry(flat_host.copy(), None, shape, 0,
                                block_idx)
        self._host[key] = hentry
        self._host_bytes += hentry.nbytes

    def _upload_host_entry(self, hentry: _HostEntry):
        """Host → device for one host-tier entry: upload the compact
        blocks and scatter them back to the dense shape (or upload the
        full array when incompressible). Billed to the active request
        as upload bytes, like any residency miss."""
        if hentry.idx is not None:
            blocks = jax.device_put(hentry.blocks, self.device)
            idx = jax.device_put(hentry.idx, self.device)
            flat = _scatter_blocks(blocks, idx, hentry.n_blocks,
                                   COMPRESS_BLOCK_WORDS)
            arr = flat.reshape(hentry.shape)
        else:
            arr = jax.device_put(
                hentry.blocks.reshape(hentry.shape), self.device)
        cost = current_cost()
        if cost is not None:
            cost.note_upload(int(arr.nbytes))
        return arr

    def promote_key(self, key: tuple) -> int:
        """Tiering-pass promotion of one host-tier entry back to dense
        residency; returns the host bytes freed, 0 when the key is no
        longer host-resident (a query's lookup promoted it first — the
        pacer sleeps OUTSIDE the lock, so this race is expected)."""
        with self._lock:
            hentry = self._host.pop(key, None)
            if hentry is None:
                return 0
            self._host_bytes -= hentry.nbytes
            self.tier_promotions += 1
            arr = self._upload_host_entry(hentry)
            self._insert_dense(key, arr, hentry.block_idx)
            return int(hentry.nbytes)

    def host_keys_of(self, scope: str, index: str, field: str,
                     shard: int) -> list:
        """(key, nbytes) of the host-tier entries of one fragment —
        the tiering pass promotes them outside the lock (paced)."""
        with self._lock:
            return [(k, e.nbytes) for k, e in self._host.items()
                    if self._frag_match(k, scope, index, field, shard)]

    def host_stack_keys_of(self, scope: str, index: str,
                           field: str) -> list:
        with self._lock:
            return [(k, e.nbytes) for k, e in self._host.items()
                    if self._stack_match(k, scope, index, field)]

    def _evict_host_locked(self) -> None:
        # LRU within the host tier's own budget; no generation bump
        # (snapshots only ever hold device arrays)
        while self._host_bytes > self.host_budget_bytes and self._host:
            key, hentry = self._host.popitem(last=False)
            self._host_bytes -= hentry.nbytes
            self.evictions += 1
            self._drop_updater(key)

    def tier_overlay(self) -> tuple[dict, dict]:
        """The tiering manager's world view and the
        ``/debug/heatmap?tier=true`` column source:
        ``(per_fragment, per_field_stacks)`` — bytes by tier keyed
        (scope, index, field, shard) for per-fragment row/plane entries
        and (scope, index, field) for the batched executor's stacked
        leaves (a leaf spans a whole shard block). Mesh-sharded and
        zero leaves are excluded (never tiered)."""
        with self._lock:
            stores = (("dense", self._rows,
                       lambda e: 0 if e.custom else e.arr.nbytes),
                      ("compressed", self._compressed,
                       lambda e: e.nbytes),
                      ("host", self._host, lambda e: e.nbytes))
            per_frag: dict[tuple, dict] = {}
            per_stack: dict[tuple, dict] = {}
            for tier, store, size in stores:
                for key, entry in store.items():
                    nbytes = int(size(entry))
                    if nbytes == 0 and tier == "dense":
                        continue  # custom placement: not tierable
                    tag = key[0]
                    if isinstance(tag, str) and tag.startswith("stack"):
                        # the stack test runs FIRST (residency_overlay's
                        # order): a plane-stack key ("stackp", scope,
                        # index, field, 2+depth, block) is len 6 with an
                        # int at [4] and would otherwise masquerade as a
                        # fragment entry under a bogus key with heat 0 —
                        # demoted every pass no matter how hot the field
                        if tag not in ("stack", "stackp") or len(key) < 4:
                            continue  # stackm (mesh) / stackz: not tiered
                        out, okey = per_stack, (key[1], key[2], key[3])
                    elif len(key) >= 6 and isinstance(key[4], int):
                        out, okey = per_frag, (key[0], key[1], key[2],
                                               key[4])
                    else:
                        continue
                    slot = out.get(okey)
                    if slot is None:
                        slot = out[okey] = {"dense": 0, "compressed": 0,
                                            "host": 0}
                    slot[tier] += nbytes
        return per_frag, per_stack

    def residency_overlay(self) -> tuple[dict, dict]:
        """HBM residency bucketed for the heat map (/debug/heatmap):
        ``(per_fragment, per_field)`` — exact bytes per (scope, index,
        field, shard) for per-fragment row/plane entries, and (scope,
        index, field) totals for the batched executor's stacked leaves
        (one stacked array spans a whole shard block, so its bytes
        cannot honestly be attributed to a single shard). Scope leads
        (the holder tag, as in frag_id/leaf_key) so in-process
        multi-holder setups never conflate replicas. Key shapes are
        pinned by executor/batch.leaf_key and Fragment.frag_id."""
        with self._lock:
            items = [(k, e.arr.nbytes) for k, e in self._rows.items()]
            items += [(k, e.nbytes) for k, e in self._compressed.items()]
        per_frag: dict[tuple, int] = {}
        per_field: dict[tuple, int] = {}
        for key, nbytes in items:
            tag = key[0]
            if isinstance(tag, str) and tag.startswith("stack"):
                # ("stack"/"stackp", scope, index, field, ...) and
                # ("stackm", scope, index, field, view, ...); "stackz"
                # (the shared zero leaf) belongs to nobody
                if len(key) >= 4 and tag != "stackz":
                    fkey = (key[1], key[2], key[3])
                    per_field[fkey] = per_field.get(fkey, 0) + int(nbytes)
                continue
            if len(key) >= 6 and isinstance(key[4], int):
                # frag_id + (row,) / frag_id + ("__planes__", depth):
                # (scope, index, field, view, shard, ...)
                fkey = (key[0], key[1], key[2], key[4])
                per_frag[fkey] = per_frag.get(fkey, 0) + int(nbytes)
        return per_frag, per_field

    # metrics() keys that are monotonic counters (get the Prometheus
    # _total suffix); the rest are point-in-time gauges
    _MONOTONIC_METRICS = frozenset({
        "residency_hits", "residency_misses", "residency_evictions",
        "residency_compressions", "residency_decompressions",
        "residency_updates", "residency_write_events",
        "residency_host_hits", "residency_tier_promotions",
        "residency_tier_demotions",
    })

    def metrics(self) -> dict:
        """Operational gauges/counters for /metrics and /debug/vars (the
        HBM LRU is the system's central capacity mechanism — reference
        analog: syswrap's mmap-count limits, SURVEY.md §2 #26)."""
        with self._lock:
            return {
                "residency_entries": len(self._rows) + len(self._compressed),
                "residency_entries_compressed": len(self._compressed),
                "residency_bytes_used": self.bytes_used,
                "residency_bytes_compressed": self._compressed_bytes,
                "residency_budget_bytes": self.budget_bytes,
                "residency_hits": self.hits,
                "residency_misses": self.misses,
                "residency_evictions": self.evictions,
                "residency_compressions": self.compressions,
                "residency_decompressions": self.decompressions,
                "residency_updates": self.updates,
                "residency_write_events": self.write_events,
                "residency_entries_host": len(self._host),
                "residency_bytes_host": self._host_bytes,
                "residency_host_budget_bytes": self.host_budget_bytes,
                "residency_host_hits": self.host_hits,
                "residency_tier_promotions": self.tier_promotions,
                "residency_tier_demotions": self.tier_demotions,
            }

    def prometheus_lines(self, prefix: str = "pilosa_tpu",
                         seen: set | None = None) -> str:
        """metrics() in Prometheus text form, following the stats
        registry's conventions (one render shared by every consumer):
        counters carry the _total suffix; values are ints emitted
        exactly (no %g truncation of byte gauges or large counters).
        Each family leads with # HELP/# TYPE so a stock Prometheus
        scrape ingests the block (docs/OBSERVABILITY.md); ``seen``
        shares the page-wide family-metadata dedupe. One renderer for
        the whole exposition page — stats.prometheus_block."""
        from pilosa_tpu.utils.stats import prometheus_block

        return prometheus_block(
            {
                (f"{name}_total" if name in self._MONOTONIC_METRICS
                 else name): v
                for name, v in self.metrics().items()
            },
            prefix, seen=seen,
        )

    def clear(self) -> None:
        with self._lock:
            self._bump_generation()
            self._rows.clear()
            self._compressed.clear()
            self._host.clear()
            self._updaters.clear()
            self._tag_index.clear()
            self._bytes = 0
            self._compressed_bytes = 0
            self._host_bytes = 0

    def _evict(self) -> None:
        # Demotion only under real pressure: the dense tier may use the
        # whole budget while it fits (a fully-resident working set stays
        # fully resident, as in the single-tier cache). Over budget, LRU
        # dense entries demote (compressible — shrinks usage) or drop;
        # then LRU compressed entries drop.
        while self.bytes_used > self.budget_bytes and len(self._rows) > 1:
            key, entry = self._rows.popitem(last=False)
            self._bytes -= entry.arr.nbytes
            self._bump_generation()
            if entry.block_idx is not None:
                self._demote(key, entry)  # key stays resident (compressed)
            else:
                self.evictions += 1
                self._drop_updater(key)
        while self.bytes_used > self.budget_bytes and self._compressed:
            key, centry = self._compressed.popitem(last=False)
            self._compressed_bytes -= centry.nbytes
            self._bump_generation()
            self.evictions += 1
            self._drop_updater(key)

    def _demote(self, key: tuple, entry: _DenseEntry) -> None:
        """Dense → compressed: gather nonzero blocks on device."""
        nb = len(entry.block_idx)
        nb_padded = next_pow2(nb)
        # pad by repeating a real index: scatter rewrites identical data
        idx_host = np.full(nb_padded, entry.block_idx[0] if nb else 0,
                           np.int32)
        idx_host[:nb] = entry.block_idx
        idx = jax.device_put(idx_host, self.device)
        flat = entry.arr.reshape(-1)
        blocks = _gather_blocks(flat, idx, COMPRESS_BLOCK_WORDS)
        centry = _CompressedEntry(
            blocks, idx, entry.arr.shape,
            flat.shape[0] // COMPRESS_BLOCK_WORDS, entry.block_idx,
        )
        self._compressed[key] = centry
        self._compressed_bytes += centry.nbytes
        self.compressions += 1


_global_cache: DeviceRowCache | None = None


def global_row_cache() -> DeviceRowCache:
    global _global_cache
    if _global_cache is None:
        _global_cache = DeviceRowCache()
    return _global_cache


def set_global_row_cache(cache: DeviceRowCache) -> None:
    global _global_cache
    _global_cache = cache
