"""Per-shard heat telemetry: decayed access/write counters per
(index, field, shard), with an HBM-residency overlay at
``GET /debug/heatmap``.

This is the admission signal ROADMAP open item 5's promote/demote policy
consumes: under Zipf multitenant traffic the residency manager needs to
know which fragments are HOT NOW — a raw access counter never forgets a
bulk scan from an hour ago, so heat decays exponentially
(``half-life`` knob, default 5 minutes) and a cold tenant's shards sink
toward zero without any sweeper thread: decay is applied lazily at read
and update time from the stored (value, last-touch) pair.

Recording cost: the executor records one batched access per resolved
query leaf (index, field, whole shard list — one lock round trip), and
fragments record writes per mutation batch. The plane shares the cost
kill switch (utils/cost.set_cost_enabled) so the bench's bare baseline
can price the hooks.
"""

from __future__ import annotations

import threading
import time

DEFAULT_HALF_LIFE_S = 300.0


class HeatMap:
    """Decayed per-(index, field, shard) access/write counters."""

    # Decay is applied lazily and AMORTIZED: between applications the
    # raw adds accumulate, and once an entry's last decay is older than
    # this many seconds the pending decay folds in. The bounded error
    # (an add inside the interval decays as if it landed at the
    # interval's start) is negligible against a 5-minute half-life, and
    # it keeps the serving hot path to dict adds — no pow() per query.
    DECAY_INTERVAL_S = 1.0

    def __init__(self, half_life_s: float = DEFAULT_HALF_LIFE_S):
        self.half_life_s = float(half_life_s)
        self._lock = threading.Lock()
        # (scope, index, field, shard) -> [access, write, last_decay].
        # scope (the holder-unique data-dir tag, same convention as
        # frag_id/leaf_key) leads the key: two embedded Servers in one
        # process hold DIFFERENT replicas' data under identical
        # index/field names, and merging their heat would corrupt the
        # promote/demote signal exactly in in-process cluster setups.
        self._h: dict[tuple, list] = {}
        self.accesses_total = 0
        self.writes_total = 0

    def _decayed(self, entry: list, now: float) -> None:
        dt = now - entry[2]
        if dt >= self.DECAY_INTERVAL_S or self.half_life_s < 2.0:
            factor = 0.5 ** (dt / max(self.half_life_s, 1e-9))
            entry[0] *= factor
            entry[1] *= factor
            entry[2] = now

    def record_access(self, index: str, field: str, shards,
                      n: float = 1.0, scope: str = "") -> None:
        self.record_access_many(index, (field,), shards, n=n, scope=scope)

    def record_access_many(self, index: str, fields, shards,
                           n: float = 1.0, scope: str = "") -> None:
        """One query's resolved leaves touched ``shards`` of every field
        in ``fields`` — batched: ONE lock round trip for the whole
        assembly (the executor calls this once per operand resolution,
        the serving hot path)."""
        now = time.monotonic()
        fresh = False
        with self._lock:
            self.accesses_total += len(shards) * len(fields)
            for field in fields:
                for shard in shards:
                    key = (scope, index, field, shard)
                    entry = self._h.get(key)
                    if entry is None:
                        self._h[key] = [float(n), 0.0, now]
                        fresh = True
                    else:
                        self._decayed(entry, now)
                        entry[0] += n
        if fresh:  # table can only grow when a key was inserted
            self._maybe_prune()

    def record_write(self, index: str, field: str, shard: int,
                     n: float = 1.0, scope: str = "") -> None:
        now = time.monotonic()
        fresh = False
        with self._lock:
            self.writes_total += 1
            key = (scope, index, field, int(shard))
            entry = self._h.get(key)
            if entry is None:
                self._h[key] = [0.0, float(n), now]
                fresh = True
            else:
                self._decayed(entry, now)
                entry[1] += n
        if fresh:  # a write-only workload (bulk ingest) must bound the
            self._maybe_prune()  # table too, not just the read path

    def _maybe_prune(self, max_entries: int = 65536) -> None:
        """Bound the table: shard churn across many indexes must not
        grow it forever. Coldest (fully-decayed) entries drop first."""
        if len(self._h) <= max_entries:  # racy pre-check: prune is best-
            return                       # effort, the lock below is exact
        with self._lock:
            if len(self._h) <= max_entries:
                return
            now = time.monotonic()
            scored = []
            for key, entry in self._h.items():
                self._decayed(entry, now)
                scored.append((entry[0] + entry[1], key))
            scored.sort()
            for _, key in scored[: len(self._h) - max_entries // 2]:
                del self._h[key]

    # --------------------------------------------------------------- views

    def snapshot(self, k: int = 0, residency_overlay: bool = True
                 ) -> dict:
        """Heat table sorted hottest-first (access + write heat), each
        row overlaid with its device residency: exact bytes for
        per-fragment row entries, plus the (index, field)-level stacked
        leaf bytes the batched executor holds (one stacked array spans a
        whole shard block, so it cannot be attributed to one shard)."""
        now = time.monotonic()
        with self._lock:
            rows = []
            for (scope, index, field, shard), entry in self._h.items():
                self._decayed(entry, now)
                row = {
                    "index": index, "field": field, "shard": shard,
                    "access": round(entry[0], 3),
                    "writes": round(entry[1], 3),
                }
                if scope:
                    row["scope"] = scope
                rows.append(row)
        rows.sort(key=lambda r: r["access"] + r["writes"], reverse=True)
        if k:
            rows = rows[:k]
        out = {"halfLifeS": self.half_life_s, "shards": rows}
        if residency_overlay:
            from pilosa_tpu.storage.residency import global_row_cache

            per_frag, per_field = global_row_cache().residency_overlay()
            for r in rows:
                key = (r.get("scope", ""), r["index"], r["field"],
                       r["shard"])
                nbytes = per_frag.get(key, 0)
                r["residentBytes"] = nbytes
                r["resident"] = bool(
                    nbytes or per_field.get(
                        (r.get("scope", ""), r["index"], r["field"]))
                )
            out["stackedBytesByField"] = [
                {"index": i, "field": f, "bytes": b,
                 **({"scope": s} if s else {})}
                for (s, i, f), b in sorted(per_field.items())
            ]
        return out

    def hottest(self, k: int = 10) -> list[dict]:
        return self.snapshot(k=k, residency_overlay=False)["shards"]

    def metrics(self) -> dict:
        with self._lock:
            return {
                "tracked_shards": len(self._h),
                "accesses_total": self.accesses_total,
                "writes_total": self.writes_total,
                "half_life_seconds": self.half_life_s,
            }

    def prometheus_lines(self, prefix: str, seen: set | None = None,
                         max_series: int = 32) -> str:
        """Untagged summary block plus the ``max_series`` hottest shards
        as tagged gauges (the full table lives at /debug/heatmap)."""
        from pilosa_tpu.utils.stats import (
            _meta_lines,
            escape_label,
            prometheus_block,
        )

        seen = seen if seen is not None else set()
        text = prometheus_block(self.metrics(), prefix, "heat", seen=seen)
        lines: list[str] = []
        family = f"{prefix}_heat_shard"
        lines.extend(_meta_lines(
            family, "gauge", "decayed per-shard access+write heat "
            "(hottest shards only; full table at /debug/heatmap)", seen,
        ))
        for r in self.hottest(max_series):
            # scope ALWAYS in the label set (empty for unscoped direct
            # constructions): two in-process holders sharing the global
            # map would otherwise emit duplicate samples under identical
            # labels — an invalid exposition page
            lines.append(
                f'{family}{{scope="{escape_label(r.get("scope", ""))}",'
                f'index="{escape_label(r["index"])}",'
                f'field="{escape_label(r["field"])}",'
                f'shard="{r["shard"]}"}} '
                f'{r["access"] + r["writes"]:g}'
            )
        return text + "\n".join(lines) + ("\n" if lines else "")

    def clear(self) -> None:
        with self._lock:
            self._h.clear()
            self.accesses_total = 0
            self.writes_total = 0


def merge_shard_heat(row_lists) -> dict:
    """Cluster-wide per-(index, shard) heat from several nodes'
    ``snapshot()["shards"]`` row lists — the autopilot planner's unit
    of movement is the (index, shard) group, summing field-level rows.

    Rows are first deduped by their full (scope, index, field, shard)
    key with MAX-merge: an in-process cluster shares one global heat
    map, so polling every member returns the same entries n times —
    max is exact dedup there, while genuinely distinct nodes (unique
    data-dir scope tags) contribute their own entries. Malformed rows
    are skipped, not fatal: one old-wire peer must not blank the
    plan."""
    by_key: dict[tuple, float] = {}
    for rows in row_lists:
        for r in rows or []:
            try:
                key = (str(r.get("scope", "")), str(r["index"]),
                       str(r["field"]), int(r["shard"]))
                heat = (float(r.get("access", 0.0))
                        + float(r.get("writes", 0.0)))
            except (AttributeError, KeyError, TypeError, ValueError):
                continue
            if heat > by_key.get(key, -1.0):
                by_key[key] = heat
    out: dict[tuple, float] = {}
    for (_scope, index, _field, shard), heat in by_key.items():
        group = (index, shard)
        out[group] = out.get(group, 0.0) + heat
    return out


_global_heat: HeatMap | None = None


def global_heat() -> HeatMap:
    global _global_heat
    if _global_heat is None:
        _global_heat = HeatMap()
    return _global_heat


def set_global_heat(heat: HeatMap) -> None:
    global _global_heat
    _global_heat = heat
