"""Incremental manifest backup / restore for a holder data dir.

The round-5 story was ``tar.gz`` the whole tree — a FULL copy every
time, offline only, no verification. This module is the object-store
shape instead (reference ctl backup lineage, rebuilt on the PR-4
checksum-block machinery):

- ``<dest>/blobs/<digest>`` — content-addressed payloads, zlib
  compressed (Chambi et al. 1402.6407: roaring payloads compress
  dramatically). Fragment data is stored per BLOCK_ROWS checksum block
  (storage/fragment.py blocks()), so a backup generation only writes
  the blocks that changed since ANY previous generation — unchanged
  blocks, and identical blocks across fragments, are free.
- ``<dest>/<gen>/MANIFEST.json`` — one immutable manifest per
  generation: every fragment's (block → digest) list plus
  content-hashes of the sidecar stores (.meta, translate log, attr
  dbs). Restore of any generation is self-contained.

Fragment payloads come from the LIVE bitmaps under each fragment's lock
(``blocks()``/``block_ids()``), not from files — so a backup taken from
an open holder is consistent per fragment even in ``group`` durability
mode, where fragment files lag the WAL. Restore verifies every block
against its manifest digest before writing; corruption fails loudly
instead of restoring garbage.

``backup_from_host`` does the same walk over a LIVE cluster through the
anti-entropy wire (one ``sync_manifest`` RTT per (node, index), blocks
fetched as multi-block deltas) — riding the PR-4 zlib/pacer transfer
path, so a backup storm can be rate-shaped away from serving traffic.
"""

from __future__ import annotations

import datetime as dt
import hashlib
import json
import os
import zlib

import numpy as np

from pilosa_tpu.storage.wal import fsync_dir

MANIFEST_NAME = "MANIFEST.json"
_SKIP_SUFFIXES = (".cache", ".tmp", ".snapshotting")


def _digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _ids_digest(ids: np.ndarray) -> str:
    """The SAME digest fragment.blocks() publishes for a checksum block
    (and the sync manifest carries) — backup, anti-entropy, and restore
    verification all speak one checksum language."""
    return _digest(np.ascontiguousarray(ids).astype("<u8").tobytes())


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def _write_blob(blob_dir: str, digest: str, payload: bytes) -> bool:
    """Store one content-addressed payload; returns False when the blob
    already existed (the incremental fast path)."""
    path = os.path.join(blob_dir, digest)
    if os.path.exists(path):
        return False
    _atomic_write(path, zlib.compress(payload, 6))
    return True


def _read_raw(src: str, digest: str) -> bytes:
    path = os.path.join(src, "blobs", digest)
    with open(path, "rb") as f:
        try:
            return zlib.decompress(f.read())
        except zlib.error as e:
            # bit rot must surface as the designed verification error,
            # not a raw zlib traceback through the CLI
            raise ValueError(
                f"backup blob {digest} fails content verification "
                f"(corrupt compression stream: {e})"
            ) from e


def _read_blob(src: str, digest: str) -> bytes:
    data = _read_raw(src, digest)
    if _digest_matches(digest, data):
        return data
    raise ValueError(f"backup blob {digest} fails content verification")


def _read_block_ids(src: str, digest: str):
    """Read one fragment-block blob and return its verified IDs —
    decode + digest exactly ONCE. (Fragment blobs are addressed by IDs
    digest, so the generic _read_blob would verify via a full roaring
    decode the caller then has to repeat.)"""
    import struct

    from pilosa_tpu.roaring.format import load

    data = _read_raw(src, digest)
    try:
        block, _ = load(data)
        ids = block.to_ids()
    except (ValueError, struct.error) as e:
        raise ValueError(
            f"backup blob {digest} fails content verification") from e
    if _ids_digest(ids) != digest:
        raise ValueError(f"backup blob {digest} fails content verification")
    return ids


def _digest_matches(digest: str, data: bytes) -> bool:
    import struct

    # fragment-block blobs are addressed by their IDS digest, sidecar
    # files by their raw content digest — accept either
    if _digest(data) == digest:
        return True
    try:
        from pilosa_tpu.roaring.format import load

        bitmap, _ = load(data)
        return _ids_digest(bitmap.to_ids()) == digest
    except (ValueError, struct.error):
        return False


def list_generations(dest: str) -> list[int]:
    if not os.path.isdir(dest):
        return []
    out = []
    for entry in os.listdir(dest):
        if entry.isdigit() and os.path.exists(
            os.path.join(dest, entry, MANIFEST_NAME)
        ):
            out.append(int(entry))
    return sorted(out)


def load_manifest(dest: str, generation: int) -> dict:
    with open(os.path.join(dest, f"{generation:06d}", MANIFEST_NAME)) as f:
        return json.load(f)


def _finish_generation(dest: str, manifest: dict) -> dict:
    gen = manifest["generation"]
    gen_dir = os.path.join(dest, f"{gen:06d}")
    os.makedirs(gen_dir, exist_ok=True)
    _atomic_write(
        os.path.join(gen_dir, MANIFEST_NAME),
        json.dumps(manifest, indent=1, sort_keys=True).encode(),
    )
    _atomic_write(os.path.join(dest, "LATEST"), f"{gen:06d}".encode())
    return manifest


# ------------------------------------------------------------------ backup


def _capture_feed(wal, since: int, high: int) -> tuple[bytes, bool]:
    """Drain the WAL tail feed for ``(since, high]`` into one frame
    stream (cdc/feed.py layout — the bytes a live consumer would have
    received). Returns (frames, complete): ``complete`` is False when
    the WAL already reclaimed part of the range (retention budget) —
    the generation still restores, but ``--as-of`` into the gap is
    refused with a readable error instead of a silent hole."""
    from pilosa_tpu.cdc.feed import encode_events
    from pilosa_tpu.storage.wal import TailGone

    frames = bytearray()
    pos = since
    try:
        while pos < high:
            events, next_seq, _durable = wal.read_tail(
                pos, max_bytes=4 << 20)
            frames += encode_events(events)
            if next_seq <= pos:
                break
            pos = next_seq
    except TailGone:
        return bytes(frames), False
    return bytes(frames), pos >= high


def backup_holder(holder, dest: str) -> dict:
    """One incremental backup generation of an OPEN holder. Returns the
    manifest (with ``newBlobs``/``reusedBlobs`` counts for reporting).

    With a grouped WAL the manifest is also a point-in-time anchor for
    ``restore --as-of`` (docs/OPERATIONS.md Replication & CDC): it
    stamps ``walSeqLow`` (every op at or below it is IN the walked
    content) and ``walSeq`` (no op above it is), and stores the feed
    frames for ``(previous generation's walSeqLow, walSeq]`` as a blob
    — the replay fuel that turns the nearest generation into any seq
    between generations. A ``backup:`` cursor pins the NEXT window's
    segments against GC, inside the cdc-max-retention-bytes budget."""
    dest = os.path.expanduser(dest)
    blob_dir = os.path.join(dest, "blobs")
    os.makedirs(blob_dir, exist_ok=True)
    gens = list_generations(dest)
    gen = (gens[-1] + 1) if gens else 1
    wal = getattr(holder, "wal", None)
    if wal is not None and not wal.grouped:
        wal = None
    wal_low = None
    if wal is not None:
        # everything appended so far must be fsynced (and so group-
        # indexed for read_tail) before it can anchor the low mark
        wal.barrier()
        wal_low = wal.durable_seq()

    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize

    fragments: dict[str, list] = {}
    new_blobs = reused = 0
    # list() snapshots: the holder is live — concurrent schema/fragment
    # creation must not perturb the traversal (per-fragment consistency
    # is the frag.lock below; container membership is point-in-time)
    for iname, idx in sorted(list(holder.indexes.items())):
        for fname, fld in sorted(list(idx.fields.items())):
            for vname, view in sorted(list(fld.views.items())):
                for shard in sorted(list(view.fragments)):
                    frag = view.fragment(shard)
                    if frag is None:
                        continue
                    key = f"{iname}/{fname}/{vname}/{shard}"
                    # one consistent view per fragment: a write racing
                    # between blocks() and block_ids() would otherwise
                    # store a NEW payload under the OLD digest,
                    # poisoning the content-addressed blob for every
                    # generation that references it
                    with frag.lock:
                        blocks = list(frag.blocks())
                        payloads = [
                            (digest, serialize(RoaringBitmap.from_ids(
                                frag.block_ids(block))))
                            for block, digest in blocks
                            if not os.path.exists(
                                os.path.join(blob_dir, digest))
                        ]
                    fragments[key] = [[b, d] for b, d in blocks]
                    reused += len(blocks) - len(payloads)
                    for digest, payload in payloads:
                        if _write_blob(blob_dir, digest, payload):
                            new_blobs += 1
                        else:
                            reused += 1

    files: dict[str, str] = {}
    root = holder.data_dir
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != ".wal"]
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            if name.endswith(_SKIP_SUFFIXES):
                continue
            parts = rel.split(os.sep)
            if len(parts) >= 2 and parts[-2] == "fragments":
                continue  # fragment data rides the block blobs
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            digest = _digest(data)
            if _write_blob(blob_dir, digest, data):
                new_blobs += 1
            else:
                reused += 1
            files[rel.replace(os.sep, "/")] = digest

    wal_feed = None
    wal_high = None
    if wal is not None:
        # ops landing DURING the walk may or may not be in the walked
        # content (the bitmap union happens before the seq is assigned)
        # — so the anchor is a band: content holds everything <= low
        # and nothing > high. Quiesced backups (the restore --as-of
        # contract) collapse the band to a point.
        wal.barrier()
        wal_high = wal.durable_seq()
        since = wal_low
        if gens:
            prev = load_manifest(dest, gens[-1])
            if prev.get("walSeqLow") is not None:
                since = prev["walSeqLow"]
        frames, complete = _capture_feed(wal, since, wal_high)
        feed_digest = _digest(frames)
        # the feed blob is as-of replay METADATA, accounted on the
        # walFeed record — newBlobs/reusedBlobs stay checksum-block
        # content counts ("only the changed block shipped" semantics)
        feed_written = _write_blob(blob_dir, feed_digest, frames)
        wal_feed = {"blob": feed_digest, "sinceSeq": since,
                    "walSeq": wal_high, "complete": complete,
                    "newBlob": bool(feed_written)}
        # pin the NEXT generation's replay window ((this low, next
        # high]) against WAL GC — within cdc-max-retention-bytes, so a
        # stalled backup destination can't fill the disk
        wal.register_cursor(f"backup:{_digest(dest.encode())[:8]}",
                            wal_low)

    manifest = {
        "generation": gen,
        "createdAt": dt.datetime.now(dt.timezone.utc).isoformat(),
        "basedOn": gens[-1] if gens else None,
        "scope": "full",
        "walSeqLow": wal_low,
        "walSeq": wal_high,
        "walFeed": wal_feed,
        "indexes": {
            iname: {
                "options": {"keys": idx.keys,
                            "trackExistence": idx.track_existence},
                "fields": {
                    fname: fld.options.to_dict()
                    for fname, fld in sorted(list(idx.fields.items()))
                },
            }
            for iname, idx in sorted(list(holder.indexes.items()))
        },
        "fragments": fragments,
        "files": files,
        "newBlobs": new_blobs,
        "reusedBlobs": reused,
    }
    return _finish_generation(dest, manifest)


def backup_from_host(host: str, dest: str, client=None) -> dict:
    """Incremental backup of a LIVE cluster over the sync wire: walks
    every node from ``/status``, pulls one batched sync manifest per
    (node, index), and fetches only the blocks whose blobs are missing
    — as multi-block deltas on the compressed, pacer-shaped PR-4
    transfer path (wire the caller's RepairPacer onto ``client``).

    Fragment data only (``scope: "fragments"``): the key-translation
    log and attribute stores have no snapshot-consistent remote fetch,
    so keyed indexes and attrs need an offline ``-d`` backup (the
    restore side rebuilds ``.meta`` files from the schema captured
    here)."""
    from pilosa_tpu.parallel.client import InternalClient

    dest = os.path.expanduser(dest)
    blob_dir = os.path.join(dest, "blobs")
    os.makedirs(blob_dir, exist_ok=True)
    gens = list_generations(dest)
    gen = (gens[-1] + 1) if gens else 1
    client = client or InternalClient()

    from pilosa_tpu.roaring.format import serialize

    host = host.rstrip("/")
    status = client.status(host)
    uris = [n.get("uri", host) for n in status.get("nodes", [])
            if n.get("state") != "DOWN"] or [host]
    schema = client.schema(host)
    indexes = {
        i["name"]: {
            "options": i.get("options", {}),
            "fields": {f["name"]: f.get("options", {})
                       for f in i.get("fields", [])},
        }
        for i in schema.get("indexes", [])
    }

    fragments: dict[str, list] = {}
    new_blobs = reused = races = 0
    for uri in uris:
        for iname in sorted(indexes):
            for field, vname, shard, blocks in client.sync_manifest(
                uri, iname
            ):
                key = f"{iname}/{field}/{vname}/{shard}"
                if key in fragments:
                    continue  # first replica seen wins
                entry = [[b, d] for b, d in blocks]
                missing = [
                    b for b, d in blocks
                    if not os.path.exists(os.path.join(blob_dir, d))
                ]
                if missing:
                    bitmaps = client.sync_blocks(
                        uri, iname, [(field, vname, shard, missing)]
                    )
                    want = {b: d for b, d in blocks}
                    for block, bitmap in zip(missing, bitmaps):
                        ids = bitmap.to_ids()
                        digest = _ids_digest(ids)
                        if digest != want[block]:
                            # a write raced the manifest fetch: keep the
                            # fetched content under ITS digest — each
                            # block stays self-consistent
                            races += 1
                            entry = [
                                [b, digest if b == block else d]
                                for b, d in entry
                            ]
                        if _write_blob(blob_dir, digest,
                                       serialize(bitmap)):
                            new_blobs += 1
                        else:
                            reused += 1
                reused += len(blocks) - len(missing)
                fragments[key] = entry

    manifest = {
        "generation": gen,
        "createdAt": dt.datetime.now(dt.timezone.utc).isoformat(),
        "basedOn": gens[-1] if gens else None,
        "scope": "fragments",
        "source": host,
        "indexes": indexes,
        "fragments": fragments,
        "files": {},
        "newBlobs": new_blobs,
        "reusedBlobs": reused,
        "racedBlocks": races,
    }
    return _finish_generation(dest, manifest)


# ----------------------------------------------------------------- restore


def _select_as_of(src: str, gens: list[int], as_of: int):
    """Pick the restore base and replay feed for ``--as-of <seq>``:
    base = the newest generation whose ``walSeq`` <= as_of (its content
    holds nothing past as_of), feed = the frame blob covering
    ``(base.walSeqLow, as_of]`` — the base's own when as_of lands
    exactly on its high mark, else the NEXT generation's (whose
    ``sinceSeq`` is the base's low mark by construction)."""
    manifests = [load_manifest(src, g) for g in gens]
    anchored = [m for m in manifests if m.get("walSeq") is not None]
    if not anchored:
        raise ValueError(
            "--as-of needs backups taken from a group-durability WAL "
            "(no generation here carries a walSeq anchor)")
    candidates = [m for m in anchored if m["walSeq"] <= as_of]
    if not candidates:
        raise ValueError(
            f"as-of seq {as_of} predates the earliest anchored "
            f"generation (walSeq {anchored[0]['walSeq']})")
    base = candidates[-1]
    if as_of == base["walSeq"] or as_of <= base.get(
            "walSeqLow", base["walSeq"]):
        feed = base.get("walFeed")
    else:
        later = [m for m in anchored
                 if m["generation"] > base["generation"]]
        if not later:
            raise ValueError(
                f"as-of seq {as_of} is past the latest generation's "
                f"walSeq {base['walSeq']}; take a newer backup first")
        feed = later[0].get("walFeed")
    low = base.get("walSeqLow", base["walSeq"])
    if low < as_of:
        if feed is None:
            raise ValueError(
                "backup generation carries no WAL feed blob; cannot "
                f"replay to seq {as_of}")
        if not feed.get("complete", False):
            raise ValueError(
                "the WAL feed covering this range is incomplete (the "
                "source WAL reclaimed part of it before the backup "
                f"ran); cannot replay to seq {as_of} — restore a "
                "generation boundary instead")
        if feed["sinceSeq"] > low:
            raise ValueError(
                f"WAL feed starts at seq {feed['sinceSeq']}, after the "
                f"base generation's low mark {low}; replay gap")
    return base, feed, low


def restore_holder(src: str, data_dir: str,
                   generation: int | None = None,
                   as_of: int | None = None) -> dict:
    """Rebuild a data dir from one backup generation. The target must
    be empty or absent; every fragment is reassembled from its block
    blobs, digest-verified against the manifest, and fsynced. Returns
    the manifest restored.

    ``as_of`` restores to an exact WAL sequence number instead of a
    generation boundary: the nearest anchored generation at or before
    the seq is restored, then the stored change feed is replayed
    through ``as_of`` by appending the raw WAL op records to the
    restored fragment files (op records ARE the fragment op-log
    format; the reopened fragments replay them onto the snapshot).
    Deletions (tombstones) inside the replay window cannot be
    replayed — restore a generation after the deletion instead."""
    src = os.path.expanduser(src)
    data_dir = os.path.expanduser(data_dir)
    gens = list_generations(src)
    if not gens:
        raise ValueError(f"no backup generations under {src}")
    feed = replay_low = None
    if as_of is not None:
        if generation is not None:
            raise ValueError("pass either generation or as_of, not both")
        base, feed, replay_low = _select_as_of(src, gens, as_of)
        generation = base["generation"]
    if generation is None:
        generation = gens[-1]
    if generation not in gens:
        raise ValueError(f"generation {generation} not in {gens}")
    if os.path.isdir(data_dir) and os.listdir(data_dir):
        raise ValueError(f"restore target {data_dir} is not empty")
    manifest = load_manifest(src, generation)
    if manifest.get("scope") == "fragments":
        # live --host backups carry fragment data only (no translate
        # log — backup_from_host docstring): restoring a keyed index
        # from one would silently lose every key->ID mapping and
        # re-attribute all restored bits to whatever keys arrive next
        keyed = sorted(
            iname for iname, ientry in manifest.get("indexes", {}).items()
            if ientry.get("options", {}).get("keys")
            or any(fopts.get("keys")
                   for fopts in ientry.get("fields", {}).values())
        )
        if keyed:
            raise ValueError(
                f"refusing to restore keyed index(es) {', '.join(keyed)} "
                "from a fragments-scope (live --host) backup: it has no "
                "key-translation log, so every key->ID mapping would be "
                "lost — take an offline backup with -d instead"
            )
    os.makedirs(data_dir, exist_ok=True)

    for rel, digest in sorted(manifest.get("files", {}).items()):
        path = os.path.join(data_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(path) or data_dir, exist_ok=True)
        _atomic_write(path, _read_blob(src, digest))

    # fragments-scope manifests (live HTTP backups) carry no sidecar
    # files: synthesize the .meta files restore-open needs from the
    # schema captured at backup time
    for iname, ientry in sorted(manifest.get("indexes", {}).items()):
        ipath = os.path.join(data_dir, iname)
        os.makedirs(ipath, exist_ok=True)
        imeta = os.path.join(ipath, ".meta")
        if not os.path.exists(imeta):
            opts = ientry.get("options", {})
            _atomic_write(imeta, json.dumps({
                "keys": opts.get("keys", False),
                "trackExistence": opts.get("trackExistence", True),
            }).encode())
        for fname, fopts in sorted(ientry.get("fields", {}).items()):
            fpath = os.path.join(ipath, fname)
            os.makedirs(fpath, exist_ok=True)
            fmeta = os.path.join(fpath, ".meta")
            if not os.path.exists(fmeta):
                _atomic_write(fmeta, json.dumps(fopts).encode())

    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import load, serialize
    from pilosa_tpu.storage import integrity

    restored = 0
    for key, blocks in sorted(manifest.get("fragments", {}).items()):
        iname, fname, vname, shard = key.split("/")
        fmeta = os.path.join(data_dir, iname, fname, ".meta")
        if fname == "_exists" and not os.path.exists(fmeta):
            # the schema omits internal fields; restore its meta so the
            # reopened index doesn't give the existence field a ranked
            # TopN cache it never has
            os.makedirs(os.path.dirname(fmeta), exist_ok=True)
            _atomic_write(fmeta, json.dumps(
                {"type": "set", "cacheType": "none"}).encode())
        frag_dir = os.path.join(data_dir, iname, fname, "views", vname,
                                "fragments")
        os.makedirs(frag_dir, exist_ok=True)
        bitmap = RoaringBitmap()
        for block, digest in blocks:
            try:
                ids = _read_block_ids(src, digest)
            except ValueError as e:
                raise ValueError(
                    f"backup block {digest} of {key} fails digest "
                    "verification; refusing to restore corrupt data"
                ) from e
            bitmap.add_ids(ids)
        frag_path = os.path.join(frag_dir, shard)
        _atomic_write(frag_path, serialize(bitmap))
        # Read-back verification against the LIVE checksum index: the
        # blob digests above prove the SOURCE was intact; re-reading
        # the bytes the target disk actually holds catches a
        # corrupt-at-rest restore target at restore time instead of at
        # first query. (The read rides the disk fault plane's seam, so
        # the oracle can drive this path with injected bit flips.)
        live = integrity.block_digests(
            load(integrity.read_file(frag_path))[0].to_ids()
        )
        if live != [(int(b), d) for b, d in blocks]:
            raise ValueError(
                f"restored fragment {key} at {frag_path} fails digest "
                "verification against the live checksum index; the "
                "restore target is corrupting data at rest"
            )
        # checksum sidecar: the restored dir is verify-on-load- and
        # scrub-ready from its first open
        integrity.save_checksums(frag_path + integrity.CHECKSUM_SUFFIX,
                                 live)
        restored += 1
    manifest["restoredFragments"] = restored

    if as_of is not None and replay_low is not None and replay_low < as_of:
        manifest.update(_replay_feed(src, data_dir, manifest, feed,
                                     replay_low, as_of))
        manifest["asOfSeq"] = as_of
    elif as_of is not None:
        manifest.update({"replayedOps": 0, "skippedReplayOps": 0,
                         "asOfSeq": as_of})
    return manifest


def _replay_feed(src: str, data_dir: str, manifest: dict, feed: dict,
                 low: int, as_of: int) -> dict:
    """Append the stored change-feed ops in ``(low, as_of]`` to the
    restored fragment files, in commit order. Op bodies are the
    fragment op-log record format, so the appended bytes replay onto
    the snapshot at first open — no bitmap decode round-trip. The
    integrity sidecars written above cover the snapshot prefix only,
    so appending after them is safe (same layout a crashed live node
    reopens from)."""
    from pilosa_tpu.cdc.feed import iter_frames
    from pilosa_tpu.roaring import RoaringBitmap
    from pilosa_tpu.roaring.format import serialize
    from pilosa_tpu.storage.wal import REC_TOMBSTONE

    frames = _read_blob(src, feed["blob"])
    known = manifest.get("indexes", {})
    appends: dict[str, list[bytes]] = {}
    replayed = skipped = 0
    for seq, rtype, key, body in iter_frames(frames):
        if not (low < seq <= as_of):
            continue
        if rtype == REC_TOMBSTONE:
            raise ValueError(
                f"deletion of {key!r} at seq {seq} falls inside the "
                f"as-of replay window ({low}, {as_of}]; deletions "
                "cannot be replayed onto a restored snapshot — "
                "restore a generation taken after the deletion"
            )
        parts = key.split("/")
        if len(parts) != 4 or parts[0] not in known:
            # an index created after the base walk: its schema isn't
            # in this manifest, so the write has nowhere to land
            skipped += 1
            continue
        iname, fname, vname, shard = parts
        if fname != "_exists" and fname not in known[iname].get(
                "fields", {}):
            skipped += 1
            continue
        frag_path = os.path.join(data_dir, iname, fname, "views",
                                 vname, "fragments", shard)
        appends.setdefault(frag_path, []).append(body)
        replayed += 1

    empty = serialize(RoaringBitmap())
    for frag_path, bodies in sorted(appends.items()):
        frag_dir = os.path.dirname(frag_path)
        os.makedirs(frag_dir, exist_ok=True)
        fmeta = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(frag_dir))), ".meta")
        if not os.path.exists(fmeta):
            # replay created this (internal) field's first fragment
            _atomic_write(fmeta, json.dumps(
                {"type": "set", "cacheType": "none"}).encode())
        if not os.path.exists(frag_path):
            # first write to this fragment happened inside the replay
            # window: synthesize an empty snapshot for the ops to
            # replay onto
            _atomic_write(frag_path, empty)
        with open(frag_path, "ab") as f:
            for body in bodies:
                f.write(body)
            f.flush()
            os.fsync(f.fileno())
    return {"replayedOps": replayed, "skippedReplayOps": skipped}
