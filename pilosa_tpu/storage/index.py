"""Index: a named database of fields + existence tracking.

Reference: index.go (SURVEY.md §2 #7): owns fields, the ``keys`` option
(string column keys via the translate store), and ``trackExistence`` — an
internal ``_exists`` field recording which columns exist so ``Not``/``All``
have a universe to complement against.
"""

from __future__ import annotations

import json
import os
import shutil
import threading

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.storage.field import Field, FieldOptions, TYPE_SET
from pilosa_tpu.storage.view import VIEW_STANDARD

EXISTENCE_FIELD = "_exists"


class Index:
    def __init__(self, path: str, name: str, keys: bool = False,
                 track_existence: bool = True, wal=None,
                 verify_on_load: bool = False):
        self.path = path
        self.name = name
        self.wal = wal  # holder WAL, threaded down the storage tree
        self.verify_on_load = verify_on_load
        # Residency-cache scope: unique per holder data dir, so two
        # Holders in ONE process (in-process cluster tests, embedded
        # multi-server use) can never collide on device-cache keys or
        # write-routing tags for same-named indexes (a shared-cache hit
        # on another holder's leaf served stale replica data — found by
        # the seed-swept membership-churn property test).
        self.scope = path
        self.keys = keys
        self.track_existence = track_existence
        self.fields: dict[str, Field] = {}
        # serializes field creation (implicit creation via Store() can
        # race under the threaded server; see View._create_lock)
        self._create_lock = threading.Lock()
        self.column_attrs = None  # AttrStore, opened in open()
        # schema epoch: bumped on field create/delete so cached query
        # plans (executor._plan_cache) revalidate with one int compare
        self.plan_epoch = 0
        # available_shards memo, validated by total fragment count (the
        # shard set only ever grows, and only by creating a fragment)
        self._shards_memo: tuple[int, list[int]] | None = None

    # ------------------------------------------------------------- lifecycle

    def open(self) -> "Index":
        os.makedirs(self.path, exist_ok=True)
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                d = json.load(f)
            self.keys = d.get("keys", False)
            self.track_existence = d.get("trackExistence", True)
        else:
            self._save_meta()
        for entry in sorted(os.listdir(self.path)):
            p = os.path.join(self.path, entry)
            if entry.startswith(".trash-"):
                # a delete_field crashed between rename and rmtree
                shutil.rmtree(p, ignore_errors=True)
                continue
            if os.path.isdir(p) and not entry.startswith("."):
                self.fields[entry] = Field(
                    p, self.name, entry, scope=self.scope, wal=self.wal,
                    verify_on_load=self.verify_on_load,
                ).open()
        if self.track_existence and EXISTENCE_FIELD not in self.fields:
            self.create_field(EXISTENCE_FIELD, FieldOptions(type=TYPE_SET, cache_type="none"))
        from pilosa_tpu.storage.attrs import AttrStore

        self.column_attrs = AttrStore(os.path.join(self.path, ".colattrs.db")).open()
        return self

    def close(self, discard: bool = False) -> None:
        for f in list(self.fields.values()):
            f.close(discard=discard)
        if self.column_attrs is not None:
            self.column_attrs.close()

    def _save_meta(self) -> None:
        # fsynced: WAL recovery resolves replayed ops through this file
        # (and this directory entry) — a power cut that loses them would
        # make recover() silently drop the field's acked, fsynced ops
        from pilosa_tpu.storage.wal import fsync_dir
        from pilosa_tpu.testing import faults

        meta = os.path.join(self.path, ".meta")
        try:
            faults.disk_check("write", meta)
            with open(meta, "w") as f:
                json.dump({"keys": self.keys,
                           "trackExistence": self.track_existence}, f)
                f.flush()
                faults.disk_check("fsync", meta)
                os.fsync(f.fileno())
        except OSError as e:
            health = getattr(self.wal, "health", None) if self.wal else None
            if health is not None:
                health.trip(f".meta write of {meta}: {e}")
            raise
        fsync_dir(self.path)
        fsync_dir(os.path.dirname(self.path) or ".")

    # ---------------------------------------------------------------- fields

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._create_lock:
            if name in self.fields:
                raise ValueError(f"field {name!r} already exists")
            _validate_name(name, allow_internal=name == EXISTENCE_FIELD)
            field = Field(
                os.path.join(self.path, name), self.name, name, options,
                scope=self.scope, wal=self.wal,
                verify_on_load=self.verify_on_load,
            ).open()
            self.fields[name] = field
            self.plan_epoch += 1
            return field

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def delete_field(self, name: str) -> None:
        field = self.fields.pop(name, None)
        if field is None:
            raise KeyError(f"field {name!r} not found")
        # rename-then-tombstone (the delete_index pattern): the rename
        # removes the field from the tree in one step, so a crash at
        # any point leaves either the whole field or no field — never a
        # live field missing acked writes; the DURABLE tombstone then
        # keeps replay from resurrecting its ops into a same-name
        # re-creation. open() sweeps any .trash-* a crash leaves.
        from pilosa_tpu.storage.wal import fsync_dir

        trash = os.path.join(self.path, f".trash-{name}")
        shutil.rmtree(trash, ignore_errors=True)
        try:
            os.rename(field.path, trash)
        except OSError:
            trash = None  # already gone; nothing on disk to resurrect
        else:
            # the rename must reach the platter before the delete is
            # acked — a power cut would otherwise undo it and resurrect
            # every snapshot file (recover() only suppresses op replay)
            fsync_dir(self.path)
        if self.wal is not None:
            self.wal.tombstone(f"{self.name}/{name}/")
            self.wal.barrier()
        field.close(discard=True)
        if trash is not None:
            shutil.rmtree(trash, ignore_errors=True)
        self.plan_epoch += 1
        self._shards_memo = None  # deletes can shrink the shard set

    def public_fields(self) -> list[Field]:
        return [f for n, f in sorted(self.fields.items()) if not n.startswith("_")]

    # ------------------------------------------------------------- existence

    def mark_columns_exist(self, columns) -> None:
        """Set row 0 of the _exists field for every column. Bulk-imports
        per shard: bulk writes mark hundreds of thousands of columns, and
        a per-column set_bit loop (op-log append each) dominates the
        whole import at that scale."""
        if not self.track_existence:
            return
        import numpy as np

        from pilosa_tpu.shardwidth import shard_groups

        cols = np.asarray(columns, np.uint64)
        if cols.size == 0:
            return
        ex = self.fields[EXISTENCE_FIELD]
        view = ex.view(VIEW_STANDARD, create=True)
        order, bounds, shards_sorted = shard_groups(cols)
        cols = cols[order]
        zeros = np.zeros(cols.size, np.uint64)
        for i in range(bounds.size - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            frag = view.fragment(int(shards_sorted[lo]), create=True)
            frag.bulk_import(
                zeros[lo:hi], cols[lo:hi] & np.uint64(SHARD_WIDTH - 1)
            )

    def mark_columns_exist_shard(self, shard: int, positions) -> None:
        """One shard group's existence write, ``positions`` already
        in-shard. The bulk import path calls this from its per-group
        workers: the caller's shard_groups pass already sorted the
        batch, so re-deriving groups here (a second argsort over the
        whole batch — half of mark_columns_exist's cost) is skipped, and
        the existence write parallelizes with the data write instead of
        running as a serial tail."""
        if not self.track_existence:
            return
        import numpy as np

        positions = np.asarray(positions, np.uint64)
        if positions.size == 0:
            return
        ex = self.fields[EXISTENCE_FIELD]
        frag = ex.view(VIEW_STANDARD, create=True).fragment(
            int(shard), create=True
        )
        frag.bulk_import(np.zeros(positions.size, np.uint64), positions)

    def existence_fragment(self, shard: int):
        if not self.track_existence:
            return None
        view = self.fields[EXISTENCE_FIELD].view(VIEW_STANDARD)
        return view.fragment(shard) if view else None

    # ----------------------------------------------------------------- info

    def available_shards(self) -> list[int]:
        """Sorted union of every field's shard set, memoized: between
        field deletions (which drop the memo) the set only grows, and
        only by fragment creation, so a total-fragment count validates
        the memo in O(fields x views). The per-query set-union + sort
        otherwise shows up on the pipelined submit path."""
        n_frags = 0
        for f in list(self.fields.values()):
            for v in list(f.views.values()):
                n_frags += len(v.fragments)
        memo = self._shards_memo
        if memo is not None and memo[0] == n_frags:
            return memo[1]
        shards: set[int] = set()
        for f in list(self.fields.values()):
            shards.update(f.available_shards())
        out = sorted(shards)
        self._shards_memo = (n_frags, out)
        return out

    def schema(self) -> dict:
        return {
            "name": self.name,
            "options": {"keys": self.keys, "trackExistence": self.track_existence},
            "fields": [
                {"name": f.name, "options": f.options.to_dict()}
                for f in self.public_fields()
            ],
        }


def _validate_name(name: str, allow_internal: bool = False) -> None:
    ok_first = name[:1].isalpha() or (allow_internal and name[:1] == "_")
    if not name or len(name) > 230 or not ok_first or not all(
        c.isalnum() or c in "-_" for c in name
    ):
        raise ValueError(f"invalid name {name!r}")
