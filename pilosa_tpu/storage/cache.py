"""Per-fragment row cache: (rowID → count) feeding TopN candidates.

Reference: cache.go (SURVEY.md §2 #4) — three kinds: ``ranked`` (bounded,
sorted by count, default size 50k), ``lru``, ``none``. The cache is the
reason TopN is approximate when cold (SURVEY.md §3.4). Here counts come
from device popcounts at import/write time; the cache itself is pure host
bookkeeping and persists next to the fragment file.
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict

CACHE_TYPE_RANKED = "ranked"
CACHE_TYPE_LRU = "lru"
CACHE_TYPE_NONE = "none"

DEFAULT_CACHE_SIZE = 50_000

# A ranked cache recalculates its sorted top set lazily; this is the
# overfetch headroom before a re-sort is forced.
_RANK_SLACK = 1.1


class RankCache:
    """Bounded map rowID → count keeping the highest-count rows."""

    kind = CACHE_TYPE_RANKED

    def __init__(self, max_size: int = DEFAULT_CACHE_SIZE):
        self.max_size = max_size
        self._counts: dict[int, int] = {}

    def bulk_add(self, row: int, count: int) -> None:
        if count <= 0:
            self._counts.pop(row, None)
            return
        self._counts[row] = count

    add = bulk_add

    def get(self, row: int) -> int | None:
        return self._counts.get(row)

    def invalidate(self) -> None:
        pass  # counts are authoritative updates; nothing derived to drop

    def top(self):
        """All cached (row, count) pairs, highest count first (ties: lower
        row id first, matching the reference's deterministic ordering)."""
        self._trim()
        return sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))

    def ids(self):
        return list(self._counts)

    def __len__(self):
        return len(self._counts)

    def _trim(self) -> None:
        if len(self._counts) <= self.max_size * _RANK_SLACK:
            return
        keep = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        self._counts = dict(keep[: self.max_size])

    # --- persistence ---

    def save(self, path: str) -> None:
        self._trim()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"kind": self.kind, "counts": list(self._counts.items())}, f)
            # fsync BEFORE the rename: os.replace is atomic in the
            # namespace but not on the platter — a power cut mid-save
            # could otherwise publish a torn .cache under the final name
            # (silently "repaired" by recalculate_cache, masking the
            # corruption)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        from pilosa_tpu.storage.wal import fsync_dir

        fsync_dir(os.path.dirname(path) or ".")

    def load(self, path: str) -> None:
        try:
            with open(path) as f:
                data = json.load(f)
            self._counts = {int(r): int(c) for r, c in data.get("counts", [])}
        except (OSError, ValueError):
            self._counts = {}


class LRUCache(RankCache):
    """LRU variant: recency-bounded instead of count-ranked."""

    kind = CACHE_TYPE_LRU

    def __init__(self, max_size: int = DEFAULT_CACHE_SIZE):
        super().__init__(max_size)
        self._counts = OrderedDict()

    def bulk_add(self, row: int, count: int) -> None:
        if count <= 0:
            self._counts.pop(row, None)
            return
        self._counts[row] = count
        self._counts.move_to_end(row)
        while len(self._counts) > self.max_size:
            self._counts.popitem(last=False)

    add = bulk_add

    def _trim(self) -> None:
        pass


class NoneCache(RankCache):
    """Disabled cache (fields that never serve TopN)."""

    kind = CACHE_TYPE_NONE

    def bulk_add(self, row: int, count: int) -> None:
        pass

    add = bulk_add

    def top(self):
        return []

    def save(self, path: str) -> None:
        pass

    def load(self, path: str) -> None:
        pass


def new_row_cache(kind: str, size: int = DEFAULT_CACHE_SIZE):
    if kind == CACHE_TYPE_RANKED:
        return RankCache(size)
    if kind == CACHE_TYPE_LRU:
        return LRUCache(size)
    if kind == CACHE_TYPE_NONE:
        return NoneCache(size)
    raise ValueError(f"unknown cache type {kind!r}")
