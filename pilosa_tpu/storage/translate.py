"""Key translation: string keys ↔ sequential uint64 IDs.

Reference: translate.go (SURVEY.md §2 #9) — indexes translate column keys,
fields translate row keys; the store is an append-only log replayed on
open, and replicas tail the primary's log (the tailing endpoint is served
by the cluster layer at /internal/translate/data).

Implementation: one log file per holder; each record is
(namespace, key) — the assigned ID is implicit in per-namespace append
order, which makes the log trivially replayable and the replica protocol
"send me bytes from offset N".
"""

from __future__ import annotations

import os
import struct
import threading

_REC = struct.Struct("<HI")  # namespace-length, key-length


class TranslateStore:
    """Bidirectional key↔ID maps per namespace, backed by an append log.

    Namespaces: ``c/<index>`` for column keys, ``r/<index>/<field>`` for
    row keys (IDs in both spaces start at 0 and increment densely).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self._key_to_id: dict[str, dict[str, int]] = {}
        self._id_to_key: dict[str, list[str]] = {}
        self._file = None
        self._dirty = False  # appended-but-not-fsynced records pending

    # ------------------------------------------------------------- lifecycle

    def open(self) -> "TranslateStore":
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                buf = f.read()
            pos = 0
            while pos + _REC.size <= len(buf):
                ns_len, key_len = _REC.unpack_from(buf, pos)
                end = pos + _REC.size + ns_len + key_len
                if end > len(buf):
                    break  # torn tail
                ns = buf[pos + _REC.size : pos + _REC.size + ns_len].decode()
                key = buf[pos + _REC.size + ns_len : end].decode()
                self._assign(ns, key)
                pos = end
        self._file = open(self.path, "ab")
        return self

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    # ------------------------------------------------------------ translate

    def translate(self, namespace: str, keys, create: bool = False) -> list[int | None]:
        """Keys → IDs. With create=False unknown keys map to None."""
        out = []
        with self._lock:
            for key in keys:
                ids = self._key_to_id.setdefault(namespace, {})
                id_ = ids.get(key)
                if id_ is None and create:
                    id_ = self._assign(namespace, key)
                    self._append(namespace, key)
                out.append(id_)
        return out

    def translate_one(self, namespace: str, key: str, create: bool = False) -> int | None:
        return self.translate(namespace, [key], create=create)[0]

    def keys_of(self, namespace: str, ids) -> list[str | None]:
        """IDs → keys (None for never-assigned IDs)."""
        with self._lock:
            table = self._id_to_key.get(namespace, [])
            return [
                table[i] if 0 <= int(i) < len(table) else None for i in ids
            ]

    # --------------------------------------------------------- replication

    def log_size(self) -> int:
        with self._lock:
            if self._file:
                self._file.flush()
            return os.path.getsize(self.path) if os.path.exists(self.path) else 0

    def read_log(self, offset: int) -> bytes:
        """Raw log bytes from offset (primary side of replica tailing)."""
        with self._lock:
            if self._file:
                self._file.flush()
            with open(self.path, "rb") as f:
                f.seek(offset)
                return f.read()

    def apply_log(self, data: bytes) -> int:
        """Replica side: append+replay bytes received from the primary."""
        applied = 0
        pos = 0
        with self._lock:
            while pos + _REC.size <= len(data):
                ns_len, key_len = _REC.unpack_from(data, pos)
                end = pos + _REC.size + ns_len + key_len
                if end > len(data):
                    break
                ns = data[pos + _REC.size : pos + _REC.size + ns_len].decode()
                key = data[pos + _REC.size + ns_len : end].decode()
                if self._key_to_id.get(ns, {}).get(key) is None:
                    self._assign(ns, key)
                    self._append(ns, key)
                applied += 1
                pos = end
        return applied

    # -------------------------------------------------------------- helpers

    def _assign(self, namespace: str, key: str) -> int:
        ids = self._key_to_id.setdefault(namespace, {})
        if key in ids:
            return ids[key]
        table = self._id_to_key.setdefault(namespace, [])
        id_ = len(table)
        ids[key] = id_
        table.append(key)
        return id_

    def _append(self, namespace: str, key: str) -> None:
        if self._file is None:
            return
        ns_b, key_b = namespace.encode(), key.encode()
        self._file.write(_REC.pack(len(ns_b), len(key_b)) + ns_b + key_b)
        self._file.flush()
        self._dirty = True

    def sync(self) -> None:
        """Fsync appended key records. The write ACK gate calls this in
        the fsyncing durability modes: an acked keyed write whose bit
        survives a crash but whose key→ID mapping does not would come
        back re-attributed to a DIFFERENT later key (IDs are implicit
        in append order). No-op when nothing was appended, so unkeyed
        writes pay nothing."""
        with self._lock:
            if not self._dirty or self._file is None:
                return
            from pilosa_tpu.storage.wal import wal_fsync

            self._file.flush()
            wal_fsync(self._file.fileno())
            self._dirty = False


def column_namespace(index: str) -> str:
    return f"c/{index}"


def row_namespace(index: str, field: str) -> str:
    return f"r/{index}/{field}"
