"""Heat-driven HBM residency tiering: the actuator on PR 8's sensor.

PR 8 built decayed per-(index, field, shard) heat with an exact
HBM-residency overlay (``/debug/heatmap``) and nothing acted on it —
ROADMAP open item 3's second leg. This worker closes the loop:

- **Demote**: device-resident fragment entries whose heat fell below
  ``demote_heat`` move to the DeviceRowCache's compressed HOST tier
  (``demote_fragment_to_host``) — zero HBM, one paced upload away from
  dense residency. Roaring-density data compacts to its nonzero 4 KiB
  blocks, so the host tier holds 10-100x more fragments per byte than
  HBM holds dense rows (Chambi et al. 1402.6407).
- **Promote**: host-tier entries whose fragment heat climbed past
  ``promote_heat`` upload back to dense residency — shaped by the
  node's RepairPacer so a promotion storm (a tenant going viral) never
  starves serving of host↔device bandwidth, exactly like repair
  transfers. Query-path host hits promote inline too (the access IS
  the heat); the pass catches entries the queries did not touch
  directly — e.g. the rest of a fragment whose one hot row was
  promoted by a lookup, or operand-memo-served leaves.
- **Hysteresis**: ``promote_heat > demote_heat`` opens a dead band, and
  a fragment promoted by the pass is immune from demotion for
  ``min_dwell_s`` — borderline shards park in whichever tier they are
  in instead of thrashing host↔device every pass.

Safety: every move happens under the DeviceRowCache lock, and a reader
between tiers simply re-decodes from the roaring file (the miss path) —
old-resident or new-resident, never absent, the same swap discipline as
scrub read-repair. Writes invalidate host copies like compressed ones
(decompress+patch costs more than the re-decode they were demoted to
avoid).
"""

from __future__ import annotations

import threading
import time

DEFAULT_PROMOTE_HEAT = 4.0
DEFAULT_DEMOTE_HEAT = 1.0

# Bound on remembered decisions / dwell stamps: observability rings,
# not unbounded history (shard churn across many indexes).
MAX_TRACKED = 65536


class ResidencyTierer:
    """Promotion/demotion worker over (HeatMap, DeviceRowCache)."""

    def __init__(self, cache=None, heat=None, interval_s: float = 0.0,
                 promote_heat: float = DEFAULT_PROMOTE_HEAT,
                 demote_heat: float = DEFAULT_DEMOTE_HEAT,
                 min_dwell_s: float | None = None,
                 pacer=None, logger=None):
        if cache is None:
            from pilosa_tpu.storage.residency import global_row_cache

            cache = global_row_cache()
        if heat is None:
            from pilosa_tpu.storage.heat import global_heat

            heat = global_heat()
        self.cache = cache
        self.heat = heat
        self.interval_s = float(interval_s)
        self.promote_heat = float(promote_heat)
        self.demote_heat = float(demote_heat)
        # dwell immunity defaults to two intervals (one pass of noise
        # cannot undo the last pass's promotion)
        self.min_dwell_s = (float(min_dwell_s) if min_dwell_s is not None
                            else max(2 * self.interval_s, 1.0))
        self.pacer = pacer
        self.logger = logger
        self._lock = threading.Lock()
        self._promoted_at: dict[tuple, float] = {}
        self._decisions: dict[tuple, str] = {}
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        self.passes = 0
        self.promotions = 0
        self.demotions = 0
        self.promoted_bytes = 0
        self.demoted_bytes = 0
        self.paced_sleep_s = 0.0
        self.last_pass_s = 0.0

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ResidencyTierer":
        if self.interval_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="residency-tierer"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._closed.wait(self.interval_s):
            try:
                self.run_pass()
            except Exception as e:  # noqa: BLE001 — ticker must not die
                if self.logger is not None:
                    self.logger.warning("residency tiering pass failed: %s",
                                        e)

    def close(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # ---------------------------------------------------------------- pass

    def run_pass(self) -> dict:
        """One promote/demote sweep. Reads the heat snapshot and the
        cache's tier overlay, then acts per (scope, index, field,
        shard): device-resident + cold → host; host-resident + hot →
        dense (paced). Returns the pass record (tests, /internal)."""
        t0 = time.monotonic()
        score_by: dict[tuple, float] = {}
        field_score: dict[tuple, float] = {}
        for r in self.heat.snapshot(residency_overlay=False)["shards"]:
            fkey = (r.get("scope", ""), r["index"], r["field"], r["shard"])
            score = r["access"] + r["writes"]
            score_by[fkey] = score
            # stacked leaves span a whole shard block: a field is as hot
            # as its hottest shard (demoting a stack strands EVERY shard
            # it covers, so one hot shard pins the leaf)
            skey = fkey[:3]
            if score > field_score.get(skey, 0.0):
                field_score[skey] = score
        per_frag, per_stack = self.cache.tier_overlay()
        promoted = demoted = 0
        promoted_bytes = demoted_bytes = 0
        paced = 0.0
        decisions: dict[tuple, str] = {}
        now = time.monotonic()

        def promote(keys_bytes, stamp_key):
            nonlocal promoted, promoted_bytes, paced
            for key, nbytes in keys_bytes:
                if self.pacer is not None:
                    # pace OUTSIDE the cache lock: a bandwidth-starved
                    # promotion sleeps here, serving lookups proceed
                    # (and may promote the entry themselves first —
                    # promote_key then no-ops)
                    paced += self.pacer.consume(nbytes)
                up = self.cache.promote_key(key)
                if up:
                    promoted += 1
                    promoted_bytes += up
            with self._lock:
                self._promoted_at[stamp_key] = now

        def dwell_held(stamp_key) -> bool:
            with self._lock:
                return (now - self._promoted_at.get(stamp_key, -1e9)
                        < self.min_dwell_s)

        for fkey, tiers in per_frag.items():
            score = score_by.get(fkey, 0.0)
            on_device = tiers["dense"] + tiers["compressed"] > 0
            if tiers["host"] > 0 and score >= self.promote_heat:
                promote(self.cache.host_keys_of(*fkey), fkey)
                decisions[fkey] = "promoted"
            elif on_device and score < self.demote_heat:
                if dwell_held(fkey):
                    decisions[fkey] = "hold"  # hysteresis dwell
                    continue
                n, freed = self.cache.demote_fragment_to_host(*fkey)
                if n:
                    demoted += n
                    demoted_bytes += freed
                    decisions[fkey] = "demoted"
                else:
                    decisions[fkey] = "resident"
            elif on_device:
                decisions[fkey] = "resident"
            else:
                decisions[fkey] = "host"
        for skey, tiers in per_stack.items():
            score = field_score.get(skey, 0.0)
            on_device = tiers["dense"] + tiers["compressed"] > 0
            if tiers["host"] > 0 and score >= self.promote_heat:
                promote(self.cache.host_stack_keys_of(*skey), skey)
                decisions[skey] = "promoted"
            elif on_device and score < self.demote_heat:
                if dwell_held(skey):
                    decisions[skey] = "hold"
                    continue
                n, freed = self.cache.demote_field_stacks_to_host(*skey)
                if n:
                    demoted += n
                    demoted_bytes += freed
                    decisions[skey] = "demoted"
                else:
                    decisions[skey] = "resident"
            elif on_device:
                decisions[skey] = "resident"
            else:
                decisions[skey] = "host"
        with self._lock:
            self.passes += 1
            self.promotions += promoted
            self.demotions += demoted
            self.promoted_bytes += promoted_bytes
            self.demoted_bytes += demoted_bytes
            self.paced_sleep_s += paced
            self.last_pass_s = time.monotonic() - t0
            self._decisions = decisions
            if len(self._promoted_at) > MAX_TRACKED:
                # drop the stalest dwell stamps (their immunity expired
                # long ago anyway)
                for k in sorted(self._promoted_at,
                                key=self._promoted_at.get)[
                        : len(self._promoted_at) - MAX_TRACKED // 2]:
                    del self._promoted_at[k]
        return {
            "promoted": promoted,
            "demoted": demoted,
            "promotedBytes": promoted_bytes,
            "demotedBytes": demoted_bytes,
            "pacedSleepS": round(paced, 6),
            "seconds": round(self.last_pass_s, 6),
            "fragmentsSeen": len(per_frag),
            "stackedFieldsSeen": len(per_stack),
        }

    # --------------------------------------------------------------- views

    def last_decisions(self) -> dict:
        """The latest pass's per-fragment verdicts, for the
        ``/debug/heatmap?tier=true`` decision column."""
        with self._lock:
            return dict(self._decisions)

    def to_json(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "intervalS": self.interval_s,
                "promoteHeat": self.promote_heat,
                "demoteHeat": self.demote_heat,
                "minDwellS": self.min_dwell_s,
                "passes": self.passes,
                "promotions": self.promotions,
                "demotions": self.demotions,
            }

    def metrics(self) -> dict:
        """residency_tier_* series (docs/OBSERVABILITY.md) — the pass
        counters here; the per-tier byte gauges ride the residency
        block (the cache owns the tiers)."""
        with self._lock:
            return {
                "residency_tier_passes_total": self.passes,
                "residency_tier_pass_promotions_total": self.promotions,
                "residency_tier_pass_demotions_total": self.demotions,
                "residency_tier_promoted_bytes_total": self.promoted_bytes,
                "residency_tier_demoted_bytes_total": self.demoted_bytes,
                "residency_tier_paced_sleep_seconds_total":
                    round(self.paced_sleep_s, 6),
                "residency_tier_last_pass_seconds":
                    round(self.last_pass_s, 6),
            }
