"""Fragment: one (index, field, view, shard) slice of the bitmap matrix.

Reference: fragment.go (SURVEY.md §2 #3, §3.2–3.3) — the hot storage unit.
Row ``r`` of the matrix occupies bit positions [r·2^20, (r+1)·2^20) of the
fragment bitmap. Durability model: a roaring snapshot file plus an
append-only op log, compacted once the op count crosses a threshold;
crash recovery = snapshot + replay (torn tails dropped). WHERE the op
log lives depends on the holder's durability mode (storage/wal.py):
``group`` routes records through the per-holder group-commit WAL (one
fsync per wave of writers, fragment files hold snapshots only);
``per-op``/``flush-only`` append to this fragment's own file as the
reference does.

TPU divergence (SURVEY.md §7.1): reads are served from dense bit-packed
rows decoded on demand and cached in device HBM (residency.DeviceRowCache),
so query kernels see uniform uint32[32768] vectors instead of container
trees. The roaring form never reaches the device.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from pilosa_tpu.roaring import RoaringBitmap, OP_ADD, OP_REMOVE
from pilosa_tpu.roaring import kernels
from pilosa_tpu.roaring.format import (
    deserialize,
    encode_op,
    load_any,
    replay_ops,
    serialize,
)
from pilosa_tpu.shardwidth import (
    SHARD_WIDTH,
    SHARD_WIDTH_EXP,
    keep_last_unique,
)
from pilosa_tpu.serving import rescache
from pilosa_tpu.storage.cache import CACHE_TYPE_RANKED, DEFAULT_CACHE_SIZE, new_row_cache
from pilosa_tpu.storage import residency
from pilosa_tpu.storage.heat import global_heat
from pilosa_tpu.storage.integrity import (
    CHECKSUM_SUFFIX,
    CorruptFragmentError,
    DECODE_ERRORS,
    block_digests,
    load_verified,
    read_file,
    save_checksums,
)
from pilosa_tpu.storage.wal import MODE_PER_OP, fsync_dir, wal_fsync
from pilosa_tpu.testing import faults as _faults
from pilosa_tpu.utils.cost import current_cost

# Snapshot (compact) once this many op records have accumulated
# (reference fragment.go opN threshold; exact upstream value unverifiable —
# SURVEY.md Appendix B).
DEFAULT_SNAPSHOT_OP_THRESHOLD = 2048

# Anti-entropy checksum granularity: rows per block (reference
# fragment.go Blocks(), 100 rows per block — SURVEY.md §2 #3).
BLOCK_ROWS = 100


def _group_by_row(rows: np.ndarray, positions: np.ndarray):
    """Yield ``(row, positions_in_row)`` ascending by row, preserving
    each row's original position order — one stable sort instead of a
    per-row mask scan."""
    if rows.size == 0:
        return
    order = np.argsort(rows, kind="stable")
    sorted_rows = rows[order]
    sorted_pos = positions[order]
    uniq, starts = np.unique(sorted_rows, return_index=True)
    bounds = np.append(starts, sorted_rows.size)
    for i, r in enumerate(uniq.tolist()):
        yield int(r), sorted_pos[bounds[i]:bounds[i + 1]]


class Fragment:
    def __init__(
        self,
        path: str,
        index: str,
        field: str,
        view: str,
        shard: int,
        cache_type: str = CACHE_TYPE_RANKED,
        cache_size: int = DEFAULT_CACHE_SIZE,
        snapshot_threshold: int = DEFAULT_SNAPSHOT_OP_THRESHOLD,
        scope: str = "",
        wal=None,
        verify_on_load: bool = False,
    ):
        self.path = path
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.scope = scope
        # Holder-level write-ahead log (storage/wal.py). None (direct
        # construction, unit tests) behaves exactly like the round-5
        # flush-only path; a holder-provided WAL switches _log_op to the
        # configured durability mode.
        self.wal = wal
        # Verified loads (storage/integrity.py): open() checks the
        # snapshot's block digests against the .checksums sidecar
        # written at snapshot time, so silent media rot surfaces as a
        # typed CorruptFragmentError instead of being decoded and
        # served. Hot paths pay nothing — the digests ride the blocks()
        # memo against the mutation counter.
        self.verify_on_load = verify_on_load
        self.wal_key = f"{index}/{field}/{view}/{shard}"
        # scope leads the id: residency keys and write-routing tags must
        # never collide across two Holders in one process (in-process
        # clusters, embedded multi-server) — same-named fragments on
        # different holders hold DIFFERENT replicas' data
        self.frag_id = (scope, index, field, view, shard)
        self.bitmap = RoaringBitmap()
        self.op_n = 0
        # monotonic content version: bumped on every mutation (see
        # _log_op); validates the row_counts memo
        self.mutations = 0
        self._row_counts_memo: tuple | None = None
        self._blocks_memo: tuple | None = None
        self.snapshot_threshold = snapshot_threshold
        self.row_cache = new_row_cache(cache_type, cache_size)
        self._file = None
        self._open = False
        # One writer at a time per fragment (reference fragment.mu):
        # mutators, snapshot, and consistent-view readers (blocks,
        # serialize_snapshot) take this; row reads stay lock-free against
        # atomic container swaps.
        self.lock = threading.RLock()

    # ------------------------------------------------------------- lifecycle

    def open(self) -> "Fragment":
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        if os.path.exists(self.path):
            buf = read_file(self.path)  # disk-fault read seam
            if buf:
                # snapshot decode + (verify-on-load) sidecar digest
                # check BEFORE op replay: the sidecar describes exactly
                # the snapshot portion; trailing ops carry their own
                # CRCs. Any decode error or digest mismatch raises the
                # typed CorruptFragmentError — View.open quarantines
                # the file and moves on; direct callers see the error.
                self.bitmap, ops_at = load_verified(
                    buf, self.path, verify=self.verify_on_load
                )
                try:
                    self.op_n = replay_ops(self.bitmap, buf, ops_at)
                except DECODE_ERRORS as e:
                    raise CorruptFragmentError(
                        self.path, f"op replay failed: {e}", offset=ops_at,
                    ) from e
        else:
            with open(self.path, "wb") as f:
                f.write(serialize(self.bitmap))
        self.row_cache.load(self._cache_path())
        self._file = open(self.path, "ab")
        self._open = True
        if self.op_n > self.snapshot_threshold:
            self.snapshot()
        return self

    def close(self, discard: bool = False) -> None:
        """``discard=True`` is the delete-path close: the caller is
        about to unlink the files, so skip the snapshot / cache-save /
        op-tail-fsync work that would durably rewrite data the
        tombstone already covers (a resize cleanup over many shards
        would otherwise pay one full fsynced bitmap rewrite per
        fragment purely to delete it)."""
        with self.lock:
            if not self._open:
                return
            if not discard:
                if (self.wal is not None and self.wal.grouped
                        and self.op_n > 0):
                    # group mode keeps ops only in the WAL: a clean
                    # close must snapshot so the fragment file is
                    # self-contained (and the holder can truncate the
                    # WAL afterwards). A FAILED snapshot (full/dying
                    # disk) must not abort the close: the ops stay
                    # durable in their WAL segments — note_snapshot was
                    # never called, so segment GC keeps them and the
                    # next open's recover() replays them (the contract
                    # holder.close documents).
                    try:
                        self._snapshot_locked()
                    except OSError:
                        pass  # health already tripped by the snapshot
                try:
                    self.row_cache.save(self._cache_path())
                except OSError:
                    pass  # cache is derived data; recount rebuilds it
            elif self.wal is not None and self.wal.grouped:
                # delete path: a write in flight during the delete may
                # have appended AFTER the tombstone's seq — release the
                # key's segment pins or that op holds the WAL hostage
                self.wal.discard_key(self.wal_key)
            if self._file:
                if self.op_n > 0 and not discard:
                    # clean-close durability for the appended op tail
                    # (flush-only/per-op modes): one fsync per fragment,
                    # not one per op
                    try:
                        self._file.flush()
                        os.fsync(self._file.fileno())
                    except OSError:
                        pass
                self._file.close()
                self._file = None
            residency.global_row_cache().invalidate_fragment(self.frag_id)
            # delete/repair-swap closes change what this fragment will
            # answer next; clean closes are invalidated too (harmless —
            # the holder is going away or the file may change while shut)
            rescache.invalidate_write(self.scope, self.index, self.field,
                                      self.shard)
            self._open = False

    def _cache_path(self) -> str:
        return self.path + ".cache"

    # ----------------------------------------------------------------- reads

    def max_row_id(self) -> int:
        if not self.bitmap.keys:
            return 0
        return self.bitmap.keys[-1] >> 4  # key = bit >> 16; row = key >> 4

    def row_ids(self) -> list[int]:
        """Rows with at least one container present (superset of non-empty
        rows; exact after compaction since empty containers are dropped)."""
        return sorted({k >> 4 for k in self.bitmap.keys})

    def row_counts(self) -> tuple[np.ndarray, np.ndarray]:
        """Exact (row_ids, counts) for every non-empty row, in one pass
        over container metadata: a row spans 16 containers (key >> 4), and
        each container already knows its cardinality, so counting all rows
        is O(#containers) with no per-row scan and no bit materialization.

        This is the cold-path feed for TopN phase 1 and Rows()/GroupBy
        dimension discovery (reference fragment.top / executor Rows —
        SURVEY.md §3.4). The reference walks the ranked cache instead; at
        design scale (50k rows × 1k shards) a per-row count loop is
        millions of host calls, and a device pass would upload dense
        zeros — container metadata is strictly cheaper than either.

        Memoized against the fragment's mutation counter: GroupBy/Rows
        call this per fragment per query, and even the metadata pass is
        ~0.4 ms on a populated fragment — ~50 ms/query of host prelude
        at 64 shards x 2 dims. The version is snapshotted BEFORE the
        pass so a racing write can only force an extra recompute, never
        a stale hit. Callers must not mutate the returned arrays.
        """
        memo = self._row_counts_memo
        if memo is not None and memo[0] == self.mutations:
            return memo[1]
        version = self.mutations
        # flatten is the one sanctioned container walk (lock-free .get +
        # skip inside kernels.flatten); the row fold is pure vectorized
        # metadata math on the flat key/cardinality arrays
        flat = kernels.flatten(self.bitmap)
        if flat.n_containers == 0:
            out = (np.empty(0, np.int64), np.empty(0, np.int64))
        else:
            rows = flat.keys >> 4
            uniq, inv = np.unique(rows, return_inverse=True)
            counts = np.zeros(uniq.size, np.int64)
            np.add.at(counts, inv, flat.cards)
            out = (uniq, counts)
        for a in out:  # shared across callers: in-place edits would
            a.setflags(write=False)  # corrupt the memo silently
        self._row_counts_memo = (version, out)
        return out

    def row_words(self, row: int) -> np.ndarray:
        """Dense uint32[32768] for one row (host side): one flatten of
        the row's 16-container window, one batched decode kernel —
        byte-identical to the per-container ``dense_range_words32``
        walk it replaced (tests/test_roaring_kernels.py)."""
        base_key = (row << 20) >> 16
        flat = kernels.flatten(self.bitmap, base_key, base_key + 15)
        cost = current_cost()
        if cost is not None:
            # Container-taxonomy cost accounting (Chambi et al.
            # 1402.6407): ONE tally per kernel call, totals identical
            # to the retired per-container walk (the flat view holds
            # exactly the row's non-empty containers). Only residency
            # MISSES reach this path — steady-state hot queries pay
            # nothing here.
            cost.note_containers(*flat.kind_counts())
        return kernels.dense_words32(flat, base_key, 16)

    def device_row(self, row: int):
        """Device-resident dense row, decoded through the residency cache."""
        return residency.global_row_cache().get_row(
            self.frag_id + (row,), lambda: self.row_words(row)
        )

    def row_columns(self, row: int) -> np.ndarray:
        """Sorted in-shard column positions set in ``row``."""
        base = row << 20
        ids = self.bitmap.range_ids(base, base + SHARD_WIDTH)
        return (ids - np.uint64(base)).astype(np.uint64)

    def count_row(self, row: int) -> int:
        base = row << 20
        return self.bitmap.count_range(base, base + SHARD_WIDTH)

    def count(self) -> int:
        return self.bitmap.count()

    def contains(self, row: int, pos: int) -> bool:
        return (row << 20) + pos in self.bitmap

    def rows_containing(self, pos: int) -> list[int]:
        """All rows with bit ``pos`` set (Rows(column=)).

        One vectorized pass filters container metadata — for a fixed
        in-shard position only the (key & 15) == pos>>16 sub-container of
        each row can hold it — then an O(1)/O(log) membership probe per
        surviving container (Container.contains_low). No full-row decode,
        no per-row Python loop over all rows (reference executor.go Rows
        with a column filter walks rows too; at 50k rows that was the
        host-side cliff VERDICT r2 flagged — container metadata is
        strictly cheaper than either a host walk or shipping a
        [rows, words] probe matrix to the device)."""
        keys = self.bitmap.keys
        if not keys:
            return []
        arr = np.fromiter(keys, np.int64, len(keys))
        cand = arr[(arr & 15) == (pos >> 16)]
        low = pos & 0xFFFF
        out = []
        for key in cand.tolist():
            c = self.bitmap.container(key)
            if c is not None and c.contains_low(low):
                out.append(key >> 4)
        return out

    # ---------------------------------------------------------------- writes

    def set_bit(self, row: int, pos: int) -> bool:
        self._check_pos(pos)
        with self.lock:
            changed = self.bitmap.add_ids([(row << 20) + pos]) > 0
            if changed:
                self._log_op(OP_ADD, [(row << 20) + pos])
                self._after_row_write(row, positions=[pos], added=True)
            return changed

    def clear_bit(self, row: int, pos: int) -> bool:
        self._check_pos(pos)
        with self.lock:
            changed = self.bitmap.remove_ids([(row << 20) + pos]) > 0
            if changed:
                self._log_op(OP_REMOVE, [(row << 20) + pos])
                self._after_row_write(row, positions=[pos], added=False)
            return changed

    def clear_row(self, row: int) -> int:
        """Remove every bit in a row (mutex fields, Store). Returns #cleared."""
        with self.lock:
            cols = self.row_columns(row)
            if cols.size == 0:
                return 0
            ids = cols + np.uint64(row << 20)
            removed = self.bitmap.remove_ids(ids)
            self._log_op(OP_REMOVE, ids)
            self._after_row_write(row, positions=cols, added=False)
            return removed

    def write_row_words(self, row: int, words: np.ndarray) -> None:
        """Replace a row wholesale from a dense word vector (Store(),
        anti-entropy block repair). Logged as clear+add."""
        from pilosa_tpu.ops.packing import unpack_bits

        with self.lock:
            old = self.row_columns(row) + np.uint64(row << 20)
            new = unpack_bits(words) + np.uint64(row << 20)
            if old.size:
                self.bitmap.remove_ids(old)
                self._log_op(OP_REMOVE, old)
            if new.size:
                self.bitmap.add_ids(new)
                self._log_op(OP_ADD, new)
            self._after_row_write(row)

    def bulk_import(self, rows, positions) -> int:
        """Batched import of (row, position) pairs (reference
        fragment.bulkImport — SURVEY.md §3.3). Returns #bits changed."""
        rows = np.asarray(rows, dtype=np.uint64)
        positions = np.asarray(positions, dtype=np.uint64)
        if rows.shape != positions.shape:
            raise ValueError("rows and positions must have identical shape")
        if positions.size and positions.max() >= SHARD_WIDTH:
            raise ValueError("position out of shard range")
        ids = (rows << np.uint64(20)) + positions
        with self.lock:
            changed = self.bitmap.add_ids(ids)
            if changed:
                self._log_op(OP_ADD, ids)
                self._after_rows_added(rows, positions)
            return changed

    def import_mutex(self, rows: np.ndarray, positions: np.ndarray) -> int:
        """Mutex-aware bulk import (reference fragment.bulkImportMutex —
        SURVEY.md §3.3): each imported column's previous row clears in
        the same locked pass, preserving the single-value invariant that
        plain ``bulk_import`` would silently break. Duplicate positions
        keep the LAST row (sequential set_bit semantics). Returns the
        number of columns whose bit was newly added (a moved column
        counts once; a no-op re-set counts zero — matching set_bit)."""
        rows = np.asarray(rows, np.uint64)
        positions = np.asarray(positions, np.uint64)
        if rows.shape != positions.shape:
            raise ValueError("rows and positions must have identical shape")
        if positions.size == 0:
            return 0
        if int(positions.max()) >= SHARD_WIDTH:
            raise ValueError("position out of shard range")
        keep = keep_last_unique(positions)
        rows, positions = rows[keep], positions[keep]
        from pilosa_tpu.roaring import merge_kernels

        with self.lock:
            # ONE batched probe yields every (current-row, column) pair
            # set among the batch columns — replacing the old
            # row_member scan over ALL fragment rows (O(rows x batch))
            cur_rows, cur_idx = merge_kernels.set_rows_for_positions(
                self.bitmap, positions)
            conflict = cur_rows.astype(np.uint64) != rows[cur_idx]
            target_set = np.zeros(positions.size, bool)
            target_set[cur_idx[~conflict]] = True

            add_parts: list = []
            rem_parts: list = []
            rows_added: list = []
            rows_removed: list = []
            for r, p in _group_by_row(cur_rows[conflict],
                                      positions[cur_idx[conflict]]):
                rem_parts.append((np.uint64(r) << np.uint64(20)) + p)
                rows_removed.append((r, p))
            add_m = ~target_set
            changed = int(add_m.sum())
            for r, p in _group_by_row(rows[add_m], positions[add_m]):
                add_parts.append((np.uint64(r) << np.uint64(20)) + p)
                rows_added.append((r, p))
            self._apply_batch_locked(add_parts, rem_parts,
                                     rows_added, rows_removed)
            return changed

    def _apply_batch_locked(self, add_parts, rem_parts,
                            rows_added, rows_removed) -> None:
        """Shared tail of the batched import paths (caller holds the
        fragment lock): one sorted add pass + one sorted remove pass,
        each logged as a single op record, then per-row residency/cache
        bookkeeping."""
        if add_parts:
            ids = np.sort(np.concatenate(add_parts))
            self.bitmap.add_ids(ids)
            self._log_op(OP_ADD, ids)
        if rem_parts:
            ids = np.sort(np.concatenate(rem_parts))
            self.bitmap.remove_ids(ids)
            self._log_op(OP_REMOVE, ids)
        feed = self._row_count_feed(len(rows_added) + len(rows_removed))
        for r, p in rows_added:
            self._after_row_write(int(r), positions=p, added=True,
                                  count_stat=False,
                                  row_count=feed(int(r)))
        for r, p in rows_removed:
            self._after_row_write(int(r), positions=p, added=False,
                                  count_stat=False,
                                  row_count=feed(int(r)))
        # the batch-amortized tail (same shape as _after_rows_added):
        # ONE stats bump, ONE result-cache write event, ONE heat record
        # for the whole batch — a bit_depth-32 BSI import must not take
        # the global result-cache lock 34x per shard
        n_rows = len(rows_added) + len(rows_removed)
        if n_rows:
            from pilosa_tpu.utils.stats import global_stats

            global_stats().count("fragment_row_writes", n_rows)
            rescache.invalidate_write(self.scope, self.index, self.field,
                                      self.shard)
            if current_cost() is not None:
                bits = sum(len(p) for _, p in rows_added)
                bits += sum(len(p) for _, p in rows_removed)
                global_heat().record_write(self.index, self.field,
                                           self.shard, n=float(bits),
                                           scope=self.scope)

    def import_bsi(self, positions: np.ndarray, stored: np.ndarray,
                   bit_depth: int, exists_row: int = 0,
                   offset_row: int = 2) -> int:
        """Batched BSI write (reference fragment.importValue — SURVEY.md
        §3.3): one lock + one add pass + one remove pass for a whole
        (position, stored-value) batch, in place of per-column
        ``set_value``'s per-bit fragment ops (1 + depth locked ops and
        op-log appends per column). ``positions`` must be duplicate-free
        (callers dedupe keep-last). Returns the number of COLUMNS whose
        existence or stored value changed — the same count a set_value
        loop would report."""
        positions = np.asarray(positions, np.uint64)
        stored = np.asarray(stored, np.uint64)
        if positions.size and int(positions.max()) >= SHARD_WIDTH:
            raise ValueError("position out of shard range")
        from pilosa_tpu.roaring import merge_kernels

        with self.lock:
            add_parts: list = []
            rem_parts: list = []
            rows_added: list = []
            rows_removed: list = []
            # exists row + every bit plane probed in ONE batched pass
            # (the old code ran a row_member scan per plane: 1+depth
            # full-keyspace probes per import)
            member = merge_kernels.member_matrix(
                self.bitmap,
                [exists_row] + [offset_row + i for i in range(bit_depth)],
                positions)
            exists_new = ~member[0]
            changed_cols = exists_new.copy()
            if exists_new.any():
                p = positions[exists_new]
                add_parts.append(
                    (np.uint64(exists_row) << np.uint64(20)) + p
                )
                rows_added.append((exists_row, p))
            for i in range(bit_depth):
                row = offset_row + i
                desired = ((stored >> np.uint64(i)) & np.uint64(1)) == 1
                cur = member[1 + i]
                add_m = desired & ~cur
                rem_m = ~desired & cur
                if add_m.any():
                    p = positions[add_m]
                    add_parts.append((np.uint64(row) << np.uint64(20)) + p)
                    rows_added.append((row, p))
                if rem_m.any():
                    p = positions[rem_m]
                    rem_parts.append((np.uint64(row) << np.uint64(20)) + p)
                    rows_removed.append((row, p))
                changed_cols |= add_m | rem_m
            if not changed_cols.any():
                return 0
            self._apply_batch_locked(add_parts, rem_parts,
                                     rows_added, rows_removed)
            return int(changed_cols.sum())

    def import_roaring(self, data: bytes) -> int:
        """Union a serialized roaring bitmap into this fragment (reference
        api.ImportRoaring fast path). Accepts either this framework's
        layout or the upstream pilosa layout (sniffed by cookie).
        Undecodable payloads (torn wire frames, corrupt import bodies)
        raise the typed CorruptFragmentError (a ValueError subclass, so
        existing 400 mappings hold)."""
        try:
            other, _ = load_any(data)
        except DECODE_ERRORS as e:
            raise CorruptFragmentError(
                self.path, f"import-roaring payload decode failed: {e}",
            ) from e
        return self.import_roaring_bitmap(other)

    def import_roaring_bitmap(self, other) -> int:
        """Union an already-parsed RoaringBitmap into this fragment."""
        return self.add_ids(other.to_ids())

    def add_ids_mutex(self, ids) -> int:
        """Anti-entropy repair into a SINGLE-VALUE field's fragment: add
        only bits for columns not already set in a different row locally.
        A pure union would resurrect rows a newer import cleared,
        breaking the mutex invariant on this replica; conflicting
        columns keep the LOCAL row (each replica stays self-consistent,
        and the divergence heals on the next write to the column, which
        clears other rows on every replica)."""
        ids = np.asarray(ids, np.uint64)
        if ids.size == 0:
            return 0
        # incoming duplicates for one column (a peer already holding a
        # double-set) collapse to one candidate row
        pos = ids & np.uint64(SHARD_WIDTH - 1)
        ids = ids[keep_last_unique(pos)]
        pos = ids & np.uint64(SHARD_WIDTH - 1)
        rows = ids >> np.uint64(SHARD_WIDTH_EXP)
        from pilosa_tpu.roaring import merge_kernels

        with self.lock:
            # one batched probe finds every locally-set (row, column)
            # pair among the incoming columns (was a row_member scan
            # over every fragment row)
            cur_rows, cur_idx = merge_kernels.set_rows_for_positions(
                self.bitmap, pos)
            keep = np.ones(ids.size, bool)
            conflict = cur_rows.astype(np.uint64) != rows[cur_idx]
            keep[cur_idx[conflict]] = False
            ids = ids[keep]
            return self.add_ids(ids) if ids.size else 0

    def add_ids_value(self, ids, exists_row: int = 0) -> int:
        """Anti-entropy repair into a BSI fragment: per COLUMN
        all-or-nothing. A column whose exists bit is set locally keeps
        its whole local value — unioning a peer's stale planes into a
        newer value would splice together a value no client ever wrote.
        Columns absent locally adopt the peer's planes wholesale."""
        ids = np.asarray(ids, np.uint64)
        if ids.size == 0:
            return 0
        pos = ids & np.uint64(SHARD_WIDTH - 1)
        with self.lock:
            local_exists = self.bitmap.row_member(exists_row, pos)
            ids = ids[~local_exists]
            return self.add_ids(ids) if ids.size else 0

    def add_ids(self, ids) -> int:
        """Union raw bit ids under the fragment lock (import-roaring,
        anti-entropy block repair). Returns #bits changed."""
        ids = np.asarray(ids, np.uint64)
        with self.lock:
            changed = self.bitmap.add_ids(ids)
            if changed:
                self._log_op(OP_ADD, ids)
                self._after_rows_added(
                    ids >> np.uint64(20), ids & np.uint64(SHARD_WIDTH - 1)
                )
            return changed

    # ------------------------------------------------------------ durability

    def _log_op(self, op: int, ids) -> None:
        self.mutations += 1
        if self._file is None:
            return
        wal = self.wal
        record = encode_op(op, ids)
        if wal is not None and wal.grouped:
            # group commit (storage/wal.py): the record rides the
            # holder WAL; ONE fsync per group of concurrent writers.
            # The ACK point (server/api.py) barriers on the WAL, so the
            # mutator itself never blocks on the disk — and never waits
            # while holding this fragment's lock.
            wal.append_op(self.wal_key, record, self)
        else:
            self._file.write(record)
            self._file.flush()
            if wal is not None and wal.mode == MODE_PER_OP:
                # true per-write durability (round 5 only flush()ed —
                # OS-buffer-deep; see docs/OPERATIONS.md)
                try:
                    _faults.disk_check("fsync", self.path)
                    wal_fsync(self._file.fileno())
                except OSError as e:
                    self._trip_health(f"per-op fsync of {self.path}: {e}")
                    raise
        self.op_n += 1
        if self.op_n > self.snapshot_threshold:
            self.snapshot()

    def apply_recovered(self, op: int, ids) -> None:
        """Apply one replayed WAL op (holder open, single-threaded at
        recovery; also the CDC follower's live tail-apply path): the
        bitmap mutation without logging — the caller snapshots and
        recounts caches once per touched fragment afterwards."""
        ids = np.atleast_1d(np.asarray(ids, np.uint64))
        with self.lock:
            if op == OP_ADD:
                self.bitmap.add_ids(ids)
            else:
                self.bitmap.remove_ids(ids)
            self.mutations += 1
        cache = residency.global_row_cache()
        cache.invalidate_fragment(self.frag_id)
        # route the write to dependent STACKED leaves too (positions
        # unknown -> conservative invalidation, not in-place patching):
        # a crash-recovery replay has none resident, but the CDC
        # follower applies these against a live serving cache
        for row in np.unique(ids >> np.uint64(20)).tolist():
            cache.apply_write(residency.WriteEvent(
                self.index, self.field, self.view, self.shard, row,
                scope=self.scope,
            ))
        rescache.invalidate_write(self.scope, self.index, self.field,
                                  self.shard)

    def snapshot(self) -> None:
        """Compact: rewrite the file as a clean snapshot, dropping the log
        (reference fragment.snapshot — SURVEY.md §3.3)."""
        with self.lock:
            self._snapshot_locked()

    def _snapshot_locked(self) -> None:
        if self._file:
            self._file.close()
        tmp = self.path + ".snapshotting"
        try:
            payload = _faults.disk_filter_write(  # torn-write seam
                self.path, serialize(self.bitmap)
            )
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                _faults.disk_check("fsync", self.path)
                os.fsync(f.fileno())
            # the OLD sidecar must die BEFORE the new snapshot is
            # published: a crash between the rename and the new sidecar
            # landing would otherwise pair the new snapshot with stale
            # digests, and verify-on-load would quarantine a perfectly
            # healthy file (a MISSING sidecar only downgrades the next
            # open to an unverified load — safe)
            try:
                os.unlink(self.path + CHECKSUM_SUFFIX)
            except FileNotFoundError:
                pass
            os.replace(tmp, self.path)
        except OSError as e:
            # a failed snapshot (ENOSPC, EIO) flips the node to the
            # read-only storage_degraded mode instead of surfacing a
            # raw traceback through the write path; the old file is
            # intact (tmp-then-rename), so reads keep serving
            self._trip_health(f"snapshot of {self.path}: {e}")
            if self._open and self._file is not None and self._file.closed:
                try:
                    self._file = open(self.path, "ab")
                except OSError:
                    self._file = None
            raise
        # a crash between the rename and the directory entry reaching
        # disk can lose the whole snapshot: rename durability needs the
        # parent fsynced too
        fsync_dir(os.path.dirname(self.path))
        # checksum sidecar: the block digests of exactly these bytes,
        # for verify-on-load and the background scrubber. Best-effort —
        # a torn/missing sidecar downgrades to an unverified load, it
        # never condemns the healthy snapshot beside it.
        try:
            save_checksums(self.path + CHECKSUM_SUFFIX, self.blocks())
        except OSError as e:
            self._trip_health(f"checksum sidecar of {self.path}: {e}")
        if self.wal is not None:
            # every op of this fragment appended so far (the lock is
            # held, so the seq covers them all) is in the snapshot —
            # release them from WAL segment retention
            self.wal.note_snapshot(self.wal_key, self.wal.current_seq())
        self.op_n = 0
        if self._open:
            self._file = open(self.path, "ab")

    def _row_count_feed(self, n_rows: int):
        """Row-count source for batch bookkeeping: above a few touched
        rows, ONE ``row_counts()`` metadata pass feeds every
        ``row_cache.add`` instead of a ``count_row`` probe per row.
        Callers invoke this AFTER the batch's mutations are applied (the
        memo keys on the mutation counter). Small batches return None
        per row — the point-write probe is cheaper than the full pass."""
        if n_rows <= 8:
            return lambda row: None
        r_ids, r_counts = self.row_counts()

        def feed(row: int):
            i = int(np.searchsorted(r_ids, row))
            if i < r_ids.size and int(r_ids[i]) == row:
                return int(r_counts[i])
            return 0  # the batch emptied this row

        return feed

    def _after_rows_added(self, rows: np.ndarray, positions: np.ndarray) -> None:
        """Per-row write bookkeeping for bulk adds: group positions by row
        with one sort instead of a per-row mask scan (which is O(n·rows)
        and turns large imports quadratic)."""
        groups = list(_group_by_row(rows, positions))
        feed = self._row_count_feed(len(groups))
        for row, p in groups:
            self._after_row_write(
                row, positions=p, added=True, count_stat=False,
                row_count=feed(row),
            )
        # one counter bump for the whole batch: parallel ingest workers
        # would otherwise serialize on the global stats lock per row
        from pilosa_tpu.utils.stats import global_stats

        global_stats().count("fragment_row_writes", len(groups))
        # ONE result-cache write event per batch (the per-row calls
        # above pass count_stat=False and skip theirs) — unconditional:
        # the cost kill switch gates accounting, never correctness
        rescache.invalidate_write(self.scope, self.index, self.field,
                                  self.shard)
        if current_cost() is not None:
            # one heat record per batch, weighted by written bits — same
            # lock-amortization reasoning as the counter above. Gated on
            # an ACTIVE request context (like the access side): bulk
            # imports record at the API layer, and background
            # anti-entropy repair (add_ids/write_row_words with neither)
            # must not rank merely-repaired shards hot
            global_heat().record_write(self.index, self.field, self.shard,
                                       n=float(rows.size),
                                       scope=self.scope)

    def _after_row_write(self, row: int, positions=None, added=None,
                         count_stat: bool = True,
                         row_count: int | None = None) -> None:
        """Invalidate this fragment's own device entries and route the
        write to dependent stacked leaves for in-place patching (instead
        of the old global generation purge — one Set() must not evict
        unrelated resident leaves). Batch paths pass ``row_count`` from
        one shared ``row_counts()`` metadata pass; point writes leave it
        None and pay one ``count_row``."""
        cache = residency.global_row_cache()
        cache.invalidate(self.frag_id + (row,))
        cache.invalidate_fragment(self.frag_id + ("__planes__",))
        cache.apply_write(residency.WriteEvent(
            self.index, self.field, self.view, self.shard, row,
            positions=positions, added=added, scope=self.scope,
        ))
        if row_count is None:
            row_count = self.count_row(row)
        self.row_cache.add(row, row_count)
        if count_stat:
            # the WAL-visible write point: a cached result depending on
            # this (index, field, shard) must die BEFORE the write's
            # durability barrier releases its 200 — the in-memory
            # mutation above is already reader-visible, so an acked
            # write can never be masked by stale cached bytes.
            # Batch paths (count_stat=False, from _after_rows_added)
            # invalidate once per batch instead of once per row.
            rescache.invalidate_write(self.scope, self.index, self.field,
                                      self.shard)
            from pilosa_tpu.utils.stats import global_stats

            global_stats().count("fragment_row_writes", 1)
            if current_cost() is not None:
                # per-shard write heat (docs/OBSERVABILITY.md) for PQL
                # writes — an active request context only: bulk imports
                # record at the API layer, background repair records
                # nothing (see _after_rows_added)
                global_heat().record_write(self.index, self.field,
                                           self.shard, scope=self.scope)

    def _check_pos(self, pos: int) -> None:
        if not 0 <= pos < SHARD_WIDTH:
            raise ValueError(f"position {pos} outside shard width {SHARD_WIDTH}")

    def _trip_health(self, reason: str) -> None:
        """Route a disk fault into the holder's StorageHealth latch
        (read-only degraded mode) via the WAL the storage tree already
        threads; direct-constructed fragments (wal=None) just raise."""
        health = getattr(self.wal, "health", None) if self.wal else None
        if health is not None:
            health.trip(reason)

    # ---------------------------------------------------- anti-entropy blocks

    def serialize_snapshot(self) -> bytes:
        """Consistent serialized snapshot of the live bitmap (resize /
        anti-entropy fragment-data fetch)."""
        with self.lock:
            return serialize(self.bitmap)

    def blocks(self) -> list[tuple[int, str]]:
        """Checksums of BLOCK_ROWS-row blocks for replica diffing
        (reference fragment.Blocks — SURVEY.md §3.5).

        Memoized against the mutation counter: the batched manifest route
        serves EVERY fragment's checksums per anti-entropy pass, and each
        recompute is a full to_ids materialization + hash walk. The
        version is snapshotted before the pass, so a racing write can
        only force an extra recompute, never a stale hit. Callers must
        not mutate the returned list."""
        memo = self._blocks_memo
        if memo is not None and memo[0] == self.mutations:
            return memo[1]
        version = self.mutations
        # flatten under the lock (metadata-only; containers are
        # immutable once published), materialize + digest outside it —
        # the id kernel no longer serializes writers
        with self.lock:
            flat = kernels.flatten(self.bitmap)
        ids = kernels.fragment_ids(flat)
        # one digest implementation (storage/integrity.py) shared by
        # the sync manifests, backup blob addressing, verify-on-load,
        # and the scrubber — every plane speaks the same checksums
        out = block_digests(ids, BLOCK_ROWS)
        self._blocks_memo = (version, out)
        return out

    def block_ids(self, block: int) -> np.ndarray:
        """All bit ids in one checksum block (for block repair)."""
        return self.blocks_ids([block])[block]

    def blocks_ids(self, blocks) -> dict[int, np.ndarray]:
        """Ids of MANY checksum blocks from one materialization: one
        flatten + one id kernel + one searchsorted slice per request —
        the sync block server used to pay a full ``to_ids`` PER block
        (O(blocks × population))."""
        with self.lock:
            flat = kernels.flatten(self.bitmap)
        ids = kernels.fragment_ids(flat)
        return kernels.block_slices(ids, blocks, BLOCK_ROWS)

    # -------------------------------------------------------------- TopN feed

    def recalculate_cache(self) -> None:
        """Rebuild the TopN row cache from exact container cardinalities
        and persist it (reference ``POST /recalculate-caches`` —
        fragment.RecalculateCache). Every write path maintains the cache
        incrementally; this is the authoritative recount for anything
        that drifted (a crash between bitmap flush and cache save, a
        hand-edited data dir)."""
        with self.lock:
            if not self._open:
                return  # racing index delete: nothing to repair, and
                        # save() would raise inside the removed dir
            fresh = new_row_cache(self.row_cache.kind,
                                  self.row_cache.max_size)
            rows, counts = self.row_counts()
            for r, c in zip(rows.tolist(), counts.tolist()):
                fresh.bulk_add(r, c)
            self.row_cache = fresh
            self.row_cache.save(self._cache_path())

    def top(self, n: int = 10, row_ids=None):
        """Local TopN candidates: (row, count) pairs from the ranked cache,
        counts exact (recomputed) — phase 1 of the reference's two-phase
        TopN (SURVEY.md §3.4). Cold/none cache falls back to the exact
        O(#containers) metadata scan, not a per-row loop."""
        if row_ids is not None:
            pairs = [(r, self.count_row(r)) for r in row_ids]  # O(candidates)
        else:
            pairs = self.row_cache.top()
            if not pairs:
                rows, counts = self.row_counts()
                pairs = list(zip(rows.tolist(), counts.tolist()))
        pairs = [(r, c) for r, c in pairs if c > 0]
        pairs.sort(key=lambda rc: (-rc[1], rc[0]))
        return pairs[:n] if n else pairs


def build_index_manifest(idx) -> list[tuple[str, str, int, list]]:
    """Every (field, view, shard) → checksum-block list of one index, in
    deterministic order — the body of ``GET /internal/sync/manifest``.
    One response replaces the per-fragment ``fragment_blocks`` GET storm
    of the r5 anti-entropy pass (O(fragments) control RTTs → 1); the
    per-fragment blocks() memo keeps serving it cheap for unmutated
    fragments. Fragments with no data still appear (empty block list):
    the manifest doubles as the peer catalog for inventory walks."""
    out = []
    for fname, fld in sorted(idx.fields.items()):
        for vname, view in sorted(fld.views.items()):
            for shard in sorted(view.fragments):
                frag = view.fragment(shard)
                if frag is None:
                    continue
                out.append((fname, vname, shard, frag.blocks()))
    return out
