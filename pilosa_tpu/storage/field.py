"""Field: a named boolean matrix with a schema (type, cache, keys, quantum).

Reference: field.go (SURVEY.md §2 #6). Field types:

- ``set``   — default multi-value rows.
- ``mutex`` — single-value: setting a column's row clears its previous row.
- ``bool``  — mutex restricted to rows {0:false, 1:true}.
- ``time``  — set + a time quantum (YMDH) generating time views on
  timestamped writes.
- ``int``   — BSI bit-sliced integers: one ``bsig_<field>`` view whose rows
  are [exists, sign, bit 0 … bit depth-1]; values are offset-encoded
  against the field minimum so all stored magnitudes are non-negative
  (aggregates add ``base·count`` back — see executor BSI kernels).

Write ops fan into views; every view write lands in a fragment chosen by
``column >> 20``.
"""

from __future__ import annotations

import datetime as dt
import json
import os
import threading

from pilosa_tpu.shardwidth import position, shard_of
from pilosa_tpu.storage.cache import CACHE_TYPE_RANKED, DEFAULT_CACHE_SIZE
from pilosa_tpu.storage.view import (
    VIEW_STANDARD,
    View,
    validate_quantum,
    view_name_bsi,
    views_for_time,
)

TYPE_SET = "set"
TYPE_INT = "int"
TYPE_TIME = "time"
TYPE_MUTEX = "mutex"
TYPE_BOOL = "bool"

# BSI plane layout within the bsig view.
BSI_EXISTS_ROW = 0
BSI_SIGN_ROW = 1  # reserved; offset encoding keeps magnitudes non-negative
BSI_OFFSET_ROW = 2


class FieldOptions:
    def __init__(
        self,
        type: str = TYPE_SET,
        cache_type: str = CACHE_TYPE_RANKED,
        cache_size: int = DEFAULT_CACHE_SIZE,
        min: int = 0,
        max: int = 0,
        time_quantum: str = "",
        keys: bool = False,
    ):
        if type not in (TYPE_SET, TYPE_INT, TYPE_TIME, TYPE_MUTEX, TYPE_BOOL):
            raise ValueError(f"invalid field type {type!r}")
        if type == TYPE_INT and max < min:
            raise ValueError("int field requires max >= min")
        if type == TYPE_TIME:
            validate_quantum(time_quantum)
            if not time_quantum:
                raise ValueError("time field requires a time quantum")
        self.type = type
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.min = min
        self.max = max
        self.time_quantum = time_quantum
        self.keys = keys

    @property
    def base(self) -> int:
        return self.min

    @property
    def bit_depth(self) -> int:
        span = self.max - self.min
        return max(1, span.bit_length())

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "cacheType": self.cache_type,
            "cacheSize": self.cache_size,
            "min": self.min,
            "max": self.max,
            "timeQuantum": self.time_quantum,
            "keys": self.keys,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FieldOptions":
        return cls(
            type=d.get("type", TYPE_SET),
            cache_type=d.get("cacheType", CACHE_TYPE_RANKED),
            cache_size=d.get("cacheSize", DEFAULT_CACHE_SIZE),
            min=d.get("min", 0),
            max=d.get("max", 0),
            time_quantum=d.get("timeQuantum", ""),
            keys=d.get("keys", False),
        )


class Field:
    def __init__(self, path: str, index: str, name: str,
                 options: FieldOptions | None = None, scope: str = "",
                 wal=None, verify_on_load: bool = False):
        self.path = path
        self.scope = scope
        self.wal = wal  # holder WAL, threaded down to views/fragments
        self.verify_on_load = verify_on_load
        self.index = index
        self.name = name
        self.options = options or FieldOptions()
        self.views: dict[str, View] = {}
        # serializes first-write view creation (see View._create_lock:
        # unlocked check-then-create loses concurrent writers' bits)
        self._create_lock = threading.Lock()
        self.row_attrs = None  # AttrStore, opened in open()

    # ------------------------------------------------------------- lifecycle

    def open(self) -> "Field":
        os.makedirs(self.path, exist_ok=True)
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                self.options = FieldOptions.from_dict(json.load(f))
        else:
            self._save_meta()
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for name in sorted(os.listdir(views_dir)):
                self.views[name] = View(
                    os.path.join(views_dir, name),
                    self.index,
                    self.name,
                    name,
                    cache_type=self.options.cache_type,
                    cache_size=self.options.cache_size,
                    scope=self.scope,
                    wal=self.wal,
                    verify_on_load=self.verify_on_load,
                ).open()
        from pilosa_tpu.storage.attrs import AttrStore

        self.row_attrs = AttrStore(os.path.join(self.path, ".rowattrs.db")).open()
        return self

    def close(self, discard: bool = False) -> None:
        for v in list(self.views.values()):
            v.close(discard=discard)
        if self.row_attrs is not None:
            self.row_attrs.close()
        # drop derived device entries (stacked query leaves) tied to this
        # field: files may change while closed, or the field may be
        # deleted and recreated under the same name
        from pilosa_tpu.serving import rescache
        from pilosa_tpu.storage import residency

        residency.global_row_cache().invalidate_tag(
            (self.scope, self.index, self.name)
        )
        # a field closing (delete, or the holder shutting down) fences
        # every cached result of the index — deletes change what ANY
        # query of the index answers (existence columns included)
        rescache.invalidate_index_wide(self.scope, self.index)

    def _save_meta(self) -> None:
        # fsynced for the same reason as Index._save_meta: WAL recovery
        # must be able to resolve this field after a power cut, or the
        # acked ops it holds for the field are silently unreplayable
        from pilosa_tpu.storage.wal import fsync_dir
        from pilosa_tpu.testing import faults

        meta = os.path.join(self.path, ".meta")
        try:
            faults.disk_check("write", meta)
            with open(meta, "w") as f:
                json.dump(self.options.to_dict(), f)
                f.flush()
                faults.disk_check("fsync", meta)
                os.fsync(f.fileno())
        except OSError as e:
            # a full disk on a schema write degrades the node read-only
            # (storage/integrity.py) instead of leaving a half-written
            # .meta behind a raw traceback
            health = getattr(self.wal, "health", None) if self.wal else None
            if health is not None:
                health.trip(f".meta write of {meta}: {e}")
            raise
        fsync_dir(self.path)
        fsync_dir(os.path.dirname(self.path) or ".")

    # ----------------------------------------------------------------- views

    def view(self, name: str, create: bool = False) -> View | None:
        v = self.views.get(name)
        if v is None and create:
            with self._create_lock:
                v = self.views.get(name)
                if v is None:
                    v = View(
                        os.path.join(self.path, "views", name),
                        self.index,
                        self.name,
                        name,
                        cache_type=self.options.cache_type,
                        cache_size=self.options.cache_size,
                        scope=self.scope,
                        wal=self.wal,
                        verify_on_load=self.verify_on_load,
                    ).open()
                    self.views[name] = v
        return v

    def bsi_view_name(self) -> str:
        return view_name_bsi(self.name)

    def available_shards(self) -> list[int]:
        shards: set[int] = set()
        for v in list(self.views.values()):
            shards.update(v.available_shards())
        return sorted(shards)

    # ---------------------------------------------------------------- writes

    def set_bit(self, row: int, column: int, timestamp: dt.datetime | None = None) -> bool:
        """Set (row, column); mutex/bool clear the column's previous row
        first. Timestamped writes also land in quantum time views."""
        if self.options.type == TYPE_INT:
            raise ValueError("set_bit on int field; use set_value")
        if self.options.type == TYPE_BOOL and row not in (0, 1):
            raise ValueError("bool field rows must be 0 (false) or 1 (true)")
        shard, pos = shard_of(column), position(column)
        frag = self.view(VIEW_STANDARD, create=True).fragment(shard, create=True)
        if self.options.type in (TYPE_MUTEX, TYPE_BOOL):
            for other in frag.row_ids():
                if other != row and frag.contains(other, pos):
                    frag.clear_bit(other, pos)
        changed = frag.set_bit(row, pos)
        if timestamp is not None:
            if self.options.type != TYPE_TIME:
                raise ValueError("timestamped write on non-time field")
            for vname in views_for_time(VIEW_STANDARD, self.options.time_quantum, timestamp):
                self.view(vname, create=True).fragment(shard, create=True).set_bit(row, pos)
        return changed

    def clear_bit(self, row: int, column: int) -> bool:
        shard, pos = shard_of(column), position(column)
        changed = False
        for v in list(self.views.values()):
            if v.name == self.bsi_view_name():
                continue
            frag = v.fragment(shard)
            if frag is not None:
                changed |= frag.clear_bit(row, pos)
        return changed

    def set_value(self, column: int, value: int) -> bool:
        """BSI write (reference field.SetValue): offset-encode and write the
        exists bit + magnitude bit planes."""
        if self.options.type != TYPE_INT:
            raise ValueError("set_value on non-int field")
        if not self.options.min <= value <= self.options.max:
            raise ValueError(
                f"value {value} outside field range "
                f"[{self.options.min}, {self.options.max}]"
            )
        stored = value - self.options.base
        shard, pos = shard_of(column), position(column)
        frag = self.view(self.bsi_view_name(), create=True).fragment(shard, create=True)
        changed = frag.set_bit(BSI_EXISTS_ROW, pos)
        for i in range(self.options.bit_depth):
            if (stored >> i) & 1:
                changed |= frag.set_bit(BSI_OFFSET_ROW + i, pos)
            else:
                changed |= frag.clear_bit(BSI_OFFSET_ROW + i, pos)
        return changed

    def import_values(self, columns, values) -> int:
        """Batched BSI import (reference field.importValue — SURVEY.md
        §3.3): validates and offset-encodes the whole batch, groups by
        shard, and writes each shard's planes through ONE locked
        fragment pass (Fragment.import_bsi) instead of per-column
        set_value's 1+depth locked ops. Duplicate columns keep the LAST
        value (matching a sequential set_value loop). Returns the number
        of columns whose value changed."""
        import numpy as np

        from pilosa_tpu.shardwidth import (
            SHARD_WIDTH,
            keep_last_unique,
            shard_groups,
        )

        if self.options.type != TYPE_INT:
            raise ValueError("import_values on non-int field")
        columns = np.atleast_1d(np.asarray(columns, np.uint64))
        values = np.atleast_1d(np.asarray(values, np.int64))
        if columns.size == 0:
            return 0
        bad = (values < self.options.min) | (values > self.options.max)
        if bad.any():
            v = int(values[bad][0])
            raise ValueError(
                f"value {v} outside field range "
                f"[{self.options.min}, {self.options.max}]"
            )
        keep = keep_last_unique(columns)
        columns, values = columns[keep], values[keep]
        stored = (values - self.options.base).astype(np.uint64)
        view = self.view(self.bsi_view_name(), create=True)
        order, bounds, shards_sorted = shard_groups(columns)
        cols_s, stored_s = columns[order], stored[order]
        changed = 0
        for i in range(bounds.size - 1):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            frag = view.fragment(int(shards_sorted[lo]), create=True)
            changed += frag.import_bsi(
                cols_s[lo:hi] & np.uint64(SHARD_WIDTH - 1),
                stored_s[lo:hi], self.options.bit_depth,
                exists_row=BSI_EXISTS_ROW, offset_row=BSI_OFFSET_ROW,
            )
        return changed

    def value(self, column: int) -> tuple[int, bool]:
        """Read one column's BSI value host-side (reference field.Value)."""
        if self.options.type != TYPE_INT:
            raise ValueError("value on non-int field")
        shard, pos = shard_of(column), position(column)
        view = self.view(self.bsi_view_name())
        frag = view.fragment(shard) if view else None
        if frag is None or not frag.contains(BSI_EXISTS_ROW, pos):
            return 0, False
        stored = 0
        for i in range(self.options.bit_depth):
            if frag.contains(BSI_OFFSET_ROW + i, pos):
                stored |= 1 << i
        return stored + self.options.base, True

    def clear_value(self, column: int) -> bool:
        if self.options.type != TYPE_INT:
            raise ValueError("clear_value on non-int field")
        shard, pos = shard_of(column), position(column)
        view = self.view(self.bsi_view_name())
        frag = view.fragment(shard) if view else None
        if frag is None:
            return False
        changed = frag.clear_bit(BSI_EXISTS_ROW, pos)
        for i in range(self.options.bit_depth):
            frag.clear_bit(BSI_OFFSET_ROW + i, pos)
        return changed
