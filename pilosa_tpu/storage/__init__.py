"""Storage tree: holder → index → field → view → fragment.

Same hierarchy as the reference (holder.go / index.go / field.go / view.go /
fragment.go — SURVEY.md §2 #3–#8), with the TPU twist that a fragment's
durable truth is a host roaring file + op log while its *queryable* form is
dense bit-packed rows cached in device HBM (pilosa_tpu.storage.residency).
"""

from pilosa_tpu.storage.cache import LRUCache, NoneCache, RankCache, new_row_cache
from pilosa_tpu.storage.fragment import Fragment
from pilosa_tpu.storage.view import (
    View,
    VIEW_STANDARD,
    view_name_bsi,
    views_by_time_range,
    views_for_time,
)
from pilosa_tpu.storage.field import Field, FieldOptions
from pilosa_tpu.storage.index import Index
from pilosa_tpu.storage.holder import Holder
