"""Deterministic fault injection for the internal node-to-node wire.

The control plane's partition safety (docs/OPERATIONS.md failure model)
is only as good as the failures it has been driven through. This module
is the rule engine that injects them: each rule matches ONE direction of
traffic — (source node, destination endpoint-or-name, route prefix) —
and applies an action:

- ``drop``:      raise a transport fault before any bytes leave (the
                 blackhole a network partition presents to a sender);
- ``delay``:     sleep ``delay_ms`` before the exchange (congestion,
                 a slow link);
- ``error``:     answer a synthetic HTTP status without contacting the
                 peer (a sick intermediary / dying process);
- ``duplicate``: deliver the request twice and return the second
                 response (at-least-once networks; exercises handler
                 idempotency).

A network partition is just a rule set: ``partition(a, b)`` installs
drop rules both ways, ``partition(a, b, bidirectional=False)`` only
a→b — the asymmetric case where a sees b dead while b still hears a,
exactly the shape that makes single-observer failure detectors
amputate live nodes.

The hook lives in ``parallel/connpool.py`` behind a zero-overhead-
when-off check: one module-global load + ``is None`` test per request
when no plane is installed — the shipping hot path pays nothing.
Programmable in-process (tests, ``testing/chaos.py``) and over HTTP via
``/debug/faults``. Only traffic riding the connection pool is subject
to injection (every InternalClient hop); a test driver's plain urllib
edge requests are deliberately exempt, so the observer is never
partitioned from the system under test.

Crash points reuse the PR-5 SIGKILL machinery: ``crash_point(name)``
kills the process mid-operation when the name is armed in-process
(``arm_crash_point``) or via ``PILOSA_TPU_CRASH_POINT`` in a subprocess
— the crash-recovery oracle's way of landing a kill exactly between two
control-plane steps.

DISK faults live on a second, independent plane (``install_disk``):
where the wire plane intercepts node-to-node requests, the disk plane
intercepts the storage layer's file operations at three seams —

- ``read``:  flip a bit of the bytes a fragment load / scrub pass
             reads (``flip_offset``/``flip_mask``) — silent media rot;
- ``write``: truncate a snapshot's payload mid-write
             (``truncate_to``) — a torn write / lost tail;
- ``fsync``: raise ``OSError(errno)`` (ENOSPC, EIO) from the WAL group
             fsync, a snapshot fsync, or the health probe — a full or
             dying disk.

Rules match by operation and path substring, with the same bounded
``count`` semantics as wire rules. The storage layer's off-path cost is
one module-global load + ``is None`` test per file operation (the wire
plane's contract, applied to the disk).
"""

from __future__ import annotations

import itertools
import os
import signal
import threading
import time

# The one global the connpool hot path reads. None = off: the off-path
# cost is a module-attribute load and an identity test, nothing else.
_PLANE = None

_ENV_CRASH = os.environ.get("PILOSA_TPU_CRASH_POINT", "")
_armed_crash: set[str] = set()

ACTIONS = ("drop", "delay", "error", "duplicate")


def active():
    """The installed FaultPlane, or None (the normal state)."""
    return _PLANE


def install(plane: "FaultPlane | None" = None) -> "FaultPlane":
    """Install (and return) the global fault plane."""
    global _PLANE
    _PLANE = plane if plane is not None else FaultPlane()
    return _PLANE


def clear() -> None:
    """Uninstall the global plane: the wire is clean again."""
    global _PLANE
    _PLANE = None


def arm_crash_point(name: str) -> None:
    _armed_crash.add(name)


def disarm_crash_points() -> None:
    _armed_crash.clear()


def crash_point(name: str) -> None:
    """SIGKILL this process when ``name`` is armed — the hard-kill the
    crash-recovery oracle needs BETWEEN two specific control-plane
    steps (a timer-based kill cannot land there deterministically).
    SIGKILL, not sys.exit: no finally blocks, no flushes — the same
    shape as a power cut (the PR-5 durability contract)."""
    if not _armed_crash and not _ENV_CRASH:
        return
    if name in _armed_crash or name == _ENV_CRASH:
        os.kill(os.getpid(), signal.SIGKILL)


class FaultRule:
    """One match-and-act rule. ``src`` is the sender's registered node
    name (or ``*``); ``dst`` matches the destination ``host:port``
    endpoint OR its registered name (or ``*``); ``route`` is a path
    prefix (``*`` = any). ``count`` bounds how many requests the rule
    fires on (None = unlimited); an exhausted rule stops matching but
    stays listed with its hit count."""

    _ids = itertools.count(1)

    def __init__(self, action: str, src: str = "*", dst: str = "*",
                 route: str = "*", delay_ms: float = 0.0,
                 status: int = 503, count: int | None = None,
                 body: bytes = b'{"error": "fault injected"}'):
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} (want one of {ACTIONS})"
            )
        self.id = next(FaultRule._ids)
        self.action = action
        self.src = src
        self.dst = dst
        self.route = route
        self.delay_ms = float(delay_ms)
        self.status = int(status)
        self.body = body
        self.count = count if count is None else int(count)
        self.matched = 0

    def matches(self, src: str, dst_endpoint: str, dst_name: str,
                route: str) -> bool:
        if self.count is not None and self.matched >= self.count:
            return False
        if self.src != "*" and self.src != src:
            return False
        if self.dst not in ("*", dst_endpoint, dst_name):
            return False
        if self.route != "*" and not route.startswith(self.route):
            return False
        return True

    def to_json(self) -> dict:
        return {
            "id": self.id, "action": self.action, "src": self.src,
            "dst": self.dst, "route": self.route,
            "delayMs": self.delay_ms, "status": self.status,
            "count": self.count, "matched": self.matched,
        }


class _Directive:
    """The folded effect of every matching rule on one request."""

    __slots__ = ("delay_s", "drop", "error", "duplicate")

    def __init__(self):
        self.delay_s = 0.0
        self.drop = False
        self.error: tuple[int, bytes] | None = None
        self.duplicate = False


class FaultPlane:
    """Rule registry + the per-request intercept connpool calls."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rules: list[FaultRule] = []
        # endpoint ("host:port") → node name, so rules written against
        # names (the operator's vocabulary) match wire endpoints
        self._names: dict[str, str] = {}
        self.dropped = 0
        self.delayed = 0
        self.errored = 0
        self.duplicated = 0

    # ------------------------------------------------------------- registry

    def name_endpoint(self, name: str, endpoint: str) -> None:
        with self._lock:
            self._names[endpoint] = name

    def add(self, action: str, src: str = "*", dst: str = "*",
            route: str = "*", **kw) -> FaultRule:
        rule = FaultRule(action, src=src, dst=dst, route=route, **kw)
        with self._lock:
            self.rules.append(rule)
        return rule

    def remove(self, rule_id: int) -> bool:
        with self._lock:
            before = len(self.rules)
            self.rules = [r for r in self.rules if r.id != rule_id]
            return len(self.rules) != before

    def clear_rules(self) -> None:
        with self._lock:
            self.rules = []

    def partition(self, a: str, b: str,
                  bidirectional: bool = True) -> list[FaultRule]:
        """Blackhole a→b (and b→a when bidirectional): the two nodes'
        requests to each other fail at transport, exactly like a
        network partition. Names or endpoints both work."""
        rules = [self.add("drop", src=a, dst=b)]
        if bidirectional:
            rules.append(self.add("drop", src=b, dst=a))
        return rules

    def isolate(self, node: str) -> list[FaultRule]:
        """Cut a node off entirely: nothing in, nothing out."""
        return [self.add("drop", src=node), self.add("drop", dst=node)]

    def heal(self) -> int:
        """Remove every drop rule (partitions end; other rule kinds —
        delay/error shaping — stay installed). Returns #removed."""
        with self._lock:
            keep = [r for r in self.rules if r.action != "drop"]
            removed = len(self.rules) - len(keep)
            self.rules = keep
        return removed

    # ------------------------------------------------------------ intercept

    def intercept(self, src: str, dst_endpoint: str,
                  route: str) -> _Directive | None:
        """Fold every matching rule into one directive (None = clean
        pass). Called by ConnectionPool.request for every request while
        a plane is installed; rule evaluation is O(rules) under one
        lock — this is a test/chaos surface, not a production path."""
        with self._lock:
            name = self._names.get(dst_endpoint, "")
            directive = None
            for rule in self.rules:
                if not rule.matches(src, dst_endpoint, name, route):
                    continue
                rule.matched += 1
                if directive is None:
                    directive = _Directive()
                if rule.action == "drop":
                    directive.drop = True
                    self.dropped += 1
                elif rule.action == "delay":
                    directive.delay_s += rule.delay_ms / 1000.0
                    self.delayed += 1
                elif rule.action == "error":
                    directive.error = (rule.status, rule.body)
                    self.errored += 1
                else:  # duplicate
                    directive.duplicate = True
                    self.duplicated += 1
            return directive

    def sleep(self, seconds: float) -> None:
        """Delay hook (overridable in tests for virtual time)."""
        time.sleep(seconds)

    # ---------------------------------------------------------- observability

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rules": [r.to_json() for r in self.rules],
                "names": dict(self._names),
                "dropped": self.dropped,
                "delayed": self.delayed,
                "errored": self.errored,
                "duplicated": self.duplicated,
            }


# --------------------------------------------------------------- disk plane

# The one global the storage seams read. None = off: one module-
# attribute load + identity test per file operation, nothing else.
_DISK = None

DISK_OPS = ("read", "write", "fsync")


def disk_active():
    """The installed DiskFaultPlane, or None (the normal state)."""
    return _DISK


def install_disk(plane: "DiskFaultPlane | None" = None) -> "DiskFaultPlane":
    global _DISK
    _DISK = plane if plane is not None else DiskFaultPlane()
    return _DISK


def clear_disk() -> None:
    global _DISK
    _DISK = None


def disk_check(op: str, path: str) -> None:
    """Errno-injection seam: raises OSError when an armed errno rule
    matches (op, path). The storage layer calls this immediately before
    the real syscall it models."""
    plane = _DISK
    if plane is not None:
        plane.check(op, path)


def disk_filter_read(path: str, data: bytes) -> bytes:
    """Bit-flip-on-read seam: every fragment load and scrub read passes
    its bytes through here."""
    plane = _DISK
    if plane is None:
        return data
    return plane.filter(path, data, "read")


def disk_filter_write(path: str, data: bytes) -> bytes:
    """Torn-write seam: snapshot writers pass their payload through
    here before the write syscall."""
    plane = _DISK
    if plane is None:
        return data
    return plane.filter(path, data, "write")


class DiskFaultRule:
    """One disk rule: ``op`` in DISK_OPS, ``path`` a substring match
    ("*" = any file). Exactly one effect per rule: ``errno_`` raises
    OSError (read/write/fsync), ``flip_offset`` XORs ``flip_mask`` into
    one byte (read), ``truncate_to`` drops the tail (write). ``count``
    bounds firings like wire rules."""

    _ids = itertools.count(1)

    def __init__(self, op: str, path: str = "*", errno_: int | None = None,
                 flip_offset: int | None = None, flip_mask: int = 0x01,
                 truncate_to: int | None = None, count: int | None = None):
        if op not in DISK_OPS:
            raise ValueError(
                f"unknown disk fault op {op!r} (want one of {DISK_OPS})"
            )
        if errno_ is None and flip_offset is None and truncate_to is None:
            raise ValueError(
                "disk fault rule needs errno_, flip_offset, or truncate_to"
            )
        self.id = next(DiskFaultRule._ids)
        self.op = op
        self.path = path
        self.errno_ = errno_
        self.flip_offset = flip_offset
        self.flip_mask = int(flip_mask) & 0xFF
        self.truncate_to = truncate_to
        self.count = count if count is None else int(count)
        self.matched = 0

    def matches(self, op: str, path: str) -> bool:
        if self.count is not None and self.matched >= self.count:
            return False
        if self.op != op:
            return False
        return self.path == "*" or self.path in path

    def to_json(self) -> dict:
        return {
            "id": self.id, "op": self.op, "path": self.path,
            "errno": self.errno_, "flipOffset": self.flip_offset,
            "flipMask": self.flip_mask, "truncateTo": self.truncate_to,
            "count": self.count, "matched": self.matched,
        }


class DiskFaultPlane:
    """Rule registry + the per-file-operation intercepts the storage
    seams call."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rules: list[DiskFaultRule] = []
        self.read_faults = 0
        self.write_faults = 0
        self.fsync_faults = 0

    def add(self, op: str, path: str = "*", **kw) -> DiskFaultRule:
        rule = DiskFaultRule(op, path=path, **kw)
        with self._lock:
            self.rules.append(rule)
        return rule

    def remove(self, rule_id: int) -> bool:
        with self._lock:
            before = len(self.rules)
            self.rules = [r for r in self.rules if r.id != rule_id]
            return len(self.rules) != before

    def clear_rules(self) -> None:
        with self._lock:
            self.rules = []

    def check(self, op: str, path: str) -> None:
        with self._lock:
            for rule in self.rules:
                if rule.errno_ is None or not rule.matches(op, path):
                    continue
                rule.matched += 1
                if op == "fsync":
                    self.fsync_faults += 1
                elif op == "write":
                    self.write_faults += 1
                else:
                    self.read_faults += 1
                raise OSError(
                    rule.errno_, os.strerror(rule.errno_), path
                )

    def filter(self, path: str, data: bytes, op: str) -> bytes:
        with self._lock:
            for rule in self.rules:
                if not rule.matches(op, path):
                    continue
                if op == "read" and rule.flip_offset is not None and data:
                    rule.matched += 1
                    self.read_faults += 1
                    buf = bytearray(data)
                    pos = rule.flip_offset % len(buf)
                    buf[pos] ^= rule.flip_mask or 0x01
                    data = bytes(buf)
                elif op == "write" and rule.truncate_to is not None:
                    rule.matched += 1
                    self.write_faults += 1
                    data = data[: rule.truncate_to]
            return data

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rules": [r.to_json() for r in self.rules],
                "readFaults": self.read_faults,
                "writeFaults": self.write_faults,
                "fsyncFaults": self.fsync_faults,
            }
