"""Deterministic chaos harness: randomized partition/heal/kill/churn
schedules against a real in-process cluster under a mixed read+write
workload, gated on the four partition-safety oracles
(docs/OPERATIONS.md failure model):

1. **Zero lost acked writes** — every Set() a client saw acknowledged
   (HTTP 200, changed=true) is queryable cluster-wide after heal.
2. **No fragment deleted by a non-quorum node** — every
   ``cleanup_unowned`` decision is logged with its quorum verdict;
   any removal without quorum is an oracle failure.
3. **At most one coordinator acting per epoch** — every coordinated
   action (declare-dead, resize) records (epoch, node); two actors in
   one epoch means fencing failed.
4. **Byte-identical replicas after heal** — the PR-4 sync oracle: once
   converged, every owner of a fragment holds the same serialized
   bytes.

Schedules are seeded (``random.Random(seed)``) so a failing run
replays. Partitions are injected on the internal wire only
(testing/faults.py through the connection pool); the workload's edge
requests ride plain urllib, so the observer is never partitioned from
the nodes — a write acked through a reachable node counts even when
that node is about to be cut off.

Used by ``bench_suite.py config_chaos`` (the ≥20-schedule gate recorded
in BENCH_SUITE.json) and the ``slow`` soak in tests/test_partition.py.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.error
import urllib.request

from pilosa_tpu.shardwidth import SHARD_WIDTH
from pilosa_tpu.testing import faults

N_ROWS = 4
INDEX = "chaos"
FIELD = "f"


def _post(base: str, path: str, data: bytes,
          content_type: str = "application/json", timeout: float = 10.0):
    r = urllib.request.Request(f"{base}{path}", data=data, method="POST")
    r.add_header("Content-Type", content_type)
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


class ChaosHarness:
    """One cluster + one seeded schedule of fault events under load."""

    def __init__(self, tmp_dir, n_nodes: int = 3, replica_n: int = 2,
                 seed: int = 0, n_events: int = 6,
                 event_gap_s: float = 0.3, writer_threads: int = 2,
                 reader_threads: int = 1, n_shards: int = 4,
                 with_storage_faults: bool = False,
                 with_autopilot: bool = False,
                 with_cdc: bool = False,
                 with_elastic: bool = False,
                 log=lambda msg: None):
        self.tmp_dir = str(tmp_dir)
        self.n_nodes = n_nodes
        self.replica_n = replica_n
        self.rng = random.Random(seed)
        self.n_events = n_events
        self.event_gap_s = event_gap_s
        self.writer_threads = writer_threads
        self.reader_threads = reader_threads
        self.n_shards = n_shards
        # storage-fault schedules (ISSUE 10): bit-flip a live replica's
        # fragment file on disk, ENOSPC one node's fsync path — gated
        # on the integrity oracle (every fragment's disk bytes verify
        # clean after heal, on top of the four partition oracles)
        self.with_storage_faults = with_storage_faults
        # autopilot-active schedules (ISSUE 15): every node runs the
        # placement-plane ticker on a hot interval, plus a forced-pass
        # event in the bag — the five oracles must hold while the
        # autopilot mints overrides and resizes UNDER the same faults
        self.with_autopilot = with_autopilot
        # CDC mirror schedules (ISSUE 16): an out-of-cluster follower
        # tails n0's WAL feed into its own holder for the whole
        # schedule — kills, restarts and partitions included — gated on
        # the byte-identical mirror oracle (everything n0 holds after
        # heal is byte-identical in the mirror once its cursor passes
        # n0's durable seq)
        self.with_cdc = with_cdc
        # elastic-drain schedules (ISSUE 17): the bag gains a graceful
        # drain of a random member, and kills/partitions then land MID-
        # DRAIN — all six oracles must hold while shard groups move off
        # the target, its CDC cursors hand off, and it leaves the ring;
        # the finale aborts whatever drain is still in flight, retires
        # nodes that departed, and restarts them as fresh joiners
        self.with_elastic = with_elastic
        self.drains_started = 0
        self.cdc_mirror = None
        self.cdc_mirror_holder = None
        self.autopilot_moves = 0
        self.disk_plane = None
        self.corruptions_injected = 0
        self.disk_fault_rules: list[int] = []
        self.log = log
        self.servers: dict[str, object] = {}   # name -> live Server
        self.downed: dict[str, int] = {}       # name -> port to rebind
        self.plane = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # acked-write ledger: (row, col) the workload saw acknowledged
        self.acked: set[tuple[int, int]] = set()
        self.write_errors = 0
        self.writes_acked = 0
        self.events: list[str] = []
        # harvested across restarts (a closed Server's cluster object
        # would otherwise take its logs with it)
        self.all_acted: list[tuple[int, str, str]] = []  # (epoch, node, act)
        self.all_cleanups: list[dict] = []

    # ------------------------------------------------------------- lifecycle

    def _make_server(self, name: str, seeds: list[str], port: int = 0):
        from pilosa_tpu.server import Server, ServerConfig

        autopilot_cfg = dict(
            # hot enough that the ticker fires between events; the
            # tight 1.2 budget makes even mild skew actionable, so
            # schedules actually exercise placement moves under faults
            autopilot_enabled=True, autopilot_interval=0.5,
            autopilot_heat_budget=1.2, autopilot_min_dwell=1.0,
        ) if self.with_autopilot else {}
        server = Server(ServerConfig(
            data_dir=f"{self.tmp_dir}/{name}", port=port, name=name,
            replica_n=self.replica_n, seeds=seeds,
            anti_entropy_interval=0, heartbeat_interval=0,
            heartbeat_timeout=0.5, use_mesh=False, **autopilot_cfg,
        )).open()
        cluster = server.api.cluster
        # instance-attr overrides: fast backoffs + short drains so the
        # schedule's wall time is events, not timeouts
        cluster.SEND_BACKOFF_S = 0.01
        cluster.CLEANUP_DRAIN_TIMEOUT = 2.0
        cluster.RESIZE_COMPLETE_TIMEOUT = 10.0
        if self.with_storage_faults:
            # fast degraded-mode recovery so ENOSPC events heal within
            # the schedule's gaps, not its lifetime
            server.holder.health.PROBE_INTERVAL_S = 0.2
        return server

    def boot(self) -> "ChaosHarness":
        self.plane = faults.install()
        if self.with_storage_faults:
            self.disk_plane = faults.install_disk()
        for i in range(self.n_nodes):
            name = f"n{i}"
            seeds = ([self._uri(next(iter(self.servers.values())))]
                     if self.servers else [])
            self.servers[name] = self._make_server(name, seeds)
        for s in self.servers.values():
            s.api.cluster.wait_until_normal(30)
        base = self._uri(self.servers["n0"])
        _post(base, f"/index/{INDEX}", b"{}")
        _post(base, f"/index/{INDEX}/field/{FIELD}", b"{}")
        if self.with_cdc:
            self._start_cdc_mirror()
        return self

    def _start_cdc_mirror(self) -> None:
        """Boot the CDC mirror: a follower outside the cluster tailing
        n0's feed into its own holder. Its InternalClient carries no
        node identity (``fault_source`` stays ``""``), so the named
        partition rules the schedule installs never match it — like the
        urllib workload, the observer is not partitioned from the
        system under test. n0 kills reset the seq space mid-schedule;
        the follower answers the resulting FeedGone (unknown-cursor
        410) with a merge resync, which converges because the chaos
        workload is add-only and kills are graceful closes (the durable
        WAL state survives)."""
        import types

        from pilosa_tpu.cdc.tailer import CdcFollower
        from pilosa_tpu.parallel.client import InternalClient
        from pilosa_tpu.storage import Holder

        self.cdc_mirror_holder = Holder(
            f"{self.tmp_dir}/cdc_mirror").open()
        self.cdc_mirror = CdcFollower(
            types.SimpleNamespace(holder=self.cdc_mirror_holder),
            InternalClient(timeout=10.0),
            self._uri(self.servers["n0"]),
            poll_interval=0.05, cursor_name="chaos-mirror",
        )
        self.cdc_mirror.start()

    def close(self) -> None:
        self._stop.set()
        if self.cdc_mirror is not None:
            self.cdc_mirror.stop()
            self.cdc_mirror = None
        if self.cdc_mirror_holder is not None:
            try:
                self.cdc_mirror_holder.close()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
            self.cdc_mirror_holder = None
        with self._lock:
            servers = list(self.servers.values())
            self.servers = {}
        for s in servers:
            self._harvest(s)
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        faults.clear()
        faults.clear_disk()

    @staticmethod
    def _uri(server) -> str:
        return f"http://localhost:{server.port}"

    def _harvest(self, server) -> None:
        cluster = server.api.cluster
        name = cluster.local.id
        self.all_acted.extend(
            (epoch, name, action) for epoch, action in cluster.acted_epochs
        )
        self.all_cleanups.extend(cluster.cleanup_log)
        cluster.acted_epochs.clear()
        cluster.cleanup_log.clear()
        pilot = getattr(server.api, "autopilot", None)
        if pilot is not None:
            # zero after read: kills, oracle checks, and close() all
            # harvest the same server — a counter read twice would
            # double-count the schedule's move total
            self.autopilot_moves += pilot.moves_executed
            pilot.moves_executed = 0

    def _live(self) -> list:
        with self._lock:
            return list(self.servers.values())

    # -------------------------------------------------------------- workload

    def _writer(self, t: int) -> None:
        i = 0
        while not self._stop.is_set():
            servers = self._live()
            if not servers:
                time.sleep(0.05)
                continue
            server = self.rng.choice(servers)
            shard = i % self.n_shards
            pos = t * 100_000 + (i // self.n_shards)
            col = shard * SHARD_WIDTH + pos
            row = 1 + (i % N_ROWS)
            i += 1
            try:
                out = _post(self._uri(server), f"/index/{INDEX}/query",
                            f"Set({col}, {FIELD}={row})".encode(),
                            content_type="text/plain", timeout=5.0)
            except Exception:  # noqa: BLE001 — shed/refused/timeout:
                # unacked, so the ledger owes nothing for it
                self.write_errors += 1
                continue
            if out.get("results") == [True]:
                with self._lock:
                    self.acked.add((row, col))
                    self.writes_acked += 1
            time.sleep(0.01)

    def _reader(self) -> None:
        while not self._stop.is_set():
            servers = self._live()
            if servers:
                try:
                    _post(self._uri(self.rng.choice(servers)),
                          f"/index/{INDEX}/query",
                          f"Count(Row({FIELD}=1))".encode(),
                          content_type="text/plain", timeout=5.0)
                except Exception:  # noqa: BLE001 — reads may 503 on a
                    pass           # degraded minority; that IS the design
            time.sleep(0.02)

    # --------------------------------------------------------------- events

    def _heartbeat_round(self) -> None:
        for s in self._live():
            try:
                s.api.cluster.heartbeat()
                # chaos servers run heartbeat_interval=0 (the harness IS
                # the ticker), so drain resumption after a coordinator
                # kill rides this round exactly as the server tick would
                if s.api.elastic is not None:
                    s.api.elastic.maybe_resume()
            except Exception:  # noqa: BLE001 — a heartbeat pass racing
                pass           # a concurrent kill must not abort the run

    def _event_partition(self) -> str:
        self.plane.heal()
        names = sorted(self.servers) + sorted(self.downed)
        self.rng.shuffle(names)
        cut = self.rng.randrange(1, len(names))
        side_a, side_b = names[:cut], names[cut:]
        symmetric = self.rng.random() < 0.6
        for a in side_a:
            for b in side_b:
                self.plane.partition(a, b, bidirectional=symmetric)
        kind = "sym" if symmetric else "asym"
        return f"partition[{kind}] {side_a}|{side_b}"

    def _event_heal(self) -> str:
        self.plane.heal()
        return "heal"

    def _event_kill(self) -> str:
        with self._lock:
            if len(self.servers) < 3:
                return "kill-skipped"  # keep ≥2 alive for the workload
            name = self.rng.choice(sorted(self.servers))
            server = self.servers.pop(name)
        self._harvest(server)
        # remember the PORT: a restarted node comes back on its old
        # advertised address, like a real deployment — peers' member
        # lists and forgotten-peer registries hold URIs, and a node
        # that silently moves ports is undiscoverable by either
        self.downed[name] = server.port
        server.close()
        return f"kill {name}"

    def _event_corrupt(self) -> str:
        """Bit-flip one byte of a random live snapshotted fragment ON
        DISK — silent media rot. The live bitmap stays healthy (that is
        the point: replicas hold every acked write), and the scrub
        passes in converge must detect, quarantine, and read-repair it;
        the integrity oracle then proves the disk verifies clean."""
        candidates = []
        for server in self._live():
            for idx in server.holder.indexes.values():
                for field in idx.fields.values():
                    for view in field.views.values():
                        for frag in view.fragments.values():
                            # select by LIVE content: in group mode the
                            # file is a bare header until the snapshot
                            # below materializes it
                            if frag.count() > 0:
                                candidates.append((server, frag))
        if not candidates:
            return "corrupt-skipped"
        server, frag = self.rng.choice(candidates)
        # ensure file+sidecar describe real content, then flip a byte
        # of the snapshot payload (past the 20-byte header)
        try:
            frag.snapshot()
            size = os.path.getsize(frag.path)
            if size <= 20:
                return "corrupt-skipped"
            offset = self.rng.randrange(20, size)
            with open(frag.path, "r+b") as f:
                f.seek(offset)
                byte = f.read(1)
                f.seek(offset)
                f.write(bytes([byte[0] ^ (1 << self.rng.randrange(8))]))
        except OSError:
            return "corrupt-skipped"
        self.corruptions_injected += 1
        return (f"corrupt {server.config.name}:"
                f"{frag.index}/{frag.field}/{frag.view}/{frag.shard}"
                f"@{offset}")

    def _event_disk_full(self) -> str:
        """ENOSPC on one node's fsync path: its writes shed 503 and the
        node flips storage-degraded until the heal event (or finale)
        removes the rule and the probe clears the latch."""
        if self.disk_plane is None:
            return "disk-full-skipped"
        names = sorted(self.servers)
        if not names:
            return "disk-full-skipped"
        name = self.rng.choice(names)
        import errno as _errno

        rule = self.disk_plane.add(
            "fsync", path=f"{self.tmp_dir}/{name}/",
            errno_=_errno.ENOSPC,
        )
        self.disk_fault_rules.append(rule.id)
        return f"disk-full {name}"

    def _heal_disk(self) -> int:
        if self.disk_plane is None:
            return 0
        removed = 0
        for rule_id in self.disk_fault_rules:
            removed += bool(self.disk_plane.remove(rule_id))
        self.disk_fault_rules = []
        return removed

    def _event_restart(self) -> str:
        if not self.downed:
            return "restart-skipped"
        name = self.rng.choice(sorted(self.downed))
        port = self.downed.pop(name)
        live = self._live()
        seeds = [self._uri(live[0])] if live else []
        server = self._make_server(name, seeds, port=port)
        with self._lock:
            self.servers[name] = server
        return f"restart {name}"

    def _event_autopilot_pass(self) -> str:
        """Force a planner pass NOW on the acting coordinator — the
        0.5s tickers run too, but a bag event guarantees the schedule
        exercises plan/apply/resize at adversarial moments (right
        after a kill, inside a partition) instead of between them."""
        for s in self._live():
            if s.api.cluster.is_acting_coordinator:
                pilot = s.api.autopilot
                if pilot is None:
                    return "autopilot-skipped (pilot not wired)"
                try:
                    record = pilot.run_pass()
                except Exception as e:  # noqa: BLE001 — an event must
                    return f"autopilot-error {e!r}"  # not kill the run
                if record.get("acted"):
                    return (f"autopilot-pass {s.config.name} "
                            f"moves={len(record.get('moves', []))}")
                return (f"autopilot-pass {s.config.name} "
                        f"skip={record.get('reason')}")
        return "autopilot-skipped (no live coordinator)"

    def _event_drain(self) -> str:
        """Start a graceful drain of a random member through the acting
        coordinator — subsequent bag events (kills, partitions, more
        heartbeats) then land mid-drain, which is the point. Victims
        exclude the coordinator (it drives the move) and, under
        with_cdc, n0 (the mirror oracle compares against n0's holder).
        Refusals (drain already in flight, degraded, too few nodes) are
        the elastic plane's guardrails working; they log and move on."""
        live = self._live()
        if len(live) < 3:
            return "drain-skipped (<3 live)"
        coord = next((s for s in live
                      if s.api.cluster.is_acting_coordinator), None)
        if coord is None:
            return "drain-skipped (no live coordinator)"
        victims = sorted(
            s.config.name for s in live
            if s.config.name != coord.config.name
            and not (self.with_cdc and s.config.name == "n0")
        )
        if not victims:
            return "drain-skipped (no eligible victim)"
        victim = self.rng.choice(victims)
        try:
            coord.api.elastic.start_drain(victim)
        except Exception as e:  # noqa: BLE001 — guardrail refusals
            return f"drain-refused {e}"
        self.drains_started += 1
        return f"drain {victim} (via {coord.config.name})"

    def _settle_drains(self) -> None:
        """Finale, step one: no drain may still be mutating placement
        while the finale rebuilds full membership. Abort the active
        record on the acting coordinator, then wait out every worker
        thread (an abort is only observed at the worker's next state
        advance)."""
        for s in self._live():
            c = s.api.cluster
            if (c.is_acting_coordinator
                    and getattr(c, "drain_active", False)):
                try:
                    s.api.elastic.abort_drain()
                    self.log("  finale: drain-abort "
                             f"{c.drain_record.get('target')}")
                except Exception:  # noqa: BLE001 — already terminal
                    pass
                break
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            busy = [s for s in self._live()
                    if s.api.elastic is not None
                    and getattr(s.api.elastic, "_thread", None) is not None
                    and s.api.elastic._thread.is_alive()]
            if not busy:
                return
            time.sleep(0.1)

    def _retire_departed(self) -> None:
        """Finale, step two: a drained target LEFT the ring (its
        ``_left`` latch refuses auto-rejoin), but its server object is
        still running read-only. Retire it like a kill — harvest,
        remember the port, close — so the restart loop below brings it
        back as a fresh joiner and convergence reaches full membership."""
        with self._lock:
            departed = [name for name, s in self.servers.items()
                        if getattr(s.api.cluster, "_left", False)]
            retired = {name: self.servers.pop(name) for name in departed}
        for name, server in retired.items():
            self._harvest(server)
            self.downed[name] = server.port
            server.close()
            self.log(f"  finale: retire-departed {name}")

    def run_schedule(self) -> dict:
        """Workload on, randomized events, then heal + converge and
        check every oracle. Returns the schedule's record."""
        threads = [
            threading.Thread(target=self._writer, args=(t,), daemon=True)
            for t in range(self.writer_threads)
        ] + [
            threading.Thread(target=self._reader, daemon=True)
            for _ in range(self.reader_threads)
        ]
        for t in threads:
            t.start()
        choices = [
            (self._event_partition, 4), (self._event_heal, 2),
            (self._event_kill, 2), (self._event_restart, 2),
        ]
        if self.with_storage_faults:
            choices += [(self._event_corrupt, 3),
                        (self._event_disk_full, 2)]
        if self.with_autopilot:
            choices += [(self._event_autopilot_pass, 3)]
        if self.with_elastic:
            choices += [(self._event_drain, 3)]
        bag = [fn for fn, w in choices for _ in range(w)]
        t0 = time.monotonic()
        for _ in range(self.n_events):
            event = self.rng.choice(bag)()
            self.events.append(event)
            self.log(f"  event: {event}")
            # liveness passes between events: detection, death
            # declaring, degradation flips all ride heartbeats
            for _ in range(2):
                time.sleep(self.event_gap_s / 2)
                self._heartbeat_round()
        # end of schedule: stop faults, bring everything back, converge
        self._stop.set()
        for t in threads:
            t.join(timeout=10)
        self.plane.heal()
        self._heal_disk()
        if self.with_elastic:
            self._settle_drains()
            self._retire_departed()
        while self.downed:
            self.log(f"  finale: {self._event_restart()}")
        converged = self._converge(deadline_s=60)
        record = self._check_oracles()
        record.update({
            "events": list(self.events),
            "drains": self.drains_started,
            "converged": converged,
            "converge_diag": getattr(self, "converge_diag", None),
            "acked_writes": len(self.acked),
            "write_errors": self.write_errors,
            "wall_s": round(time.monotonic() - t0, 2),
        })
        return record

    # ----------------------------------------------------------- convergence

    def _converge(self, deadline_s: float = 90.0) -> bool:
        full = {f"n{i}" for i in range(self.n_nodes)}
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            self._heartbeat_round()
            self._heartbeat_round()  # suspect→dead/rejoin need streaks
            servers = self._live()
            # drain any pending/background resizes through the acting
            # coordinator's serialized resize lock
            for s in servers:
                if s.api.cluster.is_acting_coordinator:
                    try:
                        s.api.cluster.coordinate_resize()
                    except Exception:  # noqa: BLE001
                        pass
                    break
            ok = all(
                set(s.api.cluster.nodes) == full
                and s.api.cluster.wait_until_normal(5)
                and not s.api.cluster.degraded
                for s in servers
            ) and len(servers) == self.n_nodes
            if ok:
                break
            time.sleep(0.2)
        else:
            # capture WHY for the bench record — unconverged runs are
            # otherwise undebuggable after the fact
            self.converge_diag = {
                s.config.name: {
                    "members": sorted(s.api.cluster.nodes),
                    "state": s.api.cluster.state,
                    "degraded": s.api.cluster.degraded,
                    "epoch": s.api.cluster.epoch,
                } for s in self._live()
            }
            return False
        # repair passes until quiescent (bounded): every node pulls the
        # blocks it is missing from its replicas. With storage faults
        # on, each round leads with a scrub pass — injected rot must be
        # detected/quarantined BEFORE sync (quarantine-then-sync is the
        # read-repair; syncing a corrupt-on-disk fragment first would
        # never surface it)
        for _ in range(4):
            repaired = 0
            if self.with_storage_faults:
                # any still-degraded node blocks its own repair writes:
                # wait out the probe first
                for s in self._live():
                    deadline2 = time.monotonic() + 5
                    while (s.holder.health.degraded
                           and time.monotonic() < deadline2):
                        time.sleep(0.1)
                for s in self._live():
                    try:
                        repaired += s.api.scrub_now()["corrupt"]
                    except Exception:  # noqa: BLE001
                        repaired += 1
            for s in self._live():
                try:
                    repaired += s.api.cluster.sync_holder()["bits"]
                except Exception:  # noqa: BLE001
                    repaired += 1  # retry next round
            if repaired == 0:
                break
        return True

    # -------------------------------------------------------------- oracles

    def _check_oracles(self) -> dict:
        for s in self._live():
            self._harvest(s)
        lost = self._oracle_lost_writes()
        non_quorum_deletions = [
            e for e in self.all_cleanups
            if e.get("removed") and not e.get("quorum")
        ]
        actors_by_epoch: dict[int, set[str]] = {}
        for epoch, name, _action in self.all_acted:
            actors_by_epoch.setdefault(epoch, set()).add(name)
        conflicts = {e: sorted(a) for e, a in actors_by_epoch.items()
                     if len(a) > 1}
        mismatches = self._oracle_replica_identity()
        cdc_mismatches = (self._oracle_cdc_mirror()
                          if self.with_cdc else [])
        dirty_disk = (self._oracle_disk_integrity()
                      if self.with_storage_faults else [])
        degraded_stuck = [
            s.config.name for s in self._live()
            if self.with_storage_faults and s.holder.health.degraded
        ]
        return {
            "lost_acked_writes": len(lost),
            "lost_sample": sorted(lost)[:5],
            "non_quorum_deletions": len(non_quorum_deletions),
            "coordinator_conflicts": conflicts,
            "replica_mismatches": mismatches,
            "corruptions_injected": self.corruptions_injected,
            "disk_integrity_failures": dirty_disk,
            "degraded_stuck": degraded_stuck,
            "autopilot_moves": self.autopilot_moves,
            "cdc_mirror_mismatches": cdc_mismatches,
            "cdc_resyncs": (self.cdc_mirror.resyncs_total
                            if self.cdc_mirror is not None else 0),
            "cdc_applied_ops": (self.cdc_mirror.applied_ops_total
                                if self.cdc_mirror is not None else 0),
            "epochs_acted": len(actors_by_epoch),
            "ok": (not lost and not non_quorum_deletions
                   and not conflicts and not mismatches
                   and not dirty_disk and not degraded_stuck
                   and not cdc_mismatches),
        }

    def _oracle_cdc_mirror(self) -> list:
        """The CDC mirror oracle (ISSUE 16): after heal + converge, the
        out-of-cluster follower tailing n0 holds a byte-identical copy
        of every non-empty fragment n0 holds. Sound because EVERY write
        into n0's fragments — client Sets and anti-entropy repair alike
        — rides ``add_ids`` into the WAL, so it reached the mirror in
        the bulk sync or through the feed; waiting for the mirror's
        cursor to pass n0's durable seq turns the comparison into a
        barrier instead of a race. Mirror-⊇-n0, not equality: ownership
        churn can leave the mirror holding tombstoned leftovers whose
        delete fell in a resync window, which is the documented merge-
        resync semantics, not divergence."""
        n0 = self.servers.get("n0")
        if n0 is None or self.cdc_mirror is None:
            return ["n0 or mirror not live at oracle time"]
        wal = n0.holder.wal
        wal.barrier()
        durable = wal.durable_seq()
        # compare-until-deadline, not wait-then-compare: right after an
        # n0 restart the mirror can still carry a cursor from the OLD
        # seq space (numerically past the fresh durable) with its
        # unknown-cursor 410 resync in flight — a single cursor check
        # would green-light a comparison against a mid-resync mirror.
        # Nothing writes n0 after convergence, so a passing comparison
        # is stable; a persistent mismatch still fails loudly.
        deadline = time.monotonic() + 30.0
        mismatches = ["mirror never caught up for a comparison"]
        while time.monotonic() < deadline:
            since = self.cdc_mirror._since
            if since is None or since < durable:
                time.sleep(0.1)
                continue
            mismatches = self._cdc_mirror_diff(n0)
            if not mismatches:
                return []
            time.sleep(0.2)
        return mismatches

    def _cdc_mirror_diff(self, n0) -> list:
        mirror = self.cdc_mirror_holder
        mismatches = []
        for iname, idx in n0.holder.indexes.items():
            for fname, field in idx.fields.items():
                for vname, view in field.views.items():
                    for shard, frag in list(view.fragments.items()):
                        if not frag.count():
                            continue
                        midx = mirror.index(iname)
                        mf = midx.field(fname) if midx else None
                        mv = mf.view(vname) if mf else None
                        mfrag = mv.fragment(shard) if mv else None
                        if (mfrag is None
                                or mfrag.serialize_snapshot()
                                != frag.serialize_snapshot()):
                            mismatches.append(
                                f"{iname}/{fname}/{vname}/{shard}")
        return mismatches

    def _oracle_disk_integrity(self) -> list:
        """The corruption oracle (ISSUE 10): after heal + scrub, every
        fragment's BYTES ON DISK decode cleanly and match their
        checksum sidecar — injected rot was detected, quarantined, and
        repaired (or rewritten), never left to be served or replicated.
        Returns the list of still-dirty fragment paths."""
        from pilosa_tpu.storage import integrity

        dirty = []
        for server in self._live():
            for idx in server.holder.indexes.values():
                for field in idx.fields.values():
                    for view in field.views.values():
                        for frag in list(view.fragments.values()):
                            try:
                                integrity.verify_fragment_file(frag.path)
                            except integrity.CorruptFragmentError as e:
                                dirty.append(str(e))
                            except OSError:
                                continue
        return dirty

    def _oracle_lost_writes(self) -> set:
        """Every acked (row, col) must be queryable cluster-wide."""
        with self._lock:
            acked = set(self.acked)
        if not acked:
            return set()
        servers = self._live()
        missing = set(acked)
        for attempt in range(3):
            got: set[tuple[int, int]] = set()
            probe = servers[attempt % len(servers)]
            for row in range(1, N_ROWS + 1):
                try:
                    out = _post(self._uri(probe), f"/index/{INDEX}/query",
                                f"Row({FIELD}={row})".encode(),
                                content_type="text/plain", timeout=30.0)
                except Exception:  # noqa: BLE001
                    continue
                got.update((row, c) for c in
                           out.get("results", [{}])[0].get("columns", []))
            missing = acked - got
            if not missing:
                return set()
            # not yet converged: another repair round, then re-ask
            for s in servers:
                try:
                    s.api.cluster.sync_holder()
                except Exception:  # noqa: BLE001
                    pass
        return missing

    def _oracle_replica_identity(self) -> list:
        """Post-heal, every owner of a fragment holds byte-identical
        data (the PR-4 sync oracle); an owner missing a fragment other
        owners hold non-empty is a mismatch too."""
        servers = self._live()
        keys: set[tuple[str, str, str, int]] = set()
        for s in servers:
            for iname, idx in s.holder.indexes.items():
                for fname, field in idx.fields.items():
                    for vname, view in field.views.items():
                        for shard in view.fragments:
                            keys.add((iname, fname, vname, shard))
        mismatches = []
        for iname, fname, vname, shard in sorted(keys):
            owners = [s for s in servers
                      if s.api.cluster.owns_shard(iname, shard)]
            payloads = {}
            for s in owners:
                idx = s.holder.index(iname)
                field = idx.field(fname) if idx else None
                view = field.view(vname) if field else None
                frag = view.fragment(shard) if view else None
                payloads[s.config.name] = (
                    frag.serialize_snapshot()
                    if frag is not None and frag.count() else b""
                )
            distinct = set(payloads.values())
            if len(distinct) > 1:
                mismatches.append({
                    "fragment": f"{iname}/{fname}/{vname}/{shard}",
                    "holders": {k: len(v) for k, v in payloads.items()},
                })
        return mismatches


class MpServingChaos:
    """Kill-a-worker schedule for the multi-process serving tier
    (ISSUE 11): one device-owner + N ``SO_REUSEPORT`` workers under a
    mixed read+write load; the schedule SIGKILLs random workers
    mid-burst. Two oracles gate it:

    1. **Zero lost acked writes** — every Set() a client saw 200-acked
       through ANY worker is queryable afterwards (the WAL ACK barrier
       crossed the ring; a worker death must not un-happen it).
    2. **Owner never wedges** — after every kill the owner still
       answers a probe query within a bounded deadline (dead workers'
       in-flight ring slots were reclaimed, nothing blocks the drain
       loops) and the worker fleet respawns back to N.
    """

    PROBE_DEADLINE_S = 10.0
    RESPAWN_DEADLINE_S = 30.0

    def __init__(self, tmp_dir, n_workers: int = 2, seed: int = 0,
                 n_kills: int = 3, kill_gap_s: float = 0.8,
                 writer_threads: int = 3, reader_threads: int = 2,
                 log=lambda msg: None):
        self.tmp_dir = str(tmp_dir)
        self.n_workers = n_workers
        self.rng = random.Random(seed)
        self.n_kills = n_kills
        self.kill_gap_s = kill_gap_s
        self.writer_threads = writer_threads
        self.reader_threads = reader_threads
        self.log = log
        self.server = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.acked: set[tuple[int, int]] = set()
        self.write_errors = 0
        self.events: list[str] = []
        self.wedges: list[str] = []

    def boot(self) -> "MpServingChaos":
        import socket as _socket

        from pilosa_tpu.server import Server, ServerConfig

        if not hasattr(_socket, "SO_REUSEPORT"):
            raise RuntimeError("SO_REUSEPORT unavailable")
        self.server = Server(ServerConfig(
            data_dir=self.tmp_dir, port=0, name="mpchaos",
            serving_workers=self.n_workers, anti_entropy_interval=0,
            heartbeat_interval=0, use_mesh=False,
        )).open()
        if self.server._mpserve is None:
            raise RuntimeError("multi-process serving did not start")
        base = f"http://localhost:{self.server.port}"
        _post(base, f"/index/{INDEX}", b"{}")
        _post(base, f"/index/{INDEX}/field/{FIELD}", b"{}")
        return self

    def close(self) -> None:
        self._stop.set()
        if self.server is not None:
            self.server.close()

    # -------------------------------------------------------------- workload

    def _public(self) -> str:
        return f"http://localhost:{self.server.port}"

    def _owner(self) -> str:
        return f"http://127.0.0.1:{self.server._mpserve.owner_port}"

    def _writer(self, t: int) -> None:
        i = 0
        while not self._stop.is_set():
            shard = i % 2
            pos = t * 100_000 + (i // 2)
            col = shard * SHARD_WIDTH + pos
            row = 1 + (i % N_ROWS)
            i += 1
            try:
                out = _post(self._public(), f"/index/{INDEX}/query",
                            f"Set({col}, {FIELD}={row})".encode(),
                            content_type="text/plain", timeout=5.0)
            except Exception:  # noqa: BLE001 — a kill mid-request:
                self.write_errors += 1  # unacked, the ledger owes nothing
                continue
            if out.get("results") == [True]:
                with self._lock:
                    self.acked.add((row, col))
            time.sleep(0.005)

    def _reader(self) -> None:
        while not self._stop.is_set():
            try:
                _post(self._public(), f"/index/{INDEX}/query",
                      f"Count(Row({FIELD}=1))".encode(),
                      content_type="text/plain", timeout=5.0)
            except Exception:  # noqa: BLE001 — resets from dying
                pass           # workers are expected mid-kill
            time.sleep(0.01)

    # --------------------------------------------------------------- oracle

    def _probe_owner(self) -> bool:
        """Owner-never-wedges, half 1: a probe query through the
        owner's own listener answers within the deadline."""
        deadline = time.monotonic() + self.PROBE_DEADLINE_S
        while time.monotonic() < deadline:
            try:
                out = _post(self._owner(), f"/index/{INDEX}/query",
                            f"Count(Row({FIELD}=1))".encode(),
                            content_type="text/plain", timeout=5.0)
                if "results" in out:
                    return True
            except Exception:  # noqa: BLE001
                time.sleep(0.1)
        return False

    def _kill_one_worker(self) -> str:
        mp = self.server._mpserve
        pids = [w["pid"] for w in mp.workers_json()
                if w["alive"] and w["pid"]]
        if not pids:
            return "kill-skipped"
        pid = self.rng.choice(pids)
        try:
            os.kill(pid, 9)
        except ProcessLookupError:
            return "kill-raced"
        return f"kill-worker pid={pid}"

    def run_schedule(self) -> dict:
        mp = self.server._mpserve
        threads = [
            threading.Thread(target=self._writer, args=(t,), daemon=True)
            for t in range(self.writer_threads)
        ] + [
            threading.Thread(target=self._reader, daemon=True)
            for _ in range(self.reader_threads)
        ]
        for t in threads:
            t.start()
        t0 = time.monotonic()
        time.sleep(self.kill_gap_s)  # let the burst establish
        for _ in range(self.n_kills):
            event = self._kill_one_worker()
            self.events.append(event)
            self.log(f"  event: {event}")
            if not self._probe_owner():
                self.wedges.append(f"owner probe timed out after {event}")
            if not mp.wait_workers(self.n_workers,
                                   timeout=self.RESPAWN_DEADLINE_S):
                self.wedges.append(f"fleet never respawned after {event}")
            time.sleep(self.kill_gap_s)
        self._stop.set()
        for t in threads:
            t.join(timeout=10)
        # final owner-never-wedges check, then the acked-write oracle
        # against the owner's authoritative listener
        if not self._probe_owner():
            self.wedges.append("owner probe timed out at finale")
        with self._lock:
            acked = set(self.acked)
        missing = set(acked)
        for _ in range(3):
            got: set[tuple[int, int]] = set()
            for row in range(1, N_ROWS + 1):
                try:
                    out = _post(self._owner(), f"/index/{INDEX}/query",
                                f"Row({FIELD}={row})".encode(),
                                content_type="text/plain", timeout=30.0)
                except Exception:  # noqa: BLE001
                    continue
                got.update((row, c) for c in
                           out.get("results", [{}])[0].get("columns", []))
            missing = acked - got
            if not missing:
                break
            time.sleep(0.2)
        m = mp.metrics()
        return {
            "events": list(self.events),
            "acked_writes": len(acked),
            "write_errors": self.write_errors,
            "lost_acked_writes": len(missing),
            "lost_sample": sorted(missing)[:5],
            "owner_wedges": list(self.wedges),
            "respawns": m["serving_worker_respawns_total"],
            "dropped_inflight": sum(w["droppedInflight"]
                                    for w in mp.workers_json()),
            "wall_s": round(time.monotonic() - t0, 2),
            "ok": not missing and not self.wedges,
        }


def run_mp_chaos(tmp_dir, n_schedules: int = 2, n_workers: int = 2,
                 seed: int = 0, n_kills: int = 3,
                 log=lambda msg: None) -> dict:
    """Run ``n_schedules`` independent kill-a-worker schedules (fresh
    server each) and fold the two mp-serving oracles; part of the
    default chaos config (bench_suite config_chaos) and the
    ``mp_serving`` gate."""
    records = []
    for i in range(n_schedules):
        schedule_seed = seed * 1000 + i
        log(f"mp chaos schedule {i + 1}/{n_schedules} "
            f"(seed {schedule_seed})")
        harness = MpServingChaos(
            f"{tmp_dir}/mpsched{i}", n_workers=n_workers,
            seed=schedule_seed, n_kills=n_kills, log=log,
        )
        try:
            harness.boot()
            record = harness.run_schedule()
        finally:
            harness.close()
        record["seed"] = schedule_seed
        records.append(record)
        log(f"  -> ok={record['ok']} acked={record['acked_writes']} "
            f"kills={len(record['events'])} wall={record['wall_s']}s")
    failed = [r for r in records if not r["ok"]]
    return {
        "schedules": n_schedules,
        "n_workers": n_workers,
        "kills_total": sum(len(r["events"]) for r in records),
        "acked_writes_total": sum(r["acked_writes"] for r in records),
        "lost_acked_writes": sum(r["lost_acked_writes"] for r in records),
        "owner_wedges": [w for r in records for w in r["owner_wedges"]],
        "respawns_total": sum(r["respawns"] for r in records),
        "dropped_inflight_total": sum(r["dropped_inflight"]
                                      for r in records),
        "failed_seeds": [r["seed"] for r in failed],
        "ok": not failed,
    }


def run_chaos(tmp_dir, n_schedules: int = 20, n_nodes: int = 3,
              replica_n: int = 2, seed: int = 0, n_events: int = 6,
              event_gap_s: float = 0.3, with_storage_faults: bool = False,
              with_autopilot: bool = False, with_cdc: bool = False,
              with_elastic: bool = False,
              log=lambda msg: None) -> dict:
    """Run ``n_schedules`` independent seeded schedules (fresh cluster
    each — a schedule's damage must not leak into the next) and fold
    the oracle verdicts. Any failing schedule reports its seed so the
    run replays deterministically. ``with_storage_faults`` adds
    bit-flip and disk-full events plus the disk-integrity oracle
    (bench_suite config_scrub); ``with_autopilot`` runs the placement
    plane live (fast tickers + forced-pass events) so the same oracles
    gate autopilot-minted resizes (bench_suite config_autopilot);
    ``with_cdc`` runs an out-of-cluster CDC mirror tailing n0 for the
    whole schedule, gated on the byte-identical mirror oracle
    (bench_suite config_cdc); ``with_elastic`` adds graceful-drain
    events so kills and partitions land mid-drain (bench_suite
    config_elastic), gated on all of the above."""
    records = []
    for i in range(n_schedules):
        schedule_seed = seed * 1000 + i
        log(f"chaos schedule {i + 1}/{n_schedules} (seed {schedule_seed})")
        harness = ChaosHarness(
            f"{tmp_dir}/sched{i}", n_nodes=n_nodes, replica_n=replica_n,
            seed=schedule_seed, n_events=n_events,
            event_gap_s=event_gap_s,
            with_storage_faults=with_storage_faults,
            with_autopilot=with_autopilot, with_cdc=with_cdc,
            with_elastic=with_elastic, log=log,
        )
        try:
            harness.boot()
            record = harness.run_schedule()
        finally:
            harness.close()
        record["seed"] = schedule_seed
        records.append(record)
        log(f"  -> ok={record['ok']} acked={record['acked_writes']} "
            f"wall={record['wall_s']}s")
    failed = [r for r in records if not r["ok"]]
    return {
        "schedules": n_schedules,
        "n_nodes": n_nodes,
        "replica_n": replica_n,
        "acked_writes_total": sum(r["acked_writes"] for r in records),
        "events_total": sum(len(r["events"]) for r in records),
        "lost_acked_writes": sum(r["lost_acked_writes"] for r in records),
        "non_quorum_deletions": sum(r["non_quorum_deletions"]
                                    for r in records),
        "coordinator_conflicts": [r["coordinator_conflicts"]
                                  for r in records
                                  if r["coordinator_conflicts"]],
        "replica_mismatches": sum(len(r["replica_mismatches"])
                                  for r in records),
        "corruptions_injected": sum(r.get("corruptions_injected", 0)
                                    for r in records),
        "disk_integrity_failures": sum(
            len(r.get("disk_integrity_failures", []))
            for r in records),
        "degraded_stuck": sum(len(r.get("degraded_stuck", []))
                              for r in records),
        "autopilot_moves_total": sum(r.get("autopilot_moves", 0)
                                     for r in records),
        "drains_total": sum(r.get("drains", 0) for r in records),
        "cdc_mirror_mismatches": sum(
            len(r.get("cdc_mirror_mismatches", [])) for r in records),
        "cdc_resyncs_total": sum(r.get("cdc_resyncs", 0)
                                 for r in records),
        "cdc_applied_ops_total": sum(r.get("cdc_applied_ops", 0)
                                     for r in records),
        "unconverged": sum(1 for r in records if not r["converged"]),
        "failed_seeds": [r["seed"] for r in failed],
        "failed_diags": [
            {"seed": r["seed"], "events": r["events"],
             "lost": r["lost_acked_writes"],
             "mismatches": len(r["replica_mismatches"]),
             "diag": r.get("converge_diag")}
            for r in failed
        ],
        "ok": not failed,
    }
