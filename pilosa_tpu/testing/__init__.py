"""Deterministic fault-injection and chaos machinery.

``faults`` is the rule engine hooked into the internal HTTP wire
(parallel/connpool.py) behind a zero-overhead-when-off check; ``chaos``
drives randomized partition/heal/kill schedules against an in-process
cluster and checks the four partition-safety oracles
(docs/OPERATIONS.md failure model).
"""
