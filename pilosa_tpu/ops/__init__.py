"""Device-side bitmap kernels (the TPU-native replacement for roaring/ ops).

The reference's roaring container ops (array/bitmap/run × union/intersect/
difference/xor, popcount-based Count/CountRange — roaring/roaring.go) are
re-expressed as dense bitwise + population_count XLA ops over bit-packed
uint32 tensors. Per-container branching is replaced by uniform vector ops
the VPU executes at full width; XLA fuses chains of bitwise ops with the
final popcount reduction so intermediate bitmaps never hit HBM.
"""

from pilosa_tpu.ops.packing import pack_bits, unpack_bits, pack_shard_row
from pilosa_tpu.ops import bitops
