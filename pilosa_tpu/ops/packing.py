"""Host-side bit packing between column-id sets and dense uint32 words.

This is the boundary between the host storage format (roaring containers,
sorted id arrays — reference roaring/roaring.go) and the device format
(dense bit-packed uint32 vectors). Bit b of the vector lives at
``words[b // 32] >> (b % 32) & 1`` (little bit order, matching
little-endian byte layout so numpy packbits/unpackbits round-trips).
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.shardwidth import SHARD_WIDTH


def pack_bits(bit_positions, n_bits: int = SHARD_WIDTH) -> np.ndarray:
    """Pack sorted (or unsorted) bit positions into a uint32 word vector.

    Equivalent of building a roaring bitmap from an id list (reference
    roaring.Bitmap Add / NewBitmap(ids...)). Uses the fastbits C++ library
    when available (pilosa_tpu.native), numpy otherwise.
    """
    n_words = (n_bits + 31) // 32
    bit_positions = np.asarray(bit_positions, dtype=np.uint64)
    if bit_positions.size == 0:
        return np.zeros(n_words, dtype=np.uint32)
    if bit_positions.max() >= n_bits:
        raise ValueError(
            f"bit position {bit_positions.max()} out of range for {n_bits} bits"
        )
    from pilosa_tpu import native

    fast = native.pack_positions(bit_positions, n_words)
    if fast is not None:
        return fast
    bytes_ = np.zeros(n_words * 4, dtype=np.uint8)
    byte_idx = (bit_positions >> np.uint64(3)).astype(np.int64)
    bit_in_byte = (bit_positions & np.uint64(7)).astype(np.uint8)
    np.bitwise_or.at(bytes_, byte_idx, np.uint8(1) << bit_in_byte)
    return bytes_.view("<u4").copy()


def unpack_bits(words: np.ndarray, offset: int = 0) -> np.ndarray:
    """Expand a uint32 word vector to sorted absolute bit positions.

    ``offset`` shifts positions into absolute column space — the packed
    equivalent of the reference's roaring OffsetRange used when a shard's
    rowSegment is materialized to absolute columns (row.go Columns()).
    """
    from pilosa_tpu import native

    fast = native.unpack_positions(np.asarray(words), offset)
    if fast is not None:
        return fast
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64) + np.uint64(offset)


def pack_shard_row(column_positions) -> np.ndarray:
    """Pack in-shard column positions into a full shard-row word vector."""
    return pack_bits(column_positions, SHARD_WIDTH)


def popcount_words(words: np.ndarray) -> int:
    """Host popcount (native when available, numpy otherwise)."""
    from pilosa_tpu import native

    fast = native.popcount_words(np.asarray(words))
    if fast is not None:
        return fast
    words = np.ascontiguousarray(words, dtype=np.uint32)
    return int(np.unpackbits(words.view(np.uint8)).sum())
