"""Core bitwise/popcount kernels over bit-packed uint32 tensors.

TPU-native re-expression of the reference's roaring container ops
(roaring/roaring.go: Union/Intersect/Difference/Xor/Count/CountRange/Flip
and row.go Shift). Every op is a uniform dense vector op — no container
kind dispatch — so XLA fuses arbitrary PQL expression trees
(e.g. Count(Intersect(Union(a,b), Not(c)))) into a single HBM pass.

Shapes: ops are shape-polymorphic over uint32 arrays; a shard-row is
``uint32[32768]`` and a row-block is ``uint32[rows, 32768]``. Counts are
returned as int32 per row (max 2^20 per shard-row, far below overflow);
cross-shard / cross-row totals are summed host-side in Python ints.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from pilosa_tpu.shardwidth import WORD_BITS

_U32 = jnp.uint32


@jax.jit
def union(a, b):
    return a | b


@jax.jit
def intersect(a, b):
    return a & b


@jax.jit
def difference(a, b):
    return a & ~b


@jax.jit
def xor(a, b):
    return a ^ b


@jax.jit
def count(a):
    """Total set bits in the whole tensor (int32 scalar).

    Safe for a single shard-row or a small batch; use count_rows + host sum
    for large row-blocks.
    """
    return jnp.sum(lax.population_count(a).astype(jnp.int32))


@jax.jit
def count_rows(a):
    """Per-row popcount for a row-block uint32[rows, words] -> int32[rows]."""
    return jnp.sum(lax.population_count(a).astype(jnp.int32), axis=-1)


@jax.jit
def intersect_count(a, b):
    """Fused Intersect+Count — the north-star metric op. XLA fuses the AND
    with the popcount reduce so the intersection bitmap never materializes."""
    return jnp.sum(lax.population_count(a & b).astype(jnp.int32))


@partial(jax.jit, static_argnums=0)
def _range_mask(n_words, start, stop):
    """uint32[n_words] mask with bits [start, stop) set."""
    idx = lax.iota(jnp.int32, n_words)
    word_lo = jnp.asarray(start, jnp.int32) // WORD_BITS
    word_hi = jnp.asarray(stop, jnp.int32) // WORD_BITS
    bit_lo = jnp.asarray(start, jnp.int32) % WORD_BITS
    bit_hi = jnp.asarray(stop, jnp.int32) % WORD_BITS
    full = ((idx > word_lo) & (idx < word_hi)).astype(_U32) * _U32(0xFFFFFFFF)
    # Partial masks at the boundary words. (-1 << b) keeps bits >= b.
    lo_mask = _U32(0xFFFFFFFF) << bit_lo.astype(_U32)
    hi_mask = jnp.where(
        bit_hi > 0, ~(_U32(0xFFFFFFFF) << bit_hi.astype(_U32)), _U32(0)
    )
    both = lo_mask & hi_mask
    mask = full
    mask = jnp.where(idx == word_lo, jnp.where(word_lo == word_hi, both, lo_mask), mask)
    mask = jnp.where((idx == word_hi) & (word_hi > word_lo), hi_mask, mask)
    return jnp.where(jnp.asarray(stop, jnp.int32) > jnp.asarray(start, jnp.int32), mask, _U32(0))


def range_mask(n_words: int, start, stop):
    return _range_mask(n_words, start, stop)


@jax.jit
def count_range(a, start, stop):
    """Count set bits with position in [start, stop) along the last axis
    (reference roaring CountRange)."""
    mask = _range_mask(a.shape[-1], start, stop)
    return jnp.sum(lax.population_count(a & mask).astype(jnp.int32))


@jax.jit
def flip_range(a, start, stop):
    """Flip bits in [start, stop) (reference roaring Flip; basis of Not)."""
    mask = _range_mask(a.shape[-1], start, stop)
    return a ^ mask


@jax.jit
def shift(a, n):
    """Shift set bits toward higher positions by n along the last axis
    (reference row.go Shift / executor Shift(row, n)). Negative n shifts
    toward lower positions. Bits shifted past either end are dropped
    (per-shard semantics; cross-shard carry handled by the executor on
    host)."""
    n = jnp.asarray(n, jnp.int32)
    # Floor division/mod so negative n (shift toward lower positions) also
    # decomposes as n = 32*word_shift + bit_shift with bit_shift in [0, 32).
    word_shift = jnp.floor_divide(n, WORD_BITS)
    bit_shift = jnp.mod(n, WORD_BITS).astype(_U32)
    n_words = a.shape[-1]
    # Bit-level shift first with a STATIC neighbor (cross-word carry is
    # ws-independent), then ONE dynamic word roll + range mask. Gather
    # formulations cost ~3x on TPU (dynamic gather over the lane axis);
    # roll lowers to slice+concat and the rest fuses into the pass. The
    # appended tail word carries the top word's spill-over so negative
    # shifts keep the bits that land at result word n_words + word_shift.
    prev = jnp.concatenate(
        [jnp.zeros_like(a[..., :1]), a[..., :-1]], axis=-1
    )
    carry = _U32(WORD_BITS) - bit_shift
    y = (a << bit_shift) | jnp.where(
        bit_shift > 0, prev >> carry, _U32(0)
    )
    idx = lax.iota(jnp.int32, n_words)
    in_range = (idx >= word_shift) & (idx < n_words + word_shift)
    out = jnp.where(in_range, jnp.roll(y, word_shift, axis=-1), _U32(0))
    # Negative shifts: the top word's spill-over lands at result word
    # n_words + word_shift (never a valid index for word_shift >= 0, so
    # the select is a no-op there); a fused elementwise select keeps the
    # kernel one aligned pass instead of a width-(n_words+1) concat.
    tail = jnp.where(
        bit_shift > 0, a[..., -1:] >> carry, jnp.zeros_like(a[..., :1])
    )
    return jnp.where(idx == n_words + word_shift, tail, out)


@jax.jit
def any_set(a):
    """True if any bit is set (used by Rows() existence filtering)."""
    return jnp.any(a != 0)


@jax.jit
def rows_any(a):
    """Per-row non-empty flags for uint32[rows, words] -> bool[rows]."""
    return jnp.any(a != 0, axis=-1)
