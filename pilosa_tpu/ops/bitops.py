"""Device bitwise kernels that need more than an infix operator.

Fused query evaluation does NOT live here: the expression compiler
(executor/expr.py) lowers whole PQL trees to jnp operator chains that XLA
fuses into one HBM pass, so Union/Intersect/Difference/Xor/Count never
exist as standalone kernels (they would be ``a | b`` etc. with extra
indirection). The only op with a non-trivial body is Shift — reference
row.go Shift — which expr.py inlines via ``shift.__wrapped__`` so it
still fuses into the pass.

Shapes: shape-polymorphic over bit-packed uint32 arrays; a shard-row is
``uint32[32768]`` (shardwidth.WORDS_PER_SHARD).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pilosa_tpu.shardwidth import WORD_BITS

_U32 = jnp.uint32


@jax.jit
def shift(a, n):
    """Shift set bits toward higher positions by n along the last axis
    (reference row.go Shift / executor Shift(row, n)). Negative n shifts
    toward lower positions. Bits shifted past either end are dropped
    (per-shard semantics; cross-shard carry handled by the executor on
    host)."""
    n = jnp.asarray(n, jnp.int32)
    # Floor division/mod so negative n (shift toward lower positions) also
    # decomposes as n = 32*word_shift + bit_shift with bit_shift in [0, 32).
    word_shift = jnp.floor_divide(n, WORD_BITS)
    bit_shift = jnp.mod(n, WORD_BITS).astype(_U32)
    n_words = a.shape[-1]
    # Bit-level shift first with a STATIC neighbor (cross-word carry is
    # ws-independent), then ONE dynamic word roll + range mask. Gather
    # formulations cost ~3x on TPU (dynamic gather over the lane axis);
    # roll lowers to slice+concat and the rest fuses into the pass. The
    # appended tail word carries the top word's spill-over so negative
    # shifts keep the bits that land at result word n_words + word_shift.
    prev = jnp.concatenate(
        [jnp.zeros_like(a[..., :1]), a[..., :-1]], axis=-1
    )
    carry = _U32(WORD_BITS) - bit_shift
    y = (a << bit_shift) | jnp.where(
        bit_shift > 0, prev >> carry, _U32(0)
    )
    idx = lax.iota(jnp.int32, n_words)
    in_range = (idx >= word_shift) & (idx < n_words + word_shift)
    out = jnp.where(in_range, jnp.roll(y, word_shift, axis=-1), _U32(0))
    # Negative shifts: the top word's spill-over lands at result word
    # n_words + word_shift (never a valid index for word_shift >= 0, so
    # the select is a no-op there); a fused elementwise select keeps the
    # kernel one aligned pass instead of a width-(n_words+1) concat.
    tail = jnp.where(
        bit_shift > 0, a[..., -1:] >> carry, jnp.zeros_like(a[..., :1])
    )
    return jnp.where(idx == n_words + word_shift, tail, out)
