"""Pallas TPU kernels for the hottest fused ops.

XLA already fuses bitwise chains with the final popcount (expr.py), and
those ops are HBM-bandwidth-bound — so the win here is explicit tiling
control on the very largest operands: a grid over row blocks streams
uint32[rows, 32768] operands through VMEM in (8, 512)-word tiles and
accumulates partial popcounts per grid cell, avoiding any intermediate
materialization at shapes where XLA's default tiling can spill.

Used by bench.py when a TPU backend is active; everywhere else the jnp
path (ops.bitops) is the default. On CPU these kernels run in interpret
mode (tests only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

BLOCK_ROWS = 8
BLOCK_WORDS = 4096  # 16 KiB/operand tile → well within VMEM with 3 operands


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(jax.jit, static_argnames=("interpret",))
def intersect_count_pallas(a, b, interpret: bool | None = None):
    """sum(popcount(a & b)) over uint32[rows, words] via a Pallas grid.

    Returns int32 (safe: ≤ rows·words·32 ≤ 2^31 for any single fragment
    batch we feed — callers batch larger inputs).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = _use_interpret()
    rows, words = a.shape
    grid = (pl.cdiv(rows, BLOCK_ROWS), pl.cdiv(words, BLOCK_WORDS))

    def kernel(a_ref, b_ref, out_ref):
        x = a_ref[...] & b_ref[...]
        out_ref[0, 0] = jnp.sum(jax.lax.population_count(x).astype(jnp.int32))

    partials = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, BLOCK_WORDS), lambda i, j: (i, j)),
            pl.BlockSpec((BLOCK_ROWS, BLOCK_WORDS), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(grid, jnp.int32),
        interpret=interpret,
    )(a, b)
    return jnp.sum(partials)
