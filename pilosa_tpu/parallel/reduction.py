"""Compressed/quantized reduction lanes + cross-chip wire-byte model.

ROADMAP open item 2: before real multi-chip hardware shows up, make
cross-chip reduction cost a *measured* quantity and shrink it. Two ideas,
both borrowed from systems that already pay this bill:

* EQuARX-style narrow collectives (arXiv:2506.17615): the inter-group hop
  of a hierarchical reduction carries per-group partials, and a partial's
  value range is statically bounded (every per-shard summand is at most
  SHARD_WIDTH), so the lane can often be cast to uint8/uint16 and summed
  exactly on the receiver. Unlike EQuARX's lossy block scaling, every
  lane here must stay BIT-EXACT — counts and BSI aggregates are answers,
  not gradients — so narrowing only happens where the static bound proves
  losslessness, with an int32 exact fallback. The reserved accuracy
  budget is now spent where EQuARX actually spends it: the
  *candidate-ranking* lanes of TopN/GroupBy (hier_quantized_counts)
  carry 8-bit max-scaled mantissas with a transmitted error bound, the
  executor widens the final candidate window by that bound, and the
  exact recount on the widened window keeps results byte-identical
  (topn-quantized-ranking knob, default off — docs/OPERATIONS.md).

* Roaring-compressed row gathers (Chambi et al., arXiv:1402.6407): a
  materialized Row result crossing the wire as dense words pays
  padded x 128 KiB regardless of cardinality; the same payload as
  serialized roaring containers (the repair plane's format,
  roaring/format.py) is proportional to what's actually set.

The traced helpers (hier_split_channels / gather_extreme) run INSIDE
shard_map bodies on the 2-D ``groups x shards`` mesh (parallel/mesh.py);
the byte-model functions run host-side at dispatch time. Both derive
lane dtypes from the same ``lane_dtype`` bound logic so the accounting
can never drift from the program.

Wire model (documented in docs/OPERATIONS.md "Multi-chip mesh"):

* dense-equivalent — what the flat 1-D path moves: a ring all-reduce of
  the int32 packed lanes over all N mesh devices, total
  ``2*(N-1) * payload`` bytes on the wire.
* actual (headline ``dist_reduce_actual_bytes``) — the inter-group hop
  only: a ring all-gather of the encoded per-group partials over the G
  group leads, ``G*(G-1) * enc_payload`` total. Groups model the
  cross-chip/DCN boundary; that hop is the one the ROADMAP's
  85%-of-linear target lives or dies on.
* intra — the per-group dense all-reduce (``G * 2*(S/G - 1) * payload``)
  reported separately as on-chip/ICI traffic, which is not the scarce
  resource the plane optimizes.
"""

from __future__ import annotations

import threading

import numpy as np

from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

SPLIT_SHIFT = 15  # mirrors executor/batch.py (import cycle: keep literal)
SPLIT_MASK = (1 << SPLIT_SHIFT) - 1
# per-shard summand ceiling: any popcount/count lane sums values <=
# SHARD_WIDTH per slot, so split channels are bounded per slot by
# SPLIT_MASK (lo) and SHARD_WIDTH >> SPLIT_SHIFT (hi)
HI_PER_SLOT = SHARD_WIDTH >> SPLIT_SHIFT

# Quantized candidate-ranking lane (EQuARX-style, arXiv:2506.17615):
# candidates per max-scale block. One int32 scale + one error-bound lane
# amortize over QUANT_BLOCK uint8 mantissas, so the encoded payload is
# ~1 byte/candidate vs the >=3 bytes/candidate of the lossless split
# channels. Exactness note: the merged per-group total is carried in one
# int32 lane, exact while group totals stay < 2^31 — i.e. up to 2^11
# fully-set shards per group, far beyond any mesh this plane drives.
QUANT_BLOCK = 256


def lane_dtype_bytes(bound: int) -> int:
    """Width of the narrowest integer lane proven lossless for values in
    [0, bound]. int32 is the exact fallback."""
    if bound <= 0xFF:
        return 1
    if bound <= 0xFFFF:
        return 2
    return 4


def lane_dtype(bound: int):
    import jax.numpy as jnp

    return {1: jnp.uint8, 2: jnp.uint16, 4: jnp.int32}[lane_dtype_bytes(bound)]


def split_channel_bounds(group_slots: int) -> tuple[int, int]:
    """Static (lo, hi) channel bounds for a per-group split-sum partial
    over ``group_slots`` shard slots."""
    return group_slots * SPLIT_MASK, group_slots * HI_PER_SLOT


# ------------------------------------------------------- traced helpers
#
# These run inside shard_map bodies. The contract with the flat path is
# BIT-IDENTICAL packed results: integer adds are exact and associative,
# so (psum over the shards axis) + (gather + local sum over groups)
# equals the flat psum channel-for-channel, and the narrow cast is a
# no-op on values the static bound covers.


def quant_blocks(n_rows: int) -> int:
    """Number of QUANT_BLOCK-sized scale blocks covering ``n_rows``
    candidate lanes."""
    return max(1, -(-n_rows // QUANT_BLOCK))


def quant_total_elems(n_rows: int) -> int:
    """Lanes in a quantized packed result: the approx counts plus one
    error-bound lane per scale block."""
    return n_rows + quant_blocks(n_rows)


def quant_real_elems(total: int) -> int:
    """Inverse of quant_total_elems (host accounting sees only the packed
    shape). Exact by construction: total is monotone in n_rows."""
    n = max(1, total - quant_blocks(total))
    while quant_total_elems(n) < total:
        n += 1
    return n


def quant_payload_bytes(n_rows: int) -> int:
    """Encoded bytes ONE group contributes to the quantized inter-group
    hop: a uint8 mantissa per candidate + an int32 scale per block."""
    return n_rows * 1 + quant_blocks(n_rows) * 4


def hier_quantized_counts(part, groups_axis):
    """Inter-group hop for a CANDIDATE-RANKING split-sum partial
    ``[2, R]`` — the EQuARX-style lossy lane (arXiv:2506.17615).

    Per QUANT_BLOCK of candidates the per-group totals are max-scaled to
    8 bits: integer scale ``s = max(1, ceil(max/255))`` and
    stochastic-free deterministic round-to-nearest
    ``q = (v + s//2) // s`` (pure int32 arithmetic — bit-reproducible
    across dispatch order and group count, unlike float rounding).

    Error bound (the stated contract the executor's window widening
    relies on): per group ``|v - q*s| <= (s+1)//2``, and exactly 0 when
    ``s == 1`` (max <= 255 quantizes losslessly). The decoded total's
    error is at most the SUM of the per-group bounds, which the program
    computes from the gathered scales and returns as one extra lane per
    block — the bound crosses the wire with the data, so the host never
    has to re-derive it from mesh geometry.

    Returns split-form ``[2, R + n_blocks]``: approx counts followed by
    per-block error bounds (batch.merge_split + split_quantized decode).
    ``groups_axis=None`` (flat 1-D mesh) is the lossless pass-through:
    approx == exact, bound == 0.
    """
    import jax.numpy as jnp
    from jax import lax

    flat = part[0] + (part[1] << SPLIT_SHIFT)  # exact int32 group totals
    n_rows = flat.shape[0]
    nb = quant_blocks(n_rows)
    if groups_axis is None:
        out = jnp.concatenate([flat, jnp.zeros((nb,), jnp.int32)])
        return jnp.stack([out & SPLIT_MASK, out >> SPLIT_SHIFT])
    pad = nb * QUANT_BLOCK - n_rows
    blocks = jnp.pad(flat, (0, pad)).reshape(nb, QUANT_BLOCK)
    mx = jnp.max(blocks, axis=1)
    s = jnp.maximum((mx + 254) // 255, 1)  # [nb] int32 block scales
    q = ((blocks + (s[:, None] >> 1)) // s[:, None]).astype(jnp.uint8)
    gq = lax.all_gather(q, groups_axis)  # [G, nb, B] uint8  — the wire
    gs = lax.all_gather(s, groups_axis)  # [G, nb] int32     — the wire
    approx = jnp.sum(gq.astype(jnp.int32) * gs[:, :, None], axis=0)
    approx = approx.reshape(nb * QUANT_BLOCK)[:n_rows]
    err = jnp.sum(jnp.where(gs > 1, (gs + 1) >> 1, 0), axis=0)  # [nb]
    out = jnp.concatenate([approx, err])
    return jnp.stack([out & SPLIT_MASK, out >> SPLIT_SHIFT])


def split_quantized(merged: np.ndarray, n_rows: int
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Host decode of one merged quantized section ``[R + n_blocks]``
    (after batch.merge_split): (approx counts [R], per-candidate error
    bound [R] — each candidate inherits its scale block's bound)."""
    nb = quant_blocks(n_rows)
    approx = np.asarray(merged[:n_rows], np.int64)
    err_blocks = np.asarray(merged[n_rows:n_rows + nb], np.int64)
    err = np.repeat(err_blocks, QUANT_BLOCK)[:n_rows]
    return approx, err


def quant_topn_window(approx: np.ndarray, err: np.ndarray, n: int
                      ) -> np.ndarray:
    """Indices of every candidate that could still be in the exact top
    ``n`` given approx counts with per-candidate error bound ``err``
    (true count in [approx-err, approx+err]).

    Rule: admit j unless n candidates have a LOWER bound strictly above
    j's UPPER bound — those n have provably greater exact counts, so j's
    exact rank exceeds n under any tie-break. The window is therefore a
    superset of the exact top n (tests/test_mesh_reduction.py holds the
    property), and the widening per candidate is exactly its error
    bound on each side."""
    m = len(approx)
    if n <= 0 or m <= n:
        return np.arange(m)
    lo = approx - err
    hi = approx + err
    cut = np.partition(lo, m - n)[m - n]  # n-th largest lower bound
    return np.nonzero(hi >= cut)[0]


def hier_split_channels(part, groups_axis: str, group_slots: int):
    """Inter-group hop for a split-sum packed partial ``[2, ...]``:
    all_gather each channel at its narrowest lossless dtype, then
    accumulate exactly in int32 on every receiver."""
    import jax.numpy as jnp
    from jax import lax

    lo_b, hi_b = split_channel_bounds(group_slots)
    lo = lax.all_gather(part[0].astype(lane_dtype(lo_b)), groups_axis)
    hi = lax.all_gather(part[1].astype(lane_dtype(hi_b)), groups_axis)
    return jnp.stack([jnp.sum(lo.astype(jnp.int32), axis=0),
                      jnp.sum(hi.astype(jnp.int32), axis=0)])


def gather_extreme(part, groups_axis: str, want_max: bool, bound=None):
    """Inter-group hop for an extremum lane: gather the per-group bests
    (narrowed when ``bound`` proves it lossless) and fold locally."""
    import jax.numpy as jnp
    from jax import lax

    dt = part.dtype if bound is None else lane_dtype(bound)
    g = lax.all_gather(part.astype(dt), groups_axis).astype(jnp.int32)
    return jnp.max(g, axis=0) if want_max else jnp.min(g, axis=0)


# ------------------------------------------------------ host byte model


def inter_group_payload_bytes(reduce_kind: str, out_elems: int,
                              group_slots: int) -> int:
    """Encoded bytes ONE group contributes to the inter-group hop, for a
    packed result of ``out_elems`` int32 lanes (batched dispatches pass
    the batch-multiplied element count)."""
    lo_b, hi_b = split_channel_bounds(group_slots)
    lo_w, hi_w = lane_dtype_bytes(lo_b), lane_dtype_bytes(hi_b)
    if reduce_kind in ("min", "max"):
        # [best, count_lo, count_hi] per query -> best int32 + any_valid
        # uint8 + narrowed count channels
        return (out_elems // 3) * (4 + 1 + lo_w + hi_w)
    # every other packed kind is pairs of split channels
    return (out_elems // 2) * (lo_w + hi_w)


def dense_reduce_bytes(n_devices: int, out_elems: int) -> int:
    """Flat-path equivalent: ring all-reduce of the int32 packed lanes
    over the whole mesh."""
    return 2 * (n_devices - 1) * out_elems * 4


def hier_reduce_bytes(reduce_kind: str, out_elems: int, groups: int,
                      shards_per_group: int, group_slots: int
                      ) -> tuple[int, int]:
    """(inter_group_bytes, intra_group_bytes) for one hierarchical
    dispatch: narrow ring all-gather across the G group leads, dense
    int32 ring all-reduce inside each group."""
    inter = groups * (groups - 1) * inter_group_payload_bytes(
        reduce_kind, out_elems, group_slots
    )
    intra = groups * 2 * max(shards_per_group - 1, 0) * out_elems * 4
    return inter, intra


def quant_hier_bytes(n_rows: int, groups: int, shards_per_group: int,
                     group_slots: int) -> tuple[int, int, int]:
    """(inter, intra, lossless_inter) for one QUANTIZED ranking dispatch
    of ``n_rows`` candidate lanes: the 8-bit scaled inter-group hop, the
    unchanged dense intra-group all-reduce of the [2, R] split channels,
    and what the same hop would have cost on the lossless countrows
    lane — the delta the dist_reduce_quantized_* series reports."""
    inter = groups * (groups - 1) * quant_payload_bytes(n_rows)
    intra = groups * 2 * max(shards_per_group - 1, 0) * 2 * n_rows * 4
    lossless = groups * (groups - 1) * inter_group_payload_bytes(
        "countrows", 2 * n_rows, group_slots
    )
    return inter, intra, lossless


# -------------------------------------------------- row-gather wire sim


def encode_row_frames(host: np.ndarray) -> tuple[list[bytes], int]:
    """Serialize a [slots, WORDS_PER_SHARD] dense row readback as
    per-slot roaring payloads framed like the repair plane's block frames
    (wire/serializer.py). Empty slots frame as b"" (1-byte tag + length
    prefix on the wire). Returns (frames, framed_bytes)."""
    from pilosa_tpu.roaring.bitmap import RoaringBitmap
    from pilosa_tpu.roaring import format as rformat
    from pilosa_tpu.wire.serializer import encode_block_frames

    payloads = []
    for slot in range(host.shape[0]):
        words = host[slot]
        if words.any():
            payloads.append(
                rformat.serialize(RoaringBitmap.from_dense_words(words))
            )
        else:
            payloads.append(b"")
    return payloads, len(encode_block_frames(payloads))


def decode_row_frames(payloads: list[bytes], shape: tuple) -> np.ndarray:
    """Inverse of encode_row_frames: rebuild the dense [slots, words]
    uint32 array. Byte-identical round trip — this IS the result path
    when the wire sim is on, so a codec bug is a visible wrong answer,
    not a silent accounting error."""
    from pilosa_tpu.roaring import format as rformat

    out = np.zeros(shape, np.uint32)
    for slot, payload in enumerate(payloads):
        if not payload:
            continue
        bm, _ = rformat.deserialize(payload)
        out[slot] = bm.dense_range_words32(0, WORDS_PER_SHARD * 32)
    return out


# ------------------------------------------------------ global counters


class ReduceStats:
    """Process-wide dist_reduce_* counters (served on /metrics and
    /debug/vars). Lock kept tiny: a handful of integer adds per device
    dispatch, invisible next to the dispatch itself."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.dispatches = 0
            self.hier_dispatches = 0
            self.dense_bytes = 0
            self.actual_bytes = 0
            self.intra_bytes = 0
            self.row_gathers = 0
            self.row_dense_bytes = 0
            self.row_actual_bytes = 0
            self.quant_dispatches = 0
            self.quant_actual_bytes = 0
            self.quant_lossless_bytes = 0
            self.quant_window_rows = 0
            self.quant_candidate_rows = 0

    def note_reduce(self, dense: int, actual: int, intra: int,
                    hier: bool) -> None:
        with self._lock:
            self.dispatches += 1
            self.hier_dispatches += 1 if hier else 0
            self.dense_bytes += dense
            self.actual_bytes += actual
            self.intra_bytes += intra

    def note_quant_reduce(self, actual: int, lossless: int) -> None:
        """One quantized ranking dispatch: the encoded hop bytes vs what
        the lossless lane would have moved for the same candidates.
        Rides ALONGSIDE note_reduce (the hop is real actual_bytes
        traffic); this series isolates the quantization delta."""
        with self._lock:
            self.quant_dispatches += 1
            self.quant_actual_bytes += actual
            self.quant_lossless_bytes += lossless

    def note_quant_window(self, window_rows: int, candidate_rows: int
                          ) -> None:
        """One TopN window selection: candidates surviving into the
        exact recount vs the full ranked set — the other half of the
        saving (the lossless pass shrinks to the window)."""
        with self._lock:
            self.quant_window_rows += window_rows
            self.quant_candidate_rows += candidate_rows

    def note_row_gather(self, dense: int, actual: int) -> None:
        with self._lock:
            self.row_gathers += 1
            self.row_dense_bytes += dense
            self.row_actual_bytes += actual

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "hier_dispatches": self.hier_dispatches,
                "dense_bytes": self.dense_bytes,
                "actual_bytes": self.actual_bytes,
                "intra_bytes": self.intra_bytes,
                "row_gathers": self.row_gathers,
                "row_dense_bytes": self.row_dense_bytes,
                "row_actual_bytes": self.row_actual_bytes,
                "quantized_dispatches": self.quant_dispatches,
                "quantized_actual_bytes": self.quant_actual_bytes,
                "quantized_lossless_bytes": self.quant_lossless_bytes,
                "quantized_window_rows": self.quant_window_rows,
                "quantized_candidate_rows": self.quant_candidate_rows,
            }


_STATS = ReduceStats()


def global_reduce_stats() -> ReduceStats:
    return _STATS
