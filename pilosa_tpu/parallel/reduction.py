"""Compressed/quantized reduction lanes + cross-chip wire-byte model.

ROADMAP open item 2: before real multi-chip hardware shows up, make
cross-chip reduction cost a *measured* quantity and shrink it. Two ideas,
both borrowed from systems that already pay this bill:

* EQuARX-style narrow collectives (arXiv:2506.17615): the inter-group hop
  of a hierarchical reduction carries per-group partials, and a partial's
  value range is statically bounded (every per-shard summand is at most
  SHARD_WIDTH), so the lane can often be cast to uint8/uint16 and summed
  exactly on the receiver. Unlike EQuARX's lossy block scaling, every
  lane here must stay BIT-EXACT — counts and BSI aggregates are answers,
  not gradients — so narrowing only happens where the static bound proves
  losslessness, with an int32 exact fallback. (Lossy scaling stays
  reserved for TopN *candidate ranking* lanes, where a final exact
  re-verify would bound the error; no lane uses it yet.)

* Roaring-compressed row gathers (Chambi et al., arXiv:1402.6407): a
  materialized Row result crossing the wire as dense words pays
  padded x 128 KiB regardless of cardinality; the same payload as
  serialized roaring containers (the repair plane's format,
  roaring/format.py) is proportional to what's actually set.

The traced helpers (hier_split_channels / gather_extreme) run INSIDE
shard_map bodies on the 2-D ``groups x shards`` mesh (parallel/mesh.py);
the byte-model functions run host-side at dispatch time. Both derive
lane dtypes from the same ``lane_dtype`` bound logic so the accounting
can never drift from the program.

Wire model (documented in docs/OPERATIONS.md "Multi-chip mesh"):

* dense-equivalent — what the flat 1-D path moves: a ring all-reduce of
  the int32 packed lanes over all N mesh devices, total
  ``2*(N-1) * payload`` bytes on the wire.
* actual (headline ``dist_reduce_actual_bytes``) — the inter-group hop
  only: a ring all-gather of the encoded per-group partials over the G
  group leads, ``G*(G-1) * enc_payload`` total. Groups model the
  cross-chip/DCN boundary; that hop is the one the ROADMAP's
  85%-of-linear target lives or dies on.
* intra — the per-group dense all-reduce (``G * 2*(S/G - 1) * payload``)
  reported separately as on-chip/ICI traffic, which is not the scarce
  resource the plane optimizes.
"""

from __future__ import annotations

import threading

import numpy as np

from pilosa_tpu.shardwidth import SHARD_WIDTH, WORDS_PER_SHARD

SPLIT_SHIFT = 15  # mirrors executor/batch.py (import cycle: keep literal)
SPLIT_MASK = (1 << SPLIT_SHIFT) - 1
# per-shard summand ceiling: any popcount/count lane sums values <=
# SHARD_WIDTH per slot, so split channels are bounded per slot by
# SPLIT_MASK (lo) and SHARD_WIDTH >> SPLIT_SHIFT (hi)
HI_PER_SLOT = SHARD_WIDTH >> SPLIT_SHIFT


def lane_dtype_bytes(bound: int) -> int:
    """Width of the narrowest integer lane proven lossless for values in
    [0, bound]. int32 is the exact fallback."""
    if bound <= 0xFF:
        return 1
    if bound <= 0xFFFF:
        return 2
    return 4


def lane_dtype(bound: int):
    import jax.numpy as jnp

    return {1: jnp.uint8, 2: jnp.uint16, 4: jnp.int32}[lane_dtype_bytes(bound)]


def split_channel_bounds(group_slots: int) -> tuple[int, int]:
    """Static (lo, hi) channel bounds for a per-group split-sum partial
    over ``group_slots`` shard slots."""
    return group_slots * SPLIT_MASK, group_slots * HI_PER_SLOT


# ------------------------------------------------------- traced helpers
#
# These run inside shard_map bodies. The contract with the flat path is
# BIT-IDENTICAL packed results: integer adds are exact and associative,
# so (psum over the shards axis) + (gather + local sum over groups)
# equals the flat psum channel-for-channel, and the narrow cast is a
# no-op on values the static bound covers.


def hier_split_channels(part, groups_axis: str, group_slots: int):
    """Inter-group hop for a split-sum packed partial ``[2, ...]``:
    all_gather each channel at its narrowest lossless dtype, then
    accumulate exactly in int32 on every receiver."""
    import jax.numpy as jnp
    from jax import lax

    lo_b, hi_b = split_channel_bounds(group_slots)
    lo = lax.all_gather(part[0].astype(lane_dtype(lo_b)), groups_axis)
    hi = lax.all_gather(part[1].astype(lane_dtype(hi_b)), groups_axis)
    return jnp.stack([jnp.sum(lo.astype(jnp.int32), axis=0),
                      jnp.sum(hi.astype(jnp.int32), axis=0)])


def gather_extreme(part, groups_axis: str, want_max: bool, bound=None):
    """Inter-group hop for an extremum lane: gather the per-group bests
    (narrowed when ``bound`` proves it lossless) and fold locally."""
    import jax.numpy as jnp
    from jax import lax

    dt = part.dtype if bound is None else lane_dtype(bound)
    g = lax.all_gather(part.astype(dt), groups_axis).astype(jnp.int32)
    return jnp.max(g, axis=0) if want_max else jnp.min(g, axis=0)


# ------------------------------------------------------ host byte model


def inter_group_payload_bytes(reduce_kind: str, out_elems: int,
                              group_slots: int) -> int:
    """Encoded bytes ONE group contributes to the inter-group hop, for a
    packed result of ``out_elems`` int32 lanes (batched dispatches pass
    the batch-multiplied element count)."""
    lo_b, hi_b = split_channel_bounds(group_slots)
    lo_w, hi_w = lane_dtype_bytes(lo_b), lane_dtype_bytes(hi_b)
    if reduce_kind in ("min", "max"):
        # [best, count_lo, count_hi] per query -> best int32 + any_valid
        # uint8 + narrowed count channels
        return (out_elems // 3) * (4 + 1 + lo_w + hi_w)
    # every other packed kind is pairs of split channels
    return (out_elems // 2) * (lo_w + hi_w)


def dense_reduce_bytes(n_devices: int, out_elems: int) -> int:
    """Flat-path equivalent: ring all-reduce of the int32 packed lanes
    over the whole mesh."""
    return 2 * (n_devices - 1) * out_elems * 4


def hier_reduce_bytes(reduce_kind: str, out_elems: int, groups: int,
                      shards_per_group: int, group_slots: int
                      ) -> tuple[int, int]:
    """(inter_group_bytes, intra_group_bytes) for one hierarchical
    dispatch: narrow ring all-gather across the G group leads, dense
    int32 ring all-reduce inside each group."""
    inter = groups * (groups - 1) * inter_group_payload_bytes(
        reduce_kind, out_elems, group_slots
    )
    intra = groups * 2 * max(shards_per_group - 1, 0) * out_elems * 4
    return inter, intra


# -------------------------------------------------- row-gather wire sim


def encode_row_frames(host: np.ndarray) -> tuple[list[bytes], int]:
    """Serialize a [slots, WORDS_PER_SHARD] dense row readback as
    per-slot roaring payloads framed like the repair plane's block frames
    (wire/serializer.py). Empty slots frame as b"" (1-byte tag + length
    prefix on the wire). Returns (frames, framed_bytes)."""
    from pilosa_tpu.roaring.bitmap import RoaringBitmap
    from pilosa_tpu.roaring import format as rformat
    from pilosa_tpu.wire.serializer import encode_block_frames

    payloads = []
    for slot in range(host.shape[0]):
        words = host[slot]
        if words.any():
            payloads.append(
                rformat.serialize(RoaringBitmap.from_dense_words(words))
            )
        else:
            payloads.append(b"")
    return payloads, len(encode_block_frames(payloads))


def decode_row_frames(payloads: list[bytes], shape: tuple) -> np.ndarray:
    """Inverse of encode_row_frames: rebuild the dense [slots, words]
    uint32 array. Byte-identical round trip — this IS the result path
    when the wire sim is on, so a codec bug is a visible wrong answer,
    not a silent accounting error."""
    from pilosa_tpu.roaring import format as rformat

    out = np.zeros(shape, np.uint32)
    for slot, payload in enumerate(payloads):
        if not payload:
            continue
        bm, _ = rformat.deserialize(payload)
        out[slot] = bm.dense_range_words32(0, WORDS_PER_SHARD * 32)
    return out


# ------------------------------------------------------ global counters


class ReduceStats:
    """Process-wide dist_reduce_* counters (served on /metrics and
    /debug/vars). Lock kept tiny: a handful of integer adds per device
    dispatch, invisible next to the dispatch itself."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.dispatches = 0
            self.hier_dispatches = 0
            self.dense_bytes = 0
            self.actual_bytes = 0
            self.intra_bytes = 0
            self.row_gathers = 0
            self.row_dense_bytes = 0
            self.row_actual_bytes = 0

    def note_reduce(self, dense: int, actual: int, intra: int,
                    hier: bool) -> None:
        with self._lock:
            self.dispatches += 1
            self.hier_dispatches += 1 if hier else 0
            self.dense_bytes += dense
            self.actual_bytes += actual
            self.intra_bytes += intra

    def note_row_gather(self, dense: int, actual: int) -> None:
        with self._lock:
            self.row_gathers += 1
            self.row_dense_bytes += dense
            self.row_actual_bytes += actual

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dispatches": self.dispatches,
                "hier_dispatches": self.hier_dispatches,
                "dense_bytes": self.dense_bytes,
                "actual_bytes": self.actual_bytes,
                "intra_bytes": self.intra_bytes,
                "row_gathers": self.row_gathers,
                "row_dense_bytes": self.row_dense_bytes,
                "row_actual_bytes": self.row_actual_bytes,
            }


_STATS = ReduceStats()


def global_reduce_stats() -> ReduceStats:
    return _STATS
