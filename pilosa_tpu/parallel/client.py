"""Internal node-to-node HTTP client.

Reference: http/client.go InternalClient (SURVEY.md §2 #17) — remote
query, routed imports, fragment block lists / block data for anti-entropy,
fragment data for resize, schema fetch, cluster messages. JSON bodies
(the reference uses protobuf; this wire is host-control-plane only).
"""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request


class ClientError(Exception):
    pass


class InternalClient:
    def __init__(self, timeout: float = 30.0, insecure_tls: bool = False):
        """insecure_tls accepts self-signed node certificates (reference
        tls.skip-verify), scoped to THIS client only — plumbed from the
        owning server's config so one skip-verify server can't disable
        certificate verification for other servers in the same process."""
        self.timeout = timeout
        self._ssl_context: ssl.SSLContext | None = None
        if insecure_tls:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            self._ssl_context = ctx

    # -------------------------------------------------------------- helpers

    def _call(self, method: str, url: str, body: bytes | None = None,
              content_type: str = "application/json", raw: bool = False):
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout, context=self._ssl_context
            ) as resp:
                data = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise ClientError(f"{method} {url}: HTTP {e.code}: {detail}") from e
        except urllib.error.URLError as e:
            raise ClientError(f"{method} {url}: {e.reason}") from e
        return data if raw else json.loads(data or b"{}")

    # ---------------------------------------------------------------- query

    def query_node(self, uri: str, index: str, pql: str, shards: list[int],
                   remote: bool = True) -> dict:
        """One sub-query carrying an explicit shard list (reference
        QueryRequest{Remote: true, Shards: [...]} — SURVEY.md §3.2)."""
        qs = f"?shards={','.join(map(str, shards))}"
        if remote:
            qs += "&remote=true"
        return self._call(
            "POST", f"{uri}/index/{index}/query{qs}", pql.encode(),
            content_type="text/plain",
        )

    # --------------------------------------------------------------- import

    def import_bits(self, uri: str, index: str, field: str, rows, columns,
                    timestamps=None, clear: bool = False) -> int:
        payload: dict = {"rows": list(map(int, rows)),
                         "columns": list(map(int, columns)), "clear": clear}
        if timestamps is not None:
            payload["timestamps"] = timestamps
        out = self._call(
            "POST", f"{uri}/index/{index}/field/{field}/import?remote=true",
            json.dumps(payload).encode(),
        )
        return out.get("changed", 0)

    def import_values(self, uri: str, index: str, field: str, columns, values,
                      clear: bool = False) -> int:
        out = self._call(
            "POST", f"{uri}/index/{index}/field/{field}/import-value?remote=true",
            json.dumps({"columns": list(map(int, columns)),
                        "values": list(map(int, values)), "clear": clear}).encode(),
        )
        return out.get("changed", 0)

    # ----------------------------------------------------- fragments / sync

    def fragment_blocks(self, uri: str, index: str, field: str, view: str,
                        shard: int) -> list[tuple[int, str]]:
        out = self._call(
            "GET",
            f"{uri}/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}",
        )
        return [(b["block"], b["checksum"]) for b in out.get("blocks", [])]

    def fragment_block_bitmap(self, uri: str, index: str, field: str,
                              view: str, shard: int, block: int):
        """One checksum block's bits as a parsed RoaringBitmap (binary
        data plane: ~O(bitmap bytes) on the wire, not JSON int lists)."""
        from pilosa_tpu.roaring.format import load

        raw = self._call(
            "GET",
            f"{uri}/internal/fragment/block/data?index={index}&field={field}"
            f"&view={view}&shard={shard}&block={block}",
            raw=True,
        )
        bitmap, _ = load(raw)
        return bitmap

    def fragment_data(self, uri: str, index: str, field: str, view: str,
                      shard: int) -> bytes:
        return self._call(
            "GET",
            f"{uri}/internal/fragment/data?index={index}&field={field}"
            f"&view={view}&shard={shard}",
            raw=True,
        )

    def fragment_catalog(self, uri: str, index: str) -> list[dict]:
        out = self._call("GET", f"{uri}/internal/fragments?index={index}")
        return out.get("fragments", [])

    # ------------------------------------------------------ schema / cluster

    def schema(self, uri: str) -> dict:
        return self._call("GET", f"{uri}/internal/schema")

    def send_message(self, uri: str, message: dict) -> dict:
        return self._call(
            "POST", f"{uri}/internal/cluster/message",
            json.dumps(message).encode(),
        )

    def status(self, uri: str) -> dict:
        return self._call("GET", f"{uri}/status")

    def translate_keys(self, uri: str, namespace: str, keys: list[str],
                       create: bool) -> list:
        out = self._call(
            "POST", f"{uri}/internal/translate/keys",
            json.dumps({"namespace": namespace, "keys": keys,
                        "create": create}).encode(),
        )
        return out.get("ids", [])

    def translate_log(self, uri: str, offset: int) -> bytes:
        return self._call(
            "GET", f"{uri}/internal/translate/data?offset={offset}", raw=True
        )
