"""Internal node-to-node HTTP client.

Reference: http/client.go InternalClient (SURVEY.md §2 #17) — remote
query, routed imports, fragment block lists / block data for anti-entropy,
fragment data for resize, schema fetch, cluster messages. Data-plane hops
(imports, query results, block repair) are binary — protobuf or roaring
octet-stream — with per-peer JSON fallback on 406; control-plane messages
stay JSON.
"""

from __future__ import annotations

import json
import ssl

from pilosa_tpu.parallel.connpool import ConnectionPool
from pilosa_tpu.utils import as_int_list


class ClientError(Exception):
    """Peer RPC failure. ``status`` is the HTTP status code, or None for
    transport-level faults (connection refused/reset, DNS, timeout).
    ``is_node_fault`` distinguishes 'the NODE is unhealthy' (transport or
    5xx — retry another replica, mark DEGRADED) from 'the QUERY is bad'
    (4xx — deterministic, every replica would answer the same; must
    propagate, never degrade a healthy node)."""

    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status

    @property
    def is_node_fault(self) -> bool:
        return self.status is None or self.status >= 500


class InternalClient:
    def __init__(self, timeout: float = 30.0, insecure_tls: bool = False,
                 pool_size: int = 8):
        """insecure_tls accepts self-signed node certificates (reference
        tls.skip-verify), scoped to THIS client only — plumbed from the
        owning server's config so one skip-verify server can't disable
        certificate verification for other servers in the same process.

        ``pool_size`` bounds the keep-alive connections retained per peer
        (parallel/connpool.py): every hop through this client reuses a
        pooled persistent connection instead of paying TCP connect (and a
        server-side handler-thread spawn) per request. Checkout is
        exclusive, so concurrent requests — a hedge leg racing its
        primary included — always ride distinct connections."""
        self.timeout = timeout
        # peers that answered 406 to a protobuf hop: a mixed-capability
        # cluster (one node without the protobuf runtime) falls back to
        # JSON per peer instead of failing every internal request
        self._json_only_peers: set[str] = set()
        # peers whose wire predates /internal/query-batch (404/405 once):
        # the wave batcher falls back to per-query dispatch for them
        self._no_batch_peers: set[str] = set()
        # peers whose wire predates the batched sync routes
        # (/internal/sync/manifest + /internal/sync/blocks, 404/405
        # once): anti-entropy falls back to the per-fragment
        # blocks/block-data path for them (mixed-version clusters)
        self._no_manifest_peers: set[str] = set()
        # Repair/resize data-plane shaping, wired by the owning server:
        # ``pacer`` (parallel/pacer.py) bounds transfer rate + inflight;
        # ``compress_repair`` advertises Accept-Encoding: deflate on
        # fragment and delta payload fetches (the peer compresses only
        # when it actually shrinks the body).
        self.pacer = None
        self.compress_repair = True
        self._ssl_context: ssl.SSLContext | None = None
        if insecure_tls:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            self._ssl_context = ctx
        self.pool = ConnectionPool(max_per_host=pool_size, timeout=timeout,
                                   ssl_context=self._ssl_context)

    # -------------------------------------------------------------- helpers

    def _proto_ok(self, uri: str) -> bool:
        from pilosa_tpu import wire

        return wire.available() and uri not in self._json_only_peers

    @staticmethod
    def _is_406(err: "ClientError") -> bool:
        return err.status == 406

    def _pace(self, nbytes: int) -> None:
        """Debit a data-plane transfer from the repair pacer (no-op when
        the server wired none — bare clients in tests/tools)."""
        if self.pacer is not None:
            self.pacer.consume(nbytes)

    def _repair_slot(self):
        """Inflight-bound context for one repair transfer."""
        if self.pacer is not None:
            return self.pacer.slot()
        import contextlib

        return contextlib.nullcontext()

    def _repair_headers(self, trace: str | None = None) -> dict | None:
        headers = {}
        if self.compress_repair:
            headers["Accept-Encoding"] = "deflate"
        if trace is not None:
            from pilosa_tpu.utils.tracing import TRACE_HEADER

            headers[TRACE_HEADER] = trace
        return headers or None

    @staticmethod
    def _decode_body(resp) -> bytes:
        """Response body with any negotiated Content-Encoding undone."""
        if (resp.headers.get("Content-Encoding") or "").lower() == "deflate":
            import zlib

            return zlib.decompress(resp.data)
        return resp.data

    def _call(self, method: str, url: str, body: bytes | None = None,
              content_type: str = "application/json", raw: bool = False,
              accept: str | None = None, headers: dict | None = None,
              timeout: float | None = None, want_response: bool = False):
        hdrs = dict(headers or {})
        if body is not None:
            hdrs.setdefault("Content-Type", content_type)
        if accept is not None:
            hdrs.setdefault("Accept", accept)
        import http.client as _hc

        try:
            resp = self.pool.request(method, url, body=body, headers=hdrs,
                                     timeout=timeout)
        except (OSError, _hc.HTTPException) as e:
            # transport-stage faults only (connect refused, DNS, reset,
            # TLS failure, read-stage timeout on a stalled peer) map to
            # the node-level ClientError (status None) callers classify;
            # programming errors (bad URI, bad header types) propagate —
            # wrapping them would mark a healthy node DEGRADED and bury
            # the bug in replica-fallback noise
            raise ClientError(f"{method} {url}: {str(e) or type(e).__name__}"
                              ) from e
        if 300 <= resp.status < 400:
            # the pool does not follow redirects (urllib did): a proxy's
            # 3xx must surface as a readable error, not as JSONDecodeError
            # on an HTML body
            location = resp.headers.get("Location", "")
            raise ClientError(
                f"{method} {url}: HTTP {resp.status} redirect"
                + (f" to {location}" if location else "")
                + " (redirects are not followed)",
                status=resp.status,
            )
        if resp.status >= 400:
            if "x-protobuf" in (resp.headers.get("Content-Type") or ""):
                # protobuf-negotiated error body: surface the readable
                # QueryResponse.err, not raw tag/length bytes
                try:
                    from pilosa_tpu.wire.serializer import decode_results_json

                    detail = decode_results_json(resp.data).get("error", "")
                except Exception:
                    detail = resp.data.decode(errors="replace")
            else:
                detail = resp.data.decode(errors="replace")
            raise ClientError(
                f"{method} {url}: HTTP {resp.status}: {detail}",
                status=resp.status,
            )
        if want_response:
            return resp
        return resp.data if raw else json.loads(resp.data or b"{}")

    # ---------------------------------------------------------------- query

    def query_node(self, uri: str, index: str, pql: str, shards: list[int],
                   remote: bool = True, deadline=None,
                   trace: str | None = None,
                   profile: bool = False) -> dict:
        """One sub-query carrying an explicit shard list (reference
        QueryRequest{Remote: true, Shards: [...]} — SURVEY.md §3.2).

        Negotiates a protobuf response (Accept: x-protobuf) so remote row
        results travel as varint-packed column ids instead of JSON int
        lists; decoded to the same dict shapes either way. A peer whose
        wire lacks protobuf answers 406 once, then gets JSON.

        ``deadline`` (qos.Deadline) rides the hop as a remaining-budget
        header AND caps the transport timeout, so a stalled peer is
        abandoned when the root's budget runs out — not after the full
        client timeout.

        ``trace`` (an ``X-Pilosa-Trace`` value) marks the hop as part of
        a sampled trace: the peer roots a span under it and returns its
        finished subtree as a ``"trace"`` key in the response dict.

        ``profile`` asks the peer for its per-AST-node execution profile
        (PQL PROFILE — docs/OBSERVABILITY.md), returned as a
        ``"profile"`` key; profiled hops force the JSON envelope (the
        profile rides only the JSON wire), which is fine for a debugging
        surface that is off on every normal request."""
        def hop_kwargs():
            """Deadline header + transport cap from the budget remaining
            NOW — recomputed for the JSON fallback after a 406, so a
            failed protobuf attempt's latency is not re-granted to the
            peer as budget."""
            headers = {}
            if trace is not None:
                from pilosa_tpu.utils.tracing import TRACE_HEADER

                headers[TRACE_HEADER] = trace
            if deadline is None:
                return headers, None
            from pilosa_tpu.qos.deadline import DEADLINE_HEADER

            deadline.check("remote hop")
            headers[DEADLINE_HEADER] = str(deadline.to_millis())
            return (headers,
                    min(self.timeout, max(deadline.remaining(), 1e-3)))

        qs = f"?shards={','.join(map(str, shards))}"
        if remote:
            qs += "&remote=true"
        if profile:
            qs += "&profile=true"
        url = f"{uri}/index/{index}/query{qs}"
        if self._proto_ok(uri) and not profile:
            from pilosa_tpu.wire.serializer import decode_results_json

            headers, timeout = hop_kwargs()
            try:
                raw = self._call(
                    "POST", url, pql.encode(), content_type="text/plain",
                    raw=True, accept="application/x-protobuf",
                    headers=headers, timeout=timeout,
                )
            except ClientError as e:
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
            else:
                out = decode_results_json(raw)
                if "error" in out:
                    # error text inside a 200 protobuf envelope: our own
                    # server never produces this (ApiErrors ride 4xx
                    # status even in protobuf), so it can only be an
                    # odd/older peer — classify as a node fault (status
                    # None) so the caller keeps its replica fallback
                    raise ClientError(f"POST {url}: {out['error']}")
                return out
        headers, timeout = hop_kwargs()
        return self._call("POST", url, pql.encode(),
                          content_type="text/plain", headers=headers,
                          timeout=timeout)

    def supports_batch(self, uri: str) -> bool:
        """Whether the peer is believed to speak /internal/query-batch
        (flips False after one 404/405 — older wire)."""
        return uri not in self._no_batch_peers

    def query_batch(self, uri: str, items: list) -> list[dict]:
        """Ship several same-node remote sub-queries as ONE internal
        request (the cluster-wide analog of the local wave coalescer —
        server/pipeline.py): ``items`` is ``[(index, pql, shards), ...]``
        (optionally a 4th element: the item's ``X-Pilosa-Trace`` value —
        sampled wavemates keep their trace context through the shared
        POST, and the peer's per-item span subtree rides back as a
        ``"trace"`` key); returns one response dict per item, each either
        ``{"results": [...]}`` or ``{"error": ..., "status": ...}``.

        Negotiates a protobuf body/response like query_node (per-peer 406
        fallback to JSON). A peer without the route answers 404/405 —
        recorded in ``_no_batch_peers`` and re-raised so the wave batcher
        falls back to per-query dispatch for that peer."""
        url = f"{uri}/internal/query-batch"
        if self._proto_ok(uri):
            from pilosa_tpu.wire.serializer import (
                decode_batch_responses,
                encode_batch_request,
            )

            try:
                raw = self._call(
                    "POST", url, encode_batch_request(items),
                    content_type="application/x-protobuf", raw=True,
                    accept="application/x-protobuf",
                )
            except ClientError as e:
                if e.status in (404, 405):
                    self._no_batch_peers.add(uri)
                    raise
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
            else:
                return decode_batch_responses(raw)
        body = json.dumps({"queries": [
            {"index": item[0], "query": item[1],
             "shards": [int(s) for s in item[2]],
             **({"trace": item[3]} if len(item) > 3 and item[3] else {})}
            for item in items
        ]}).encode()
        try:
            out = self._call("POST", url, body)
        except ClientError as e:
            if e.status in (404, 405):
                self._no_batch_peers.add(uri)
            raise
        return out.get("responses", [])

    # --------------------------------------------------------------- import

    def import_bits(self, uri: str, index: str, field: str, rows, columns,
                    timestamps=None, clear: bool = False) -> int:
        """Routed bit import. Protobuf body when both ends speak it
        (the reference's internal hops are all protobuf — SURVEY.md §2
        #16-17: varint-packed ids, ~2-5x smaller than JSON int lists);
        JSON fallback otherwise, including on a peer's 406."""
        url = f"{uri}/index/{index}/field/{field}/import?remote=true"
        if self._proto_ok(uri):
            from pilosa_tpu.wire.serializer import encode_import_request

            body = encode_import_request(index, field, rows, columns,
                                         timestamps=timestamps, clear=clear)
            try:
                out = self._call("POST", url, body,
                                 content_type="application/x-protobuf")
                return out.get("changed", 0)
            except ClientError as e:
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
        payload: dict = {"rows": as_int_list(rows),
                         "columns": as_int_list(columns),
                         "clear": clear}
        if timestamps is not None:
            payload["timestamps"] = timestamps
        out = self._call("POST", url, json.dumps(payload).encode())
        return out.get("changed", 0)

    def import_values(self, uri: str, index: str, field: str, columns, values,
                      clear: bool = False) -> int:
        url = f"{uri}/index/{index}/field/{field}/import-value?remote=true"
        if self._proto_ok(uri):
            from pilosa_tpu.wire.serializer import (
                encode_import_value_request,
            )

            body = encode_import_value_request(index, field, columns, values,
                                               clear=clear)
            try:
                out = self._call("POST", url, body,
                                 content_type="application/x-protobuf")
                return out.get("changed", 0)
            except ClientError as e:
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
        out = self._call(
            "POST", url,
            json.dumps({"columns": as_int_list(columns),
                        "values": as_int_list(values),
                        "clear": clear}).encode(),
        )
        return out.get("changed", 0)

    def import_roaring(self, uri: str, index: str, field: str, shard: int,
                       data: bytes) -> int:
        """Whole-shard roaring body (O(bitmap bytes) on the wire): the
        bulk path for routed set-bit imports. remote=true: the slice of
        an already-admitted edge batch must not bounce off the peer's
        max-writes-per-request."""
        out = self._call(
            "POST",
            f"{uri}/index/{index}/field/{field}/import-roaring/{shard}"
            "?remote=true",
            data, content_type="application/octet-stream",
        )
        return out.get("changed", 0)

    # ----------------------------------------------------- fragments / sync

    def fragment_blocks(self, uri: str, index: str, field: str, view: str,
                        shard: int) -> list[tuple[int, str]]:
        out = self._call(
            "GET",
            f"{uri}/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}",
        )
        return [(b["block"], b["checksum"]) for b in out.get("blocks", [])]

    def fragment_block_bitmap(self, uri: str, index: str, field: str,
                              view: str, shard: int, block: int):
        """One checksum block's bits as a parsed RoaringBitmap (binary
        data plane: ~O(bitmap bytes) on the wire, not JSON int lists).
        The per-block fallback for peers without /internal/sync/blocks;
        still paced — a mixed-version repair storm must obey the same
        budget as the fast path."""
        from pilosa_tpu.roaring.format import load

        with self._repair_slot():
            raw = self._call(
                "GET",
                f"{uri}/internal/fragment/block/data?index={index}"
                f"&field={field}&view={view}&shard={shard}&block={block}",
                raw=True,
            )
        self._pace(len(raw))
        bitmap, _ = load(raw)
        return bitmap

    def fragment_data(self, uri: str, index: str, field: str, view: str,
                      shard: int) -> bytes:
        """Whole-fragment payload (resize moves). Compressed on the wire
        when ``repair-compression`` is on and the peer honors deflate;
        paced by wire bytes (what the network actually carried), not the
        inflated size."""
        with self._repair_slot():
            resp = self._call(
                "GET",
                f"{uri}/internal/fragment/data?index={index}&field={field}"
                f"&view={view}&shard={shard}",
                headers=self._repair_headers(), want_response=True,
            )
        self._pace(len(resp.data))
        return self._decode_body(resp)

    # ------------------------------------------------- anti-entropy fast path

    def supports_sync_manifest(self, uri: str) -> bool:
        """Whether the peer is believed to speak the batched sync routes
        (flips False after one 404/405 — older wire)."""
        return uri not in self._no_manifest_peers

    def sync_manifest(self, uri: str, index: str, trace: str | None = None
                      ) -> list[tuple[str, str, int, list]]:
        """One RTT for a whole index's sync state: every (field, view,
        shard) → [(block, checksum)] the peer holds. Protobuf with the
        per-peer 406 JSON fallback; a peer without the route answers
        404/405, recorded in ``_no_manifest_peers`` and re-raised so the
        caller falls back to the per-fragment blocks path. ``trace``
        (X-Pilosa-Trace) lets a sampled repair pass attribute the peer's
        serving cost in its local span ring."""
        from pilosa_tpu.utils.stats import global_stats

        url = f"{uri}/internal/sync/manifest?index={index}"
        trace_headers = None
        if trace is not None:
            from pilosa_tpu.utils.tracing import TRACE_HEADER

            trace_headers = {TRACE_HEADER: trace}
        global_stats().count("sync_manifest_fetches", 1)
        if self._proto_ok(uri):
            from pilosa_tpu.wire.serializer import decode_sync_manifest

            try:
                raw = self._call("GET", url, raw=True,
                                 accept="application/x-protobuf",
                                 headers=trace_headers)
            except ClientError as e:
                if e.status in (404, 405):
                    self._no_manifest_peers.add(uri)
                    raise
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
            else:
                return decode_sync_manifest(raw)
        try:
            out = self._call("GET", url, headers=trace_headers)
        except ClientError as e:
            if e.status in (404, 405):
                self._no_manifest_peers.add(uri)
            raise
        return [
            (e.get("field", ""), e.get("view", "standard"),
             int(e.get("shard", 0)),
             [(int(b["block"]), b["checksum"])
              for b in e.get("blocks", [])])
            for e in out.get("fragments", [])
        ]

    def sync_blocks(self, uri: str, index: str, fragments,
                    trace: str | None = None) -> list:
        """Multi-block delta fetch: ``fragments`` is
        ``[(field, view, shard, [block, ...]), ...]``; returns one parsed
        RoaringBitmap per requested block, in flattened request order.
        One POST replaces one block-data GET per differing block; the
        response is a length-prefixed roaring stream (optionally
        deflated), paced by wire bytes. 404/405 records the peer as
        old-wire and re-raises (caller drops to per-block GETs)."""
        from pilosa_tpu.roaring.format import load
        from pilosa_tpu.utils.stats import global_stats
        from pilosa_tpu.wire.serializer import decode_block_frames

        url = f"{uri}/internal/sync/blocks"
        n_blocks = sum(len(f[3]) for f in fragments)
        resp = None
        if self._proto_ok(uri):
            from pilosa_tpu.wire.serializer import (
                encode_sync_blocks_request,
            )

            try:
                with self._repair_slot():
                    resp = self._call(
                        "POST", url,
                        encode_sync_blocks_request(index, fragments),
                        content_type="application/x-protobuf",
                        headers=self._repair_headers(trace),
                        want_response=True,
                    )
            except ClientError as e:
                if e.status in (404, 405):
                    self._no_manifest_peers.add(uri)
                    raise
                if not self._is_406(e):
                    raise
                self._json_only_peers.add(uri)
        if resp is None:
            body = json.dumps({"index": index, "fragments": [
                {"field": f, "view": v, "shard": int(s),
                 "blocks": [int(b) for b in blocks]}
                for f, v, s, blocks in fragments
            ]}).encode()
            try:
                with self._repair_slot():
                    resp = self._call("POST", url, body,
                                      headers=self._repair_headers(trace),
                                      want_response=True)
            except ClientError as e:
                if e.status in (404, 405):
                    self._no_manifest_peers.add(uri)
                raise
        self._pace(len(resp.data))
        stats = global_stats()
        stats.count("sync_delta_blocks_requests", 1)
        stats.count("sync_delta_blocks_fetched", n_blocks)
        stats.count("sync_delta_blocks_bytes", len(resp.data))
        frames = decode_block_frames(self._decode_body(resp))
        if len(frames) != n_blocks:
            raise ClientError(
                f"POST {url}: {len(frames)} block frames for {n_blocks} "
                "requested blocks"
            )
        return [load(frame)[0] for frame in frames]

    def fragment_catalog(self, uri: str, index: str) -> list[dict]:
        out = self._call("GET", f"{uri}/internal/fragments?index={index}")
        return out.get("fragments", [])

    # ------------------------------------------------------------------- cdc

    def wal_tail(self, uri: str, since: int | None = None,
                 max_bytes: int | None = None, cursor: str | None = None):
        """One CDC tail poll (``GET /internal/wal/tail`` — cdc/feed.py):
        returns ``(events, next_seq, durable_seq)`` where events is
        ``[(seq, rtype, key, body), ...]`` parsed from the frame stream.
        ``since=None`` is the attach handshake (registers ``cursor`` at
        the producer's durable seq, empty body). Rides the repair pacer
        + deflate negotiation like the sync data plane — feed catch-up
        after a follower restart is repair traffic and must obey the
        same budget. A 410 raises FeedGone: the cursor fell off the
        retained tail (or the producer restarted), restart from a
        snapshot."""
        from urllib.parse import quote

        from pilosa_tpu.cdc.feed import (
            DURABLE_SEQ_HEADER,
            NEXT_SEQ_HEADER,
            FeedGone,
            iter_frames,
        )

        params = []
        if since is not None:
            params.append(f"since={int(since)}")
        if max_bytes is not None:
            params.append(f"max-bytes={int(max_bytes)}")
        if cursor:
            params.append(f"cursor={quote(cursor, safe='')}")
        url = (f"{uri}/internal/wal/tail"
               + (("?" + "&".join(params)) if params else ""))
        try:
            with self._repair_slot():
                resp = self._call("GET", url,
                                  headers=self._repair_headers(),
                                  want_response=True)
        except ClientError as e:
            if e.status == 410:
                restart, floor = -1, 0
                try:
                    detail = json.loads(
                        str(e).split(": ", 2)[-1] or "{}")
                    restart = int(detail.get("restartFrom", -1))
                    floor = int(detail.get("floor", 0))
                except (ValueError, TypeError):
                    pass
                raise FeedGone(restart, floor) from e
            raise
        self._pace(len(resp.data))
        data = self._decode_body(resp)
        events = list(iter_frames(data))
        next_seq = int(resp.headers.get(NEXT_SEQ_HEADER, -1))
        durable = int(resp.headers.get(DURABLE_SEQ_HEADER, -1))
        if since is not None:
            # a torn frame stream (iter_frames stopped early) must not
            # advance the cursor past frames it never yielded: every seq
            # in (since, next_seq] is guaranteed present in a whole
            # body, so resume from the last frame actually parsed
            expect = events[-1][0] if events else since
            if next_seq > expect:
                next_seq = expect
        return events, next_seq, durable

    # ------------------------------------------------------ schema / cluster

    def schema(self, uri: str) -> dict:
        return self._call("GET", f"{uri}/internal/schema")

    def send_message(self, uri: str, message: dict) -> dict:
        return self._call(
            "POST", f"{uri}/internal/cluster/message",
            json.dumps(message).encode(),
        )

    def heatmap(self, uri: str, k: int = 0,
                timeout: float | None = None) -> dict:
        """Peer heat snapshot (``/debug/heatmap``; ``k=0`` = full
        table). The autopilot coordinator assembles cluster-wide shard
        heat from every member's local decayed counters — heat is
        recorded where the shard EXECUTES, so no single node sees the
        whole picture."""
        return self._call("GET", f"{uri}/debug/heatmap?k={int(k)}",
                          timeout=timeout)

    def status(self, uri: str, timeout: float | None = None) -> dict:
        """``timeout`` overrides the client default for THIS probe —
        liveness checks (heartbeat, quorum, death corroboration) use a
        tight dedicated cap so one hung peer cannot stall the loop that
        detects every other failure."""
        return self._call("GET", f"{uri}/status", timeout=timeout)

    def translate_keys(self, uri: str, namespace: str, keys: list[str],
                       create: bool) -> list:
        out = self._call(
            "POST", f"{uri}/internal/translate/keys",
            json.dumps({"namespace": namespace, "keys": keys,
                        "create": create}).encode(),
        )
        return out.get("ids", [])

    def translate_log(self, uri: str, offset: int) -> bytes:
        return self._call(
            "GET", f"{uri}/internal/translate/data?offset={offset}", raw=True
        )
