"""Background scrubber: paced disk verification + quarantine + repair.

Verified loads (storage/integrity.py) catch rot at OPEN; a long-lived
node can go months without reopening a fragment, so this pass walks the
owned fragments on a budget and re-derives each snapshot's block
digests from the BYTES ON DISK, comparing them against the checksum
sidecar written at snapshot time. The comparison is disk-vs-disk — the
live bitmap never enters the verdict, so a busy write path cannot mask
rot and a scrub cannot be fooled by a healthy in-memory copy of a
rotten file.

On confirmed corruption the fragment is handled by replica topology:

- **Replicas exist** (cluster, replica_n > 1): the fragment is
  QUARANTINED whole — dropped from the view (never served again),
  files renamed to ``.quarantine-*`` — and READ-REPAIRED from the
  healthy replicas over the existing ``sync/blocks`` delta wire
  (cluster._sync_fragment: one manifest RTT + one multi-block POST,
  conflict-aware merge rules intact), then snapshotted. Single-replica
  corruption heals with zero lost acked writes (every acked write also
  lives on the healthy replica) and zero corrupt bytes ever served.
- **No replicas**: the LIVE bitmap is the only other copy; the corrupt
  file is renamed aside and a fresh snapshot is written from memory
  (self-heal). If the live state itself was loaded from the corrupt
  file before verification existed, only a backup restore can help —
  the quarantine artifact is kept for that forensics.

Budget: ``scrub-interval`` seconds between passes (0 = disabled) and a
``scrub-max-bytes-per-sec`` token bucket (parallel/pacer.py RepairPacer
— the PR-4 shape), so a scrub storm cannot starve serving I/O; the
bench gate holds the serving plateau at >= 0.97x with the scrubber on.

A racing snapshot can swap file+sidecar mid-read and fake a mismatch:
every corruption verdict is re-derived under the fragment lock before
quarantine acts.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from pilosa_tpu.parallel.pacer import RepairPacer
from pilosa_tpu.roaring import kernels
from pilosa_tpu.storage.integrity import (
    CorruptFragmentError,
    global_integrity,
    quarantine_paths,
    verify_fragment_file,
)

_LOG = logging.getLogger("pilosa_tpu.parallel.scrub")


class Scrubber:
    """One holder's background integrity scrubber (Server.open wires it
    when ``scrub-interval`` > 0; ``POST /internal/scrub`` and the CLI
    ``check --host`` run single passes on demand)."""

    def __init__(self, holder, cluster=None, interval_s: float = 0.0,
                 max_bytes_per_sec: float = 0.0, stats=None, logger=None):
        self.holder = holder
        self.cluster = cluster
        self.interval_s = float(interval_s)
        self.pacer = RepairPacer(max_bytes_per_sec=max_bytes_per_sec,
                                 stats=stats)
        self.logger = logger or _LOG
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        self._pass_lock = threading.Lock()
        # counters (api.integrity_metrics -> /metrics; zeros from
        # scrape one)
        self.passes = 0
        self.fragments_scanned = 0
        self.bytes_scanned = 0
        self.corruptions = 0
        self.repaired = 0
        self.self_healed = 0
        self.unrepaired = 0
        self.last_pass_s = 0.0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Scrubber":
        if self.interval_s <= 0 or self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="storage-scrub")
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()

    def _loop(self) -> None:
        while not self._closed.wait(self.interval_s):
            try:
                self.scrub_pass()
            except Exception as e:  # noqa: BLE001 — the ticker must
                # outlive any one pass's surprise (a fragment deleted
                # mid-walk, a peer dying mid-repair)
                self.logger.warning("scrub pass failed: %s", e)

    # ----------------------------------------------------------------- pass

    def scrub_pass(self) -> dict:
        """Walk every owned fragment once; verify, quarantine, repair.
        Returns the pass record (also folded into the counters)."""
        with self._pass_lock:  # one pass at a time (ticker + on-demand)
            t0 = time.perf_counter()
            bytes_before = self.bytes_scanned
            out = {"scanned": 0, "bytes": 0, "corrupt": 0, "repaired": 0,
                   "self_healed": 0, "unrepaired": 0, "skipped": 0}
            # every LOCAL fragment is scanned — owned fragments because
            # this node serves them, stray (unowned, post-resize)
            # copies because cleanup_unowned defers their deletion
            # until an owner absorbs them, and absorbing rot would
            # replicate it; the heal policy differs by ownership below
            for iname, idx in list(self.holder.indexes.items()):
                for fname, field in list(idx.fields.items()):
                    for vname, view in list(field.views.items()):
                        for shard in sorted(view.fragments):
                            if self._closed.is_set():
                                break
                            frag = view.fragment(shard)
                            if frag is None:
                                continue
                            self._scrub_fragment(iname, idx, fname, view,
                                                 shard, frag, out)
            self.passes += 1
            self.last_pass_s = time.perf_counter() - t0
            out["bytes"] = self.bytes_scanned - bytes_before
            out["wall_s"] = round(self.last_pass_s, 3)
            return out

    def _verify_on_disk(self, frag, count: bool = True) -> None:
        """Disk-vs-disk verification of one fragment (the shared
        integrity.verify_fragment_file recipe, so the scrubber, the
        chaos oracle, and CLI check can never drift apart), paced and
        counted. ``count=False`` on the locked confirm re-read keeps
        the scanned/bytes counters one-per-fragment. Raises
        CorruptFragmentError."""
        try:
            # build_bitmap=False: the kernel parser digests the snapshot
            # bytes directly (roaring/kernels.py) — the scrubber never
            # needs the Container tree, only the verdict
            _bitmap, data, _ops_at = verify_fragment_file(
                frag.path, build_bitmap=False)
        except CorruptFragmentError:
            raise
        finally:
            # pace/count by what was actually read, even on corruption
            try:
                size = os.path.getsize(frag.path)
            except OSError:
                size = 0
            self.pacer.consume(size)
            if count:
                self.fragments_scanned += 1
                self.bytes_scanned += size

    def _scrub_fragment(self, iname, idx, fname, view, shard, frag,
                        out) -> None:
        try:
            self._verify_on_disk(frag)
        except OSError:
            out["skipped"] += 1  # deleted/rotated mid-walk: not rot
            return
        except CorruptFragmentError:
            pass  # confirm under the lock below
        else:
            out["scanned"] += 1
            return
        # Re-derive the verdict under the fragment lock: a snapshot
        # racing the unlocked read swaps file+sidecar and can fake a
        # mismatch; under the lock the pair is stable.
        with frag.lock:
            try:
                self._verify_on_disk(frag, count=False)
            except OSError:
                out["skipped"] += 1
                return
            except CorruptFragmentError as err:
                confirmed = err
            else:
                out["scanned"] += 1
                return
        out["scanned"] += 1
        out["corrupt"] += 1
        self.corruptions += 1
        global_integrity().count("verify_failures")
        self.logger.error("scrub: %s", confirmed)
        self._heal(iname, idx, fname, view, shard, frag, confirmed, out)

    # ----------------------------------------------------------------- heal

    def _repairable(self, iname: str, shard: int) -> bool:
        """Read-repair applies to fragments this node OWNS with other
        replicas holding copies. A stray (unowned) copy self-heals from
        its live bitmap instead: cleanup_unowned defers its deletion
        until an owner absorbs it, so its bits must survive locally —
        but re-fetching data this node does not own would be wrong."""
        if self.cluster is None:
            return False
        owners = self.cluster.shard_nodes(iname, shard)
        return (any(n.id == self.cluster.local.id for n in owners)
                and any(n.id != self.cluster.local.id for n in owners))

    def _fetch_replica_copy(self, iname, fname, vname, shard):
        """One healthy replica's COMPLETE fragment content over the
        sync wire (one manifest RTT + one multi-block sync/blocks POST
        per candidate; whole-fragment GET for legacy-wire peers), with
        every fetched block digest-verified against that replica's own
        manifest — the wire is not trusted either. Returns a
        RoaringBitmap or None when no replica could supply a verified
        copy."""
        from pilosa_tpu.roaring import RoaringBitmap
        from pilosa_tpu.storage.integrity import block_digests

        key = (fname, vname, shard)
        replicas = [n for n in self.cluster.shard_nodes(iname, shard)
                    if n.id != self.cluster.local.id]
        client = self.cluster.client
        for node in replicas:
            try:
                if client.supports_sync_manifest(node.uri):
                    entry = None
                    for f, v, s, blocks in client.sync_manifest(
                            node.uri, iname):
                        if (f, v, s) == key:
                            entry = list(blocks)
                            break
                    if entry is None:
                        continue  # replica lacks the fragment
                    wanted = [b for b, _ in entry]
                    bitmaps = client.sync_blocks(
                        node.uri, iname, [(fname, vname, shard, wanted)],
                    )
                    # one batched id kernel per block bitmap, one sort,
                    # one from_ids — not N add_ids merges + a re-walk
                    parts = [kernels.fragment_ids(kernels.flatten(bm))
                             for bm in bitmaps]
                    ids = (np.sort(np.concatenate(parts)) if parts
                           else np.empty(0, np.uint64))
                    if block_digests(ids) != [
                        (int(b), d) for b, d in entry
                    ]:
                        continue  # raced or torn transfer: next replica
                    return RoaringBitmap.from_ids(ids)
                # legacy-wire peer: whole-fragment GET, verified
                # against the peer's per-fragment block checksums (the
                # same no-trust bar as the manifest path — an
                # unverified transfer would launder a flipped bit into
                # a fragment every future scrub pronounces clean)
                blocks = client.fragment_blocks(node.uri, iname, fname,
                                                vname, shard)
                data = client.fragment_data(node.uri, iname, fname,
                                            vname, shard)
                if data:
                    from pilosa_tpu.roaring.format import load_any

                    copy, _ = load_any(data)
                    if block_digests(
                        kernels.fragment_ids(kernels.flatten(copy))
                    ) != [
                        (int(b), d) for b, d in blocks
                    ]:
                        continue  # raced or torn transfer: next replica
                    return copy
            except Exception:  # noqa: BLE001 — transport faults, torn
                # frames: the next replica may still supply a copy
                continue
        return None

    def _heal(self, iname, idx, fname, view, shard, frag, err, out) -> None:
        if self._repairable(iname, shard):
            # Read-repair, REPLACE not union: on-disk rot means the
            # local copy (disk AND whatever was loaded from it) is
            # untrustworthy, and union-merging suspect bits would
            # propagate a flipped-on bit cluster-wide through
            # anti-entropy. The replica copy is fetched FIRST, and the
            # swap (quarantine old artifacts, write the fresh fragment,
            # publish it in the view) is atomic from a reader's view —
            # queries see the old in-memory state or the repaired one,
            # never a missing fragment, so zero corrupt (or absent)
            # responses are served during the window.
            copy = self._fetch_replica_copy(iname, fname, view.name, shard)
            if copy is None:
                self.unrepaired += 1
                out["unrepaired"] += 1
                self.logger.error(
                    "scrub: no healthy replica copy of %s/%s/%s/%d; "
                    "leaving it in place until the next pass",
                    iname, fname, view.name, shard,
                )
                return
            try:
                with view._create_lock:
                    stale = view.fragments.get(shard)
                    if stale is None:
                        return  # concurrently deleted: deletion wins
                    stale.close(discard=True)
                    quarantine_paths(frag.path, reason=str(err))
                    from pilosa_tpu.storage.fragment import Fragment

                    fresh = Fragment(
                        frag.path, iname, fname, view.name, shard,
                        cache_type=view.cache_type,
                        cache_size=view.cache_size, scope=view.scope,
                        wal=view.wal,
                        verify_on_load=view.verify_on_load,
                    ).open()
                    fresh.import_roaring_bitmap(copy)
                    fresh.snapshot()  # durable + fresh sidecar
                    fresh.recalculate_cache()
                    view.fragments[shard] = fresh
            except OSError as e:
                self.unrepaired += 1
                out["unrepaired"] += 1
                self.logger.error(
                    "scrub: read-repair swap of %s/%s/%s/%d failed (%s)",
                    iname, fname, view.name, shard, e,
                )
                return
            global_integrity().count("read_repairs")
            self.repaired += 1
            out["repaired"] += 1
            self.logger.warning(
                "scrub: read-repaired %s/%s/%s/%d byte-identical from a "
                "healthy replica", iname, fname, view.name, shard,
            )
        else:
            # no replica to repair from (single-node, replica_n=1, or a
            # stray unowned copy): the live bitmap is the only other
            # copy — move the rotten file aside and rewrite the
            # snapshot from memory. (If the live state itself was
            # loaded from these bytes, restore from backup; the
            # quarantine artifact is kept for that call.)
            try:
                with frag.lock:
                    if frag._file is not None:
                        frag._file.close()
                        frag._file = None
                    quarantine_paths(frag.path, reason=str(err))
                    frag.snapshot()
            except OSError as e:  # a sick disk (ENOSPC mid-heal):
                # leave it for the next pass, after the probe clears
                self.unrepaired += 1
                out["unrepaired"] += 1
                self.logger.error(
                    "scrub: self-heal of %s/%s/%s/%d failed (%s)",
                    iname, fname, view.name, shard, e,
                )
                return
            global_integrity().count("self_heals")
            self.self_healed += 1
            out["self_healed"] += 1
            self.logger.warning(
                "scrub: re-snapshotted %s/%s/%s/%d from the live bitmap "
                "(no replica copy to read-repair from)",
                iname, fname, view.name, shard,
            )

    # -------------------------------------------------------------- metrics

    def metrics(self) -> dict:
        return {
            "scrub_passes_total": self.passes,
            "scrub_fragments_scanned_total": self.fragments_scanned,
            "scrub_bytes_total": self.bytes_scanned,
            "scrub_corruptions_detected_total": self.corruptions,
            "scrub_read_repairs_total": self.repaired,
            "scrub_self_heals_total": self.self_healed,
            "scrub_unrepaired_total": self.unrepaired,
            "scrub_last_pass_seconds": round(self.last_pass_s, 6),
            "scrub_paced_sleep_seconds": round(self.pacer.paced_sleep_s, 6),
        }
