"""Distributed executor: one SPMD program per query over the shard mesh.

Reference counterpart: executor.go's remote branch — one HTTP sub-query
per node carrying its shard list, partials reduced on the caller
(SURVEY.md §3.2 ⇄NET hops). Here the whole map+reduce is a single
``shard_map``-ped XLA program: each device evaluates the fused bitmap
kernel over its resident block of shards (vmapped over the block), and
``psum``/``pmax`` over the ``shards`` axis does the reduce on ICI. No
serialization, no scatter/gather, no per-node re-dispatch.

All mapping/result logic lives in the base Executor's batched path
(executor/batch.py) — this class only swaps the placement/program
hooks: shard blocks pad to the mesh, stacked leaves are device_put with
a NamedSharding over the shard axis, and the program builders (per-query
AND micro-batched — the mesh path keeps Executor.submit's pipelined
micro-batching) wrap the same per-shard bodies in shard_map with
collective reductions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map

    SHARD_MAP_NATIVE = True
except ImportError:  # older runtimes ship it under experimental; on
    # those, concurrent shard_map programs from SEPARATE executors over
    # the same forced-CPU device set can deadlock in the cross-module
    # all-reduce rendezvous — single-mesh use is fine, multi-server
    # in-process meshes should be avoided (tests gate on this flag)
    from jax.experimental.shard_map import shard_map

    SHARD_MAP_NATIVE = False
from jax.sharding import NamedSharding, PartitionSpec as P

from pilosa_tpu.executor import expr
from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.executor import batch
from pilosa_tpu.parallel.mesh import SHARDS_AXIS, ShardAssignment, make_mesh

_DIST_JIT_CACHE: dict = {}


def _dist_body(structure, reduce_kind: str, leaf_ranks: tuple):
    """Uncompiled per-query SPMD evaluator body (runs inside shard_map):
    vmap over the local shard slots, then collective reduction over the
    mesh axis. Shared by the per-query program (_dist_fn) and the
    micro-batched program (_dist_fn_batched), mirroring
    batch._local_body / batch.local_fn_batched."""
    n_leaves = len(leaf_ranks)
    count_sub = (batch.count_elementwise_sub(structure, leaf_ranks)
                 if reduce_kind == "count" else None)

    def body(*args):
        leaves = args[:n_leaves]
        scalars = args[n_leaves:]

        if count_sub is not None:
            # elementwise count: reduce the local block flat in wide
            # chunks (batch.count_flat), then psum the packed channels
            return lax.psum(
                batch.count_flat(count_sub, leaves, scalars), SHARDS_AXIS
            )

        def per_shard(*ls):
            return expr._go(structure, ls, scalars)

        out = jax.vmap(per_shard)(*leaves)
        if reduce_kind == "count":
            return lax.psum(batch.split_sum(out), SHARDS_AXIS)
        if reduce_kind == "countrows":
            return lax.psum(batch.split_sum(out, axis=0), SHARDS_AXIS)
        if reduce_kind == "bsisum":
            plane_counts, n = out  # [S_loc, depth], [S_loc]
            return lax.psum(
                jnp.concatenate(
                    [batch.split_sum(plane_counts, axis=0),
                     batch.split_sum(n)[:, None]], axis=1
                ),
                SHARDS_AXIS,
            )
        if reduce_kind in ("min", "max"):
            values, counts = out
            want_max = reduce_kind == "max"
            masked, valid = batch.minmax_mask(values, counts, want_max)
            if want_max:
                best = lax.pmax(jnp.max(masked), SHARDS_AXIS)
            else:
                best = lax.pmin(jnp.min(masked), SHARDS_AXIS)
            any_valid = lax.pmax(
                jnp.any(valid).astype(jnp.int32), SHARDS_AXIS
            ) > 0
            n = lax.psum(
                batch.minmax_at_best(values, counts, valid, best),
                SHARDS_AXIS,
            )
            return batch.minmax_finalize(best, n, any_valid)
        return out  # 'row': stays shard-sharded

    return body


def _dist_fn(mesh, structure, reduce_kind: str, leaf_ranks: tuple,
             n_scalars: int):
    """Build (or fetch) the compiled SPMD evaluator for a query shape.
    Packed results match batch.local_fn's contracts exactly."""
    key = (mesh, structure, reduce_kind, leaf_ranks, n_scalars)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    leaf_specs = tuple(P(SHARDS_AXIS) for _ in leaf_ranks)
    scalar_specs = tuple(P() for _ in range(n_scalars))
    out_specs = P(SHARDS_AXIS) if reduce_kind == "row" else P()

    fn = jax.jit(
        shard_map(
            _dist_body(structure, reduce_kind, leaf_ranks),
            mesh=mesh,
            in_specs=leaf_specs + scalar_specs,
            out_specs=out_specs,
        )
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


def _dist_fn_batched(mesh, structure, reduce_kind: str, leaf_ranks: tuple,
                     n_scalars: int, n_queries: int):
    """ONE SPMD program evaluating ``n_queries`` same-shape pipelined
    queries over the mesh (the mesh counterpart of
    batch.local_fn_batched): per query the shared per-shard body runs
    vmapped over the local slots and psum-reduces over the shard axis;
    results come back stacked [B, ...] and replicated. Only scalar
    reductions micro-batch (count/bsisum/min/max — Executor.submit never
    coalesces 'row'), so out_specs is always replicated. Args: B
    repetitions of the sharded leaves, then (when the shape has scalars)
    ONE replicated int32[B, n_scalars] array."""
    key = ("distB", mesh, structure, reduce_kind, leaf_ranks, n_scalars,
           n_queries)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    n_leaves = len(leaf_ranks)
    body1 = _dist_body(structure, reduce_kind, leaf_ranks)
    in_specs = (
        tuple(P(SHARDS_AXIS) for _ in range(n_leaves * n_queries))
        + ((P(),) if n_scalars else ())
    )

    fn = jax.jit(
        shard_map(
            batch.batched_body(body1, n_leaves, n_scalars, n_queries),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(),
        )
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


def _dist_groupby_level_fn(mesh, filt_structure, n_filt: int, n_scalars: int,
                           n_gather: int, has_agg: bool):
    """SPMD GroupBy level program (same per-shard body as the local
    builder, psum-reduced over the mesh)."""
    key = ("gbl", mesh, filt_structure, n_filt, n_scalars, n_gather, has_agg)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    n_leaves = n_filt + n_gather + (1 if has_agg else 0)
    in_specs = (
        tuple(P(SHARDS_AXIS) for _ in range(n_leaves))
        + tuple(P() for _ in range(n_gather))  # candidate index arrays
        + tuple(P() for _ in range(n_scalars))
    )

    def body(*args):
        leaves = args[:n_leaves]
        idxs = args[n_leaves:n_leaves + n_gather]
        scalars = args[n_leaves + n_gather:]

        def per_shard(*ls):
            return batch.groupby_level_body(
                ls, idxs, scalars, filt_structure, n_filt, n_gather, has_agg
            )

        out = jax.vmap(per_shard)(*leaves)
        if not has_agg:
            return lax.psum(
                batch.split_sum(out, axis=0), SHARDS_AXIS
            ).ravel()
        counts, n_g, plane_counts = (
            batch.split_sum(o, axis=0) for o in out
        )
        return jnp.concatenate([
            lax.psum(counts, SHARDS_AXIS).ravel(),
            lax.psum(n_g, SHARDS_AXIS).ravel(),
            lax.psum(plane_counts, SHARDS_AXIS).ravel(),
        ])

    fn = jax.jit(
        shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P())
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


class DistExecutor(Executor):
    """Executor whose shard map phase runs as one SPMD program on a mesh.

    Single-process: the mesh spans all local devices and behaves like the
    base executor with on-device reduction.

    Multi-host (exercised for real by tests/test_multihost.py, two
    jax.distributed processes on the CPU backend): the same mesh spans
    hosts, and the contract is SPMD — every process drives the same query
    sequence. Each process decodes and uploads ONLY the shard slots its
    devices own (ShardAssignment.local_slots narrows block.stack, and
    _leaf_put assembles the global array with
    jax.make_array_from_process_local_data), reductions cross hosts via
    psum inside the compiled program, and reduced results come back
    replicated. Writes scatter-patch resident sharded leaves per
    addressable PIECE (batch._patch_sharded): the single-device buffer
    holding the written shard's slot is rewritten locally — a
    single-device program, no collective — and the global handle
    reassembled from the per-device buffers, so multi-host writes don't
    pay a purge + full re-decode of the process's slots.
    Row-materializing results stay shard-sharded and are only
    read back single-process; in a deployed cluster they travel per-node
    through the HTTP layer (parallel/cluster_exec.py), as the reference's
    do."""

    def __init__(self, holder, mesh=None):
        super().__init__(holder)
        self.mesh = mesh if mesh is not None else make_mesh()
        # micro-batch argument budgeting counts per-DEVICE bytes: leaves
        # are sharded over the mesh, so each chip holds 1/size of them
        self.arg_shard_factor = self.mesh.size

    def _make_block(self, shard_list):
        return ShardAssignment(shard_list, self.mesh)

    def _leaf_put(self, block):
        sharding = NamedSharding(self.mesh, P(SHARDS_AXIS))
        if jax.process_count() == 1:
            return lambda host: jax.device_put(host, sharding)
        # Multi-host: ``host`` holds only this process's slot rows
        # (ShardAssignment narrows block.local_slots, so block.stack
        # decoded just the addressable slice); assemble the global array
        # from per-process local data — no host ever materializes or
        # ships the full shard axis
        padded = block.padded

        def put(host):
            return jax.make_array_from_process_local_data(
                sharding, host, (padded,) + host.shape[1:]
            )

        return put

    def _program(self, structure, reduce_kind, leaf_ranks, n_scalars):
        return _dist_fn(self.mesh, structure, reduce_kind, leaf_ranks,
                        n_scalars)

    def _program_batched(self, structure, reduce_kind, leaf_ranks, n_scalars,
                         n_queries):
        return _dist_fn_batched(self.mesh, structure, reduce_kind, leaf_ranks,
                                n_scalars, n_queries)

    def _groupby_level_program(self, filt_structure, n_filt, n_scalars,
                               n_gather, has_agg):
        return _dist_groupby_level_fn(
            self.mesh, filt_structure, n_filt, n_scalars, n_gather, has_agg
        )
