"""Distributed executor: one SPMD program per query over the shard mesh.

Reference counterpart: executor.go's remote branch — one HTTP sub-query
per node carrying its shard list, partials reduced on the caller
(SURVEY.md §3.2 ⇄NET hops). Here the whole map+reduce is a single
``shard_map``-ped XLA program: each device evaluates the fused bitmap
kernel over its resident block of shards (vmapped over the block), and
``psum`` over the ``shards`` axis does the reduce on ICI. No
serialization, no scatter/gather, no per-node re-dispatch.

Leaves are mesh-sharded stacks ``uint32[S_padded, ...]`` built once per
(query-leaf, shard-set, write-generation) and cached in device HBM via the
residency LRU, so steady-state queries touch the host only for the final
scalar/row materialization.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from pilosa_tpu.executor import expr
from pilosa_tpu.executor.executor import (
    Executor,
    PQLError,
    _Compiled,
    _PlanesSpec,
    _RowSpec,
    _ZeroSpec,
)
from pilosa_tpu.executor.result import Pair, RowResult, ValCount
from pilosa_tpu.parallel.mesh import SHARDS_AXIS, ShardAssignment, make_mesh
from pilosa_tpu.shardwidth import WORDS_PER_SHARD
from pilosa_tpu.storage import residency
from pilosa_tpu.storage.view import VIEW_STANDARD

_DIST_JIT_CACHE: dict = {}

# Cross-products larger than this fall back to the pruned host loop: the
# dense on-device cross product evaluates every combination, which stops
# paying off when most groups are empty.
GROUPBY_DENSE_MAX_GROUPS = 4096


def _groupby_fn(mesh, filt_structure, n_filt_leaves: int, n_scalars: int,
                n_dims: int, has_agg: bool):
    """SPMD GroupBy: per shard, AND the dimension row-matrices into a dense
    cross-product mask tensor, popcount per group, and psum over the shard
    axis. With an aggregate, per-group BSI plane counts ride the same
    program (mirrors expr 'bsisum' semantics per group)."""
    key = ("groupby", mesh, filt_structure, n_filt_leaves, n_scalars,
           n_dims, has_agg)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    n_leaves = n_filt_leaves + n_dims + (1 if has_agg else 0)
    in_specs = tuple(P(SHARDS_AXIS) for _ in range(n_leaves)) + tuple(
        P() for _ in range(n_scalars)
    )
    out_specs = (P(), P(), P()) if has_agg else P()

    def body(*args):
        leaves = args[:n_leaves]
        scalars = args[n_leaves:]

        def per_shard(*ls):
            filt_leaves = ls[:n_filt_leaves]
            dim_mats = ls[n_filt_leaves:n_filt_leaves + n_dims]
            mask = dim_mats[0]  # [n_0, W]
            for d in dim_mats[1:]:
                mask = mask[..., None, :] & d  # → [n_0, …, n_i, W]
            if filt_structure is not None:
                f = expr._go(filt_structure, filt_leaves, scalars)
                mask = mask & f
            counts = jnp.sum(
                lax.population_count(mask).astype(jnp.int32), axis=-1
            )
            if not has_agg:
                return counts
            planes = ls[n_filt_leaves + n_dims]
            gmask = mask & planes[expr.PLANES_EXISTS]
            n_g = jnp.sum(
                lax.population_count(gmask).astype(jnp.int32), axis=-1
            )
            plane_counts = jnp.stack([
                jnp.sum(
                    lax.population_count(planes[b] & gmask).astype(jnp.int32),
                    axis=-1,
                )
                for b in range(expr.PLANES_OFFSET, planes.shape[0])
            ])  # [depth, n_0, …, n_k]
            return counts, n_g, plane_counts

        out = jax.vmap(per_shard)(*leaves)
        if not has_agg:
            return lax.psum(jnp.sum(out, axis=0), SHARDS_AXIS)
        counts, n_g, plane_counts = out
        return (
            lax.psum(jnp.sum(counts, axis=0), SHARDS_AXIS),
            lax.psum(jnp.sum(n_g, axis=0), SHARDS_AXIS),
            lax.psum(jnp.sum(plane_counts, axis=0), SHARDS_AXIS),
        )

    fn = jax.jit(
        shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


def _dist_fn(mesh, structure, reduce_kind: str, leaf_ranks: tuple, n_scalars: int):
    """Build (or fetch) the compiled SPMD evaluator for a query shape."""
    key = (mesh, structure, reduce_kind, leaf_ranks, n_scalars)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    leaf_specs = tuple(P(SHARDS_AXIS) for _ in leaf_ranks)
    scalar_specs = tuple(P() for _ in range(n_scalars))
    if reduce_kind in ("count", "countrows"):
        out_specs = P()
    elif reduce_kind == "bsisum":
        out_specs = (P(), P())
    elif reduce_kind == "minmax":
        out_specs = (P(SHARDS_AXIS), P(SHARDS_AXIS))
    else:  # row
        out_specs = P(SHARDS_AXIS)

    def body(*args):
        leaves = args[: len(leaf_ranks)]
        scalars = args[len(leaf_ranks):]

        def per_shard(*ls):
            return expr._go(structure, ls, scalars)

        out = jax.vmap(per_shard)(*leaves)
        if reduce_kind == "count":
            return lax.psum(jnp.sum(out), SHARDS_AXIS)
        if reduce_kind == "countrows":
            return lax.psum(jnp.sum(out, axis=0), SHARDS_AXIS)
        if reduce_kind == "bsisum":
            plane_counts, n = out
            return (
                lax.psum(jnp.sum(plane_counts, axis=0), SHARDS_AXIS),
                lax.psum(jnp.sum(n), SHARDS_AXIS),
            )
        return out  # row / minmax: stays shard-sharded

    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=leaf_specs + scalar_specs,
            out_specs=out_specs,
        )
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


class DistExecutor(Executor):
    """Executor whose shard map phase runs as one SPMD program on a mesh.

    Used single-process over all local devices; over multiple hosts the
    same code runs under jax.distributed with a global mesh (each host
    feeds its addressable shards)."""

    def __init__(self, holder, mesh=None):
        super().__init__(holder)
        self.mesh = mesh if mesh is not None else make_mesh()

    # ------------------------------------------------------- sharded leaves

    def _sharding(self):
        return NamedSharding(self.mesh, P(SHARDS_AXIS))

    def _stacked_leaf(self, idx, spec, assignment: ShardAssignment):
        cache = residency.global_row_cache()
        gen = cache.write_generation
        if isinstance(spec, _RowSpec):
            key = ("stack", gen, idx.name, spec.field, spec.views, spec.row,
                   assignment.key())

            def decode():
                return assignment.stack(
                    lambda shard: np.asarray(self._host_row(idx, spec, shard))
                )
        elif isinstance(spec, _PlanesSpec):
            field = idx.field(spec.field)
            depth = 2 + field.options.bit_depth
            key = ("stackp", gen, idx.name, spec.field, depth, assignment.key())

            def decode():
                return assignment.stack(
                    lambda shard: self._host_planes(idx, spec, shard, depth)
                )
        elif isinstance(spec, _ZeroSpec):
            key = ("stackz", assignment.padded)

            def decode():
                return np.zeros((assignment.padded, WORDS_PER_SHARD), np.uint32)
        else:
            raise PQLError(f"unknown leaf spec {type(spec).__name__}")

        sharding = self._sharding()
        return cache.get_row(
            key, decode, device_put=lambda host: jax.device_put(host, sharding)
        )

    @staticmethod
    def _host_row(idx, spec: _RowSpec, shard: int) -> np.ndarray:
        field = idx.field(spec.field)
        acc = None
        for vname in spec.views:
            view = field.view(vname) if field else None
            frag = view.fragment(shard) if view else None
            if frag is None:
                continue
            words = frag.row_words(spec.row)
            acc = words if acc is None else np.bitwise_or(acc, words)
        return acc if acc is not None else np.zeros(WORDS_PER_SHARD, np.uint32)

    @staticmethod
    def _host_planes(idx, spec: _PlanesSpec, shard: int, depth: int) -> np.ndarray:
        field = idx.field(spec.field)
        view = field.view(field.bsi_view_name())
        frag = view.fragment(shard) if view else None
        if frag is None:
            return np.zeros((depth, WORDS_PER_SHARD), np.uint32)
        return np.stack([frag.row_words(r) for r in range(depth)])

    def _dist_eval(self, idx, compiled: _Compiled, shards: list[int],
                   reduce_kind: str, extra_leaves=()):
        assignment = ShardAssignment(shards, self.mesh)
        leaves = [
            self._stacked_leaf(idx, spec, assignment) for spec in compiled.specs
        ]
        leaves.extend(extra_leaves)
        if not leaves:
            leaves = [self._stacked_leaf(idx, _ZeroSpec(), assignment)]
        scalars = tuple(jnp.asarray(s, jnp.int32) for s in compiled.scalars)
        fn = _dist_fn(
            self.mesh, compiled.node, reduce_kind,
            tuple(l.ndim - 1 for l in leaves), len(scalars),
        )
        return fn(*leaves, *scalars), assignment

    # ---------------------------------------------------- overridden calls

    def _execute_count(self, idx, call, shards=None) -> int:
        if len(call.children) != 1:
            raise PQLError("Count requires exactly one child call")
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return 0
        compiled = self._compile(idx, call.children[0], wrap="count")
        total, _ = self._dist_eval(idx, compiled, shard_list, "count")
        return int(total)

    def _execute_bitmap(self, idx, call, shards=None) -> RowResult:
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return RowResult({})
        compiled = self._compile(idx, call)
        stacked, assignment = self._dist_eval(idx, compiled, shard_list, "row")
        host = np.asarray(stacked)
        segments = {}
        for i, shard in enumerate(assignment.shards):
            if host[i].any():
                segments[shard] = host[i]
        return self._finish_row_result(idx, call, RowResult(segments))

    def _execute_bsi_aggregate(self, idx, call, shards=None) -> ValCount:
        from pilosa_tpu.storage.field import TYPE_INT

        field_name = call.arg("field") or call.arg("_field")
        if field_name is None:
            raise PQLError(f"{call.name} requires field=")
        field = idx.field(field_name)
        if field is None or field.options.type != TYPE_INT:
            raise PQLError(f"{call.name} requires an int field")
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return ValCount(0, 0)
        filt_call = call.children[0] if call.children else None

        specs: list = []
        scalars: list = []
        planes_i = self._planes_index(field, specs)
        filt_node = (
            self._compile_node(idx, filt_call, specs, scalars) if filt_call else None
        )
        base = field.options.base

        if call.name == "Sum":
            node = ("bsisum", planes_i, filt_node)
            (plane_counts, n), _ = self._dist_eval(
                idx, _Compiled(node, specs, scalars), shard_list, "bsisum"
            )
            plane_counts = np.asarray(plane_counts).tolist()
            count = int(n)
            total = sum(c << i for i, c in enumerate(plane_counts))
            return ValCount(total + base * count, count)

        want_max = call.name == "Max"
        node = ("bsiminmax", 1 if want_max else 0, planes_i, filt_node)
        (values, counts), assignment = self._dist_eval(
            idx, _Compiled(node, specs, scalars), shard_list, "minmax"
        )
        values = np.asarray(values)[: len(assignment.shards)]
        counts = np.asarray(counts)[: len(assignment.shards)]
        best, count = None, 0
        for v, n in zip(values.tolist(), counts.tolist()):
            if n == 0:
                continue
            if best is None or (v > best if want_max else v < best):
                best, count = v, n
            elif v == best:
                count += n
        if best is None:
            return ValCount(0, 0)
        return ValCount(best + base, count)

    def _stacked_matrix(self, idx, field_name: str, view, row_ids, assignment):
        """Mesh-sharded stack ``uint32[S_padded, len(row_ids), words]`` of
        the given rows of one view, cached in HBM like other leaves."""
        cache = residency.global_row_cache()
        gen = cache.write_generation
        key = ("stackm", gen, idx.name, field_name,
               view.name if view is not None else None, tuple(row_ids),
               assignment.key())

        def decode():
            def per_shard(shard):
                frag = view.fragment(shard) if view else None
                if frag is None:
                    return np.zeros((len(row_ids), WORDS_PER_SHARD), np.uint32)
                return np.stack([frag.row_words(r) for r in row_ids])

            return assignment.stack(per_shard)

        sharding = self._sharding()
        return cache.get_row(
            key, decode, device_put=lambda host: jax.device_put(host, sharding)
        )

    def _execute_groupby(self, idx, call, shards=None):
        """GroupBy as ONE SPMD program: dense cross-product of dimension
        rows evaluated per shard on its owning device, group counts (and
        BSI plane counts for aggregate=Sum) psum-reduced over the mesh.

        Replaces the reference's per-shard recursion with pruning
        (executor.executeGroupByShard) by a dense batched evaluation —
        the TPU-friendly shape — falling back to the pruned host loop when
        the cross product is too large to pay for itself."""
        limit, filt_call, agg_field, dims = self._groupby_prelude(
            idx, call, shards
        )
        if not dims:
            return []
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return []
        n_groups = 1
        for _, row_ids in dims:
            n_groups *= len(row_ids)
        if n_groups > GROUPBY_DENSE_MAX_GROUPS:
            return self._groupby_host(
                idx, shards, limit, filt_call, agg_field, dims
            )

        specs: list = []
        scalars: list = []
        filt_node = (
            self._compile_node(idx, filt_call, specs, scalars)
            if filt_call is not None
            else None
        )
        assignment = ShardAssignment(shard_list, self.mesh)
        leaves = [
            self._stacked_leaf(idx, spec, assignment) for spec in specs
        ]
        for fname, row_ids in dims:
            field = idx.field(fname)
            view = field.view(VIEW_STANDARD) if field else None
            leaves.append(
                self._stacked_matrix(idx, fname, view, row_ids, assignment)
            )
        if agg_field is not None:
            leaves.append(
                self._stacked_leaf(
                    idx, _PlanesSpec(agg_field.name), assignment
                )
            )
        fn = _groupby_fn(
            self.mesh, filt_node, len(specs), len(scalars),
            len(dims), agg_field is not None,
        )
        jscalars = tuple(jnp.asarray(s, jnp.int32) for s in scalars)
        out = fn(*leaves, *jscalars)

        if agg_field is not None:
            counts_nd, n_nd, pc_nd = (np.asarray(o) for o in out)
        else:
            counts_nd = np.asarray(out)
            n_nd = pc_nd = None
        counts: dict[tuple, int] = {}
        sums: dict[tuple, int] = {}
        base = agg_field.options.base if agg_field is not None else 0
        for flat, c in enumerate(counts_nd.reshape(-1).tolist()):
            if c <= 0:
                continue
            idxs = np.unravel_index(flat, counts_nd.shape)
            gkey = tuple(dims[d][1][i] for d, i in enumerate(idxs))
            counts[gkey] = int(c)
            if agg_field is not None:
                pc = pc_nd[(slice(None),) + idxs].tolist()
                n = int(n_nd[idxs])
                sums[gkey] = sum(v << b for b, v in enumerate(pc)) + base * n
        return self._groupby_result(idx, dims, counts, sums, agg_field, limit)

    def _execute_topn(self, idx, call, shards=None) -> list[Pair]:
        from pilosa_tpu.executor.executor import TOPN_CANDIDATE_FACTOR

        field_name = call.arg("_field") or call.arg("field")
        if field_name is None:
            raise PQLError("TopN requires a field")
        field = idx.field(field_name)
        if field is None:
            raise PQLError(f"field {field_name!r} not found")
        n = call.arg("n", 10)
        filt_call = call.children[0] if call.children else None
        shard_list = self._shards(idx, shards)
        if not shard_list:
            return []
        view = field.view(VIEW_STANDARD)

        explicit_ids = call.arg("ids")
        if explicit_ids is not None:
            candidates = sorted(int(i) for i in explicit_ids)
        else:
            overfetch = max(n * TOPN_CANDIDATE_FACTOR, n + 10)
            cand: set[int] = set()
            for shard in shard_list:
                frag = view.fragment(shard) if view else None
                if frag is None:
                    continue
                cand.update(r for r, _ in frag.top(overfetch))
            candidates = sorted(cand)
        candidates = self._filter_topn_candidates(field, call, candidates)
        if not candidates:
            return []

        # phase 2 on the mesh: stacked [S, n_cand, words] + countrows psum
        specs: list = []
        scalars: list = []
        filt_node = (
            self._compile_node(idx, filt_call, specs, scalars) if filt_call else None
        )
        node = ("countrows", len(specs), filt_node)
        assignment = ShardAssignment(shard_list, self.mesh)
        matrix = self._stacked_matrix(idx, field_name, view, candidates, assignment)
        compiled = _Compiled(node, specs, scalars)
        counts, _ = self._dist_eval(
            idx, compiled, shard_list, "countrows", extra_leaves=(matrix,)
        )
        totals = np.asarray(counts, np.int64)
        order = sorted(
            (int(-c), r) for r, c in zip(candidates, totals.tolist()) if c > 0
        )
        if n:
            order = order[:n]
        return self._finish_pairs(
            idx, field, [Pair(r, -negc) for negc, r in order]
        )
