"""Distributed executor: one SPMD program per query over the shard mesh.

Reference counterpart: executor.go's remote branch — one HTTP sub-query
per node carrying its shard list, partials reduced on the caller
(SURVEY.md §3.2 ⇄NET hops). Here the whole map+reduce is a single
``shard_map``-ped XLA program: each device evaluates the fused bitmap
kernel over its resident block of shards (vmapped over the block), and
``psum``/``pmax`` over the ``shards`` axis does the reduce on ICI. No
serialization, no scatter/gather, no per-node re-dispatch.

On a 2-D ``groups x shards`` mesh (parallel/mesh.py) every reduction
runs hierarchically: a dense intra-group ``psum``/``pmax`` over the
cheap axis, then a narrow inter-group lane carrying only encoded
per-group partials (parallel/reduction.py — uint8/uint16 where the
static SHARD_WIDTH bound proves the cast lossless, int32 otherwise, and
roaring containers for materialized row gathers). Results are
bit-identical to the flat 1-D path; only the wire shape changes, and the
dispatch path measures it (dense-equivalent vs actual bytes, the
``dist_reduce_*`` series).

All mapping/result logic lives in the base Executor's batched path
(executor/batch.py) — this class only swaps the placement/program
hooks: shard blocks pad to the mesh, stacked leaves are device_put with
a NamedSharding over the shard axis, and the program builders (per-query
AND micro-batched — the mesh path keeps Executor.submit's pipelined
micro-batching) wrap the same per-shard bodies in shard_map with
collective reductions.
"""

from __future__ import annotations

import contextlib
import inspect
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map

    SHARD_MAP_NATIVE = True
except ImportError:  # older runtimes ship it under experimental; on
    # those, concurrent shard_map programs from SEPARATE executors over
    # the same forced-CPU device set can deadlock in the cross-module
    # all-reduce rendezvous — single-mesh use is fine; multi-mesh
    # in-process dispatches are serialized by _fallback_guard below
    from jax.experimental.shard_map import shard_map

    SHARD_MAP_NATIVE = False
from jax.sharding import NamedSharding, PartitionSpec as P

from pilosa_tpu.executor import expr
from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.executor import batch
from pilosa_tpu.parallel import reduction
from pilosa_tpu.parallel.mesh import (
    GROUPS_AXIS, SHARDS_AXIS, ShardAssignment, make_mesh, mesh_groups,
    shards_spec,
)
from pilosa_tpu.utils.cost import current_cost

_DIST_JIT_CACHE: dict = {}

# ---------------------------------------------------------------------------
# Experimental-fallback dispatch guard.
#
# The experimental shard_map can deadlock when programs built over
# DIFFERENT meshes (separate in-process executors — e.g. a test server's
# auto-mesh next to a bench's explicit submesh) launch concurrently:
# both enter the collective rendezvous over the same forced-CPU device
# set and wait on each other. Native shard_map keys the rendezvous by
# mesh and doesn't need this. Rather than a comment asking callers not
# to do that, dispatches take a process-wide lock whenever more than one
# distinct live mesh exists under the fallback; single-mesh deployments
# (every production shape) never pay it. tests/test_mesh_reduction.py
# holds the regression.

_FALLBACK_DISPATCH_LOCK = threading.RLock()
_LIVE_EXECUTORS: "weakref.WeakSet" = weakref.WeakSet()
_guard_serialized_count = 0


def _multi_mesh_live(mesh) -> bool:
    meshes = {e.mesh for e in _LIVE_EXECUTORS}
    meshes.add(mesh)
    return len(meshes) > 1


@contextlib.contextmanager
def _fallback_guard(mesh):
    if SHARD_MAP_NATIVE or not _multi_mesh_live(mesh):
        yield
        return
    global _guard_serialized_count
    with _FALLBACK_DISPATCH_LOCK:
        _guard_serialized_count += 1
        yield


# hierarchical bodies produce replicated outputs via all_gather + local
# fold, which the rep checker cannot infer — disable it for those
# programs only (kwarg name varies across shard_map generations)
if "check_rep" in inspect.signature(shard_map).parameters:
    _LOOSE_REP = {"check_rep": False}
elif "check_vma" in inspect.signature(shard_map).parameters:
    _LOOSE_REP = {"check_vma": False}
else:
    _LOOSE_REP = {}


def _smap(body, mesh, in_specs, out_specs, hier):
    kwargs = _LOOSE_REP if hier is not None else {}
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **kwargs)


def _dist_body(structure, reduce_kind: str, leaf_ranks: tuple, hier=None):
    """Uncompiled per-query SPMD evaluator body (runs inside shard_map):
    vmap over the local shard slots, then collective reduction over the
    mesh. ``hier`` is (groups, shards_per_group) for the 2-D mesh —
    intra-group psum/pmax over the shards axis, then the narrow encoded
    inter-group lane (reduction.py); None is the flat 1-D reduce. Both
    forms return BIT-IDENTICAL packed results (integer adds are exact
    and associative; narrowing only where the static bound proves it).
    Shared by the per-query program (_dist_fn) and the micro-batched
    program (_dist_fn_batched), mirroring batch._local_body /
    batch.local_fn_batched."""
    n_leaves = len(leaf_ranks)
    count_sub = (batch.count_elementwise_sub(structure, leaf_ranks)
                 if reduce_kind == "count" else None)

    def body(*args):
        leaves = args[:n_leaves]
        scalars = args[n_leaves:]
        # static per-group slot count for the lossless-narrowing bounds:
        # local slots x group width (leaf shapes are concrete at trace)
        group_slots = leaves[0].shape[0] * (hier[1] if hier else 1)

        def reduce_split(packed_local):
            part = lax.psum(packed_local, SHARDS_AXIS)
            if hier is None:
                return part
            return reduction.hier_split_channels(
                part, GROUPS_AXIS, group_slots
            )

        if count_sub is not None:
            # elementwise count: reduce the local block flat in wide
            # chunks (batch.count_flat), then reduce the packed channels
            return reduce_split(batch.count_flat(count_sub, leaves, scalars))

        def per_shard(*ls):
            return expr._go(structure, ls, scalars)

        out = jax.vmap(per_shard)(*leaves)
        if reduce_kind == "count":
            return reduce_split(batch.split_sum(out))
        if reduce_kind == "countrows":
            return reduce_split(batch.split_sum(out, axis=0))
        if reduce_kind == "countrows_q":
            # quantized candidate-ranking lane: exact intra-group psum
            # of the split channels, then the 8-bit scaled inter-group
            # hop (reduction.hier_quantized_counts — lossless
            # pass-through on a flat mesh). Only the executor's TopN
            # ranking pass dispatches this kind; the exact recount of
            # the widened window rides plain 'countrows'.
            part = lax.psum(batch.split_sum(out, axis=0), SHARDS_AXIS)
            return reduction.hier_quantized_counts(
                part, GROUPS_AXIS if hier is not None else None
            )
        if reduce_kind == "bsisum":
            plane_counts, n = out  # [S_loc, depth], [S_loc]
            return reduce_split(
                jnp.concatenate(
                    [batch.split_sum(plane_counts, axis=0),
                     batch.split_sum(n)[:, None]], axis=1
                )
            )
        if reduce_kind in ("min", "max"):
            values, counts = out
            want_max = reduce_kind == "max"
            masked, valid = batch.minmax_mask(values, counts, want_max)
            if want_max:
                best = lax.pmax(jnp.max(masked), SHARDS_AXIS)
            else:
                best = lax.pmin(jnp.min(masked), SHARDS_AXIS)
            valid_g = lax.pmax(jnp.any(valid).astype(jnp.int32), SHARDS_AXIS)
            if hier is not None:
                # the group best is exact int32 (sentinel-masked values
                # can be negative — no narrowing bound); the valid flag
                # is 0/1 and crosses as uint8
                best = reduction.gather_extreme(best, GROUPS_AXIS, want_max)
                valid_g = reduction.gather_extreme(
                    valid_g, GROUPS_AXIS, True, bound=1
                )
            any_valid = valid_g > 0
            n = reduce_split(
                batch.minmax_at_best(values, counts, valid, best)
            )
            return batch.minmax_finalize(best, n, any_valid)
        return out  # 'row': stays shard-sharded

    return body


def _dist_fn(mesh, structure, reduce_kind: str, leaf_ranks: tuple,
             n_scalars: int):
    """Build (or fetch) the compiled SPMD evaluator for a query shape.
    Packed results match batch.local_fn's contracts exactly."""
    key = (mesh, structure, reduce_kind, leaf_ranks, n_scalars)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    hier = mesh_groups(mesh)
    spec = shards_spec(mesh)
    leaf_specs = tuple(spec for _ in leaf_ranks)
    scalar_specs = tuple(P() for _ in range(n_scalars))
    out_specs = spec if reduce_kind == "row" else P()

    fn = jax.jit(
        _smap(
            _dist_body(structure, reduce_kind, leaf_ranks, hier),
            mesh=mesh,
            in_specs=leaf_specs + scalar_specs,
            out_specs=out_specs,
            hier=hier,
        )
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


def _dist_fn_batched(mesh, structure, reduce_kind: str, leaf_ranks: tuple,
                     n_scalars: int, n_queries: int):
    """ONE SPMD program evaluating ``n_queries`` same-shape pipelined
    queries over the mesh (the mesh counterpart of
    batch.local_fn_batched): per query the shared per-shard body runs
    vmapped over the local slots and reduces over the mesh (flat psum or
    the hierarchical two-stage form — _dist_body); results come back
    stacked [B, ...] and replicated. Only scalar reductions micro-batch
    (count/bsisum/min/max — Executor.submit never coalesces 'row'), so
    out_specs is always replicated. Args: B repetitions of the sharded
    leaves, then (when the shape has scalars) ONE replicated
    int32[B, n_scalars] array."""
    key = ("distB", mesh, structure, reduce_kind, leaf_ranks, n_scalars,
           n_queries)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    hier = mesh_groups(mesh)
    n_leaves = len(leaf_ranks)
    body1 = _dist_body(structure, reduce_kind, leaf_ranks, hier)
    in_specs = (
        tuple(shards_spec(mesh) for _ in range(n_leaves * n_queries))
        + ((P(),) if n_scalars else ())
    )

    fn = jax.jit(
        _smap(
            batch.batched_body(body1, n_leaves, n_scalars, n_queries),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(),
            hier=hier,
        )
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


def _dist_groupby_level_fn(mesh, filt_structure, n_filt: int, n_scalars: int,
                           n_gather: int, has_agg: bool,
                           quantized: bool = False):
    """SPMD GroupBy level program (same per-shard body as the local
    builder, reduced over the mesh — hierarchically on a 2-D mesh, like
    every other split-sum lane). ``quantized`` routes the per-candidate
    counts through the 8-bit ranking lane — only intermediate PRUNING
    levels use it (their counts merely gate candidate survival); the
    final level always stays lossless, so reported counts are exact."""
    key = ("gbl", mesh, filt_structure, n_filt, n_scalars, n_gather, has_agg,
           quantized)
    fn = _DIST_JIT_CACHE.get(key)
    if fn is not None:
        return fn

    hier = mesh_groups(mesh)
    n_leaves = n_filt + n_gather + (1 if has_agg else 0)
    in_specs = (
        tuple(shards_spec(mesh) for _ in range(n_leaves))
        + tuple(P() for _ in range(n_gather))  # candidate index arrays
        + tuple(P() for _ in range(n_scalars))
    )

    def body(*args):
        leaves = args[:n_leaves]
        idxs = args[n_leaves:n_leaves + n_gather]
        scalars = args[n_leaves + n_gather:]
        group_slots = leaves[0].shape[0] * (hier[1] if hier else 1)

        def reduce_split(packed_local):
            part = lax.psum(packed_local, SHARDS_AXIS)
            if hier is None:
                return part
            return reduction.hier_split_channels(
                part, GROUPS_AXIS, group_slots
            )

        def per_shard(*ls):
            return batch.groupby_level_body(
                ls, idxs, scalars, filt_structure, n_filt, n_gather, has_agg
            )

        out = jax.vmap(per_shard)(*leaves)
        if not has_agg:
            packed = batch.split_sum(out, axis=0)
            if quantized:
                part = lax.psum(packed, SHARDS_AXIS)
                return reduction.hier_quantized_counts(
                    part, GROUPS_AXIS if hier is not None else None
                ).ravel()
            return reduce_split(packed).ravel()
        return jnp.concatenate([
            reduce_split(batch.split_sum(o, axis=0)).ravel() for o in out
        ])

    fn = jax.jit(
        _smap(body, mesh=mesh, in_specs=in_specs, out_specs=P(), hier=hier)
    )
    _DIST_JIT_CACHE[key] = fn
    return fn


class DistExecutor(Executor):
    """Executor whose shard map phase runs as one SPMD program on a mesh.

    Single-process: the mesh spans all local devices and behaves like the
    base executor with on-device reduction. A 2-D ``groups x shards``
    mesh (``DistExecutor(holder, groups=2)`` or an explicit
    ``make_mesh(groups=...)``) engages the hierarchical reduction plane:
    identical results, but cross-group traffic crosses as narrow encoded
    lanes and row gathers as roaring containers, with per-dispatch
    dense-vs-actual wire bytes recorded (reduction.global_reduce_stats,
    the cost plane's reduceBytes, and the dist_reduce_* series).

    Multi-host (exercised for real by tests/test_multihost.py, two
    jax.distributed processes on the CPU backend): the same mesh spans
    hosts, and the contract is SPMD — every process drives the same query
    sequence. Each process decodes and uploads ONLY the shard slots its
    devices own (ShardAssignment.local_slots narrows block.stack, and
    _leaf_put assembles the global array with
    jax.make_array_from_process_local_data), reductions cross hosts via
    psum inside the compiled program, and reduced results come back
    replicated. Writes scatter-patch resident sharded leaves per
    addressable PIECE (batch._patch_sharded): the single-device buffer
    holding the written shard's slot is rewritten locally — a
    single-device program, no collective — and the global handle
    reassembled from the per-device buffers, so multi-host writes don't
    pay a purge + full re-decode of the process's slots.
    Row-materializing results stay shard-sharded and are only
    read back single-process; in a deployed cluster they travel per-node
    through the HTTP layer (parallel/cluster_exec.py), as the reference's
    do."""

    def __init__(self, holder, mesh=None, groups: int | None = None,
                 quantized_ranking: bool = False,
                 verify_quantized: bool = False):
        super().__init__(holder)
        self.mesh = mesh if mesh is not None else make_mesh(groups=groups)
        # micro-batch argument budgeting counts per-DEVICE bytes: leaves
        # are sharded over the mesh, so each chip holds 1/size of them
        self.arg_shard_factor = self.mesh.size
        self._hier = mesh_groups(self.mesh)
        # EQuARX quantized candidate-ranking lane (topn-quantized-ranking
        # knob): TopN ranking + GroupBy pruning counts cross the
        # inter-group wire as 8-bit scaled lanes; final results stay
        # byte-identical via the widened-window exact recount. On a flat
        # 1-D mesh the lane is a lossless pass-through (same code path,
        # zero error bound). verify_quantized additionally runs the
        # lossless path per TopN and asserts identity — the bench/dryrun
        # certification mode, not for serving.
        self.quantized_ranking = bool(quantized_ranking)
        self.verify_quantized = bool(verify_quantized)
        _LIVE_EXECUTORS.add(self)

    def _quant_ranking_active(self) -> bool:
        return self.quantized_ranking

    def _make_block(self, shard_list):
        return ShardAssignment(shard_list, self.mesh)

    def _leaf_put(self, block):
        sharding = NamedSharding(self.mesh, shards_spec(self.mesh))
        if jax.process_count() == 1:
            return lambda host: jax.device_put(host, sharding)
        # Multi-host: ``host`` holds only this process's slot rows
        # (ShardAssignment narrows block.local_slots, so block.stack
        # decoded just the addressable slice); assemble the global array
        # from per-process local data — no host ever materializes or
        # ships the full shard axis
        padded = block.padded

        def put(host):
            return jax.make_array_from_process_local_data(
                sharding, host, (padded,) + host.shape[1:]
            )

        return put

    def _program(self, structure, reduce_kind, leaf_ranks, n_scalars):
        return _dist_fn(self.mesh, structure, reduce_kind, leaf_ranks,
                        n_scalars)

    def _program_batched(self, structure, reduce_kind, leaf_ranks, n_scalars,
                         n_queries):
        return _dist_fn_batched(self.mesh, structure, reduce_kind, leaf_ranks,
                                n_scalars, n_queries)

    def _groupby_level_program(self, filt_structure, n_filt, n_scalars,
                               n_gather, has_agg, quantized=False):
        return _dist_groupby_level_fn(
            self.mesh, filt_structure, n_filt, n_scalars, n_gather, has_agg,
            quantized,
        )

    # ------------------------------------------------ dispatch wrapping

    def _dispatch(self, node, reduce_kind, leaves, scalars):
        with _fallback_guard(self.mesh):
            return super()._dispatch(node, reduce_kind, leaves, scalars)

    def _flush_group_locked(self, key, group):
        with _fallback_guard(self.mesh):
            return super()._flush_group_locked(key, group)

    def _groupby_level_enqueue(self, *args, **kwargs):
        with _fallback_guard(self.mesh):
            return super()._groupby_level_enqueue(*args, **kwargs)

    # ------------------------------------------- wire-byte accounting

    def _note_reduce(self, reduce_kind: str, out_shape: tuple,
                     padded: int) -> None:
        """Per-dispatch reduction-lane bytes, from static shapes only
        (host side, nothing blocks on the device). dense-equivalent =
        flat int32 ring all-reduce over the whole mesh; actual = the
        narrow inter-group hop (equal to dense on a 1-D mesh, where the
        plane is pass-through); intra = per-group dense traffic,
        reported separately as the cheap-axis cost."""
        if reduce_kind == "row":
            return  # row gathers are accounted in _row_host
        elems = 1
        for d in out_shape:
            elems *= int(d)
        quantized = 0
        if reduce_kind in ("countrows_q", "groupby_q"):
            # quantized ranking dispatch: the packed section is
            # [2, R + n_blocks] (batched: leading B; groupby: raveled,
            # accounted per chunk). Recover R from the section width and
            # model the 8-bit hop vs its lossless countrows equivalent.
            width = (elems // 2 if reduce_kind == "groupby_q"
                     else int(out_shape[-1]))
            mult = max(elems // (2 * width), 1)
            n_rows = reduction.quant_real_elems(width)
            # dense equivalent: the flat ring moving the same candidate
            # lanes as exact [2, R] int32 split channels
            dense = reduction.dense_reduce_bytes(
                self.mesh.size, 2 * n_rows * mult
            )
            if self._hier is None:
                actual, intra, lossless = dense, 0, dense
            else:
                g, spg = self._hier
                actual, intra, lossless = reduction.quant_hier_bytes(
                    n_rows, g, spg, max(padded // g, 1)
                )
                actual, intra, lossless = (
                    actual * mult, intra * mult, lossless * mult
                )
            reduction.global_reduce_stats().note_quant_reduce(
                actual, lossless
            )
            quantized = actual
        else:
            dense = reduction.dense_reduce_bytes(self.mesh.size, elems)
            if self._hier is None:
                actual, intra = dense, 0
            else:
                g, spg = self._hier
                actual, intra = reduction.hier_reduce_bytes(
                    reduce_kind, elems, g, spg, max(padded // g, 1)
                )
        reduction.global_reduce_stats().note_reduce(
            dense, actual, intra, self._hier is not None
        )
        cost = current_cost()
        if cost is not None:
            cost.note_reduce(dense, actual, quantized=quantized)

    def _row_host(self, stacked, block):
        """Row-gather readback. On the hierarchical mesh the dense
        [padded, words] device result crosses the (simulated) wire as
        per-slot roaring containers in block frames — the result is
        decoded FROM those frames, so the compression is load-bearing,
        not just counted."""
        host = np.asarray(stacked)
        if self._hier is None or jax.process_count() > 1:
            return host
        frames, actual = reduction.encode_row_frames(host)
        reduction.global_reduce_stats().note_row_gather(host.nbytes, actual)
        cost = current_cost()
        if cost is not None:
            cost.note_reduce(host.nbytes, actual)
        return reduction.decode_row_frames(frames, host.shape)
