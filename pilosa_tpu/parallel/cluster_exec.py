"""Cluster-aware executor: local mesh map + cross-node HTTP reduce.

Reference: the remote branch of executor.mapReduce (SURVEY.md §3.2) —
shards owned elsewhere are batched into ONE sub-query per node
(``Remote=true`` + explicit shard list) and partial results are reduced on
the requesting node: rows union, counts add, TopN pair-merge with an
exact second pass, ValCount merge, group-merge.

Local shards evaluate through the wrapped executor (DistExecutor when a
mesh is available), so inside a host the reduce is an ICI psum and only
the cross-host hop uses HTTP/DCN — the reference's topology with its
data plane swapped out.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.executor import (
    Deferred,
    PQLError,
    TOPN_CANDIDATE_FACTOR,
    apply_options_result,
    having_predicate,
    options_child,
    options_restrict_shards,
)
from pilosa_tpu.executor.result import GroupCount, Pair, RowResult, ValCount
from pilosa_tpu.ops.packing import pack_bits
from pilosa_tpu.parallel.client import ClientError
from pilosa_tpu.parallel.cluster import (
    Cluster,
    ClusterDegradedError,
    Node,
    global_route_stats,
)
from pilosa_tpu.qos.deadline import DeadlineExceeded
from pilosa_tpu.storage.field import TYPE_BOOL, TYPE_INT, TYPE_MUTEX
from pilosa_tpu.pql import Call, parse
from pilosa_tpu.pql.ast import Query
from pilosa_tpu.shardwidth import SHARD_WIDTH, shard_of
from pilosa_tpu.utils.pool import concurrent_map, run_concurrently, spawn

_WRITE_BROADCAST = {"SetRowAttrs", "SetColumnAttrs"}
_SHARDS_TTL = 3.0

# How long a query waits for a resize to finish before erroring
# (reference: queries are deferred while the cluster is RESIZING).
_RESIZE_WAIT = 30.0


class ClusterExecutor:
    """Wraps a local executor with shard routing across cluster nodes."""

    accepts_remote = True

    def __init__(self, local_executor: Executor, cluster: Cluster,
                 qos=None, remote_batch: bool = True):
        self.local = local_executor
        self.holder = local_executor.holder
        self.cluster = cluster
        # serving-QoS bundle (qos.ServingQos): hedge policy + per-node
        # circuit breakers for the remote read fan-out; None disables
        # both (bare constructions in tests/tools)
        self.qos = qos
        # cluster-wide wave batching (parallel/wavebatch.py): deadline-
        # free primary reads bound for the same node group-commit onto
        # one /internal/query-batch request. ``remote-batch = false``
        # (ServerConfig) restores per-query dispatch.
        self.remote_batch = remote_batch
        self._wave_batcher = None
        # read rotation over a range-split shard's span owners (elastic
        # plane): bumped per routed read; a lost increment under the
        # benign unlocked race just repeats a pick
        self._range_rr = 0
        self._shards_cache: dict[str, tuple[float, list[int]]] = {}
        self._lock = threading.Lock()
        # key translation goes through the coordinator (reference:
        # translation primary); reverse lookups backfill from its log
        local_executor.key_resolver = self._resolve_key_via_coordinator
        local_executor.key_backfill = cluster.sync_translate

    def _resolve_key_via_coordinator(self, namespace: str, key: str, create: bool):
        coord = self.cluster.coordinator
        if coord.id == self.cluster.local.id:
            if create:
                return self.holder.translate.translate_one(namespace, key, create=True)
            return None
        ids = self.cluster.client.translate_keys(coord.uri, namespace, [key], create)
        id_ = ids[0] if ids else None
        if id_ is not None:
            self.cluster.sync_translate()  # mirror the assignment locally
        return id_

    # ------------------------------------------------------------ top level

    def execute(self, index_name: str, query, shards=None,
                remote: bool = False, deadline=None):
        if remote:
            # sub-query from a peer: evaluate strictly locally on the given
            # shards, no re-fan-out (reference Remote=true)
            return self.local.execute(index_name, query, shards=shards,
                                      deadline=deadline)
        if not self.cluster.wait_until_normal(
            _RESIZE_WAIT if deadline is None
            else min(_RESIZE_WAIT, max(deadline.remaining(), 0))
        ):
            if deadline is not None:
                deadline.check("resize wait")
            raise PQLError("cluster is resizing; query deferred past timeout")
        if isinstance(query, str):
            query = parse(query)
        elif isinstance(query, Call):
            query = Query([query])
        idx = self.holder.index(index_name)
        if idx is None:
            raise PQLError(f"index {index_name!r} not found")
        return [self._execute_call(idx, call, shards, deadline=deadline)
                for call in query.calls]

    def submit(self, index_name: str, query, shards=None,
               remote: bool = False, deadline=None):
        """Pipelined cluster execution: one ``Deferred`` per call.

        The cluster analog of ``Executor.submit`` (the reference serves
        concurrent queries through per-request mapReduce goroutines —
        SURVEY.md §2 #12/§3.2; on a TPU backend the scarce resource is
        DISPATCHES, so the stream must coalesce instead of merely
        interleave). Per call: local shards enqueue through the wrapped
        executor's pipelined ``submit`` — so a stream of cluster queries
        micro-batches on-device exactly like a single-node stream — while
        the remote fan-out STARTS on a background thread at submit time
        (``spawn``); ``result()`` joins both and runs the cross-node
        reduce. When every routed shard is local (single-node cluster,
        full replication) the call delegates wholesale to the wrapped
        executor and pays zero cluster overhead. Writes and point reads
        (IncludesColumn) keep their eager routed semantics.
        """
        if remote:
            # peer sub-query: strictly local, still pipelined
            return self.local.submit(index_name, query, shards=shards,
                                     deadline=deadline)
        if isinstance(query, str):
            query = parse(query)
        elif isinstance(query, Call):
            query = Query([query])
        idx = self.holder.index(index_name)
        if idx is None:
            raise PQLError(f"index {index_name!r} not found")
        if not self.cluster.wait_until_normal(0):
            # Cluster is RESIZING: the deferral wait must burn on the
            # CALLER's thread at result() — concurrent requests then wait
            # in parallel, and a serving pipeline's dispatcher (which
            # calls submit, never result) stays unblocked.
            def deferred(call):
                def finalize():
                    wait = _RESIZE_WAIT
                    if deadline is not None:
                        wait = min(wait, max(deadline.remaining(), 0))
                    if not self.cluster.wait_until_normal(wait):
                        if deadline is not None:
                            deadline.check("resize wait")
                        raise PQLError(
                            "cluster is resizing; query deferred past timeout"
                        )
                    return self._execute_call(idx, call, shards,
                                              deadline=deadline)

                return Deferred(finalize)

            return [deferred(call) for call in query.calls]
        return [self._submit_call(idx, call, shards, deadline=deadline)
                for call in query.calls]

    def _submit_call(self, idx, call: Call, shards=None,
                     deadline=None) -> Deferred:
        if deadline is not None:
            deadline.check("cluster submit")
        name = call.name
        if name == "Options":
            inner = self._submit_call(
                idx, options_child(call),
                options_restrict_shards(call, shards), deadline=deadline,
            )
            return Deferred(
                lambda: apply_options_result(idx, call, inner.result())
            )
        if name == "IncludesColumn":
            # a READ with a possible remote hop: start it on a background
            # thread NOW so a slow shard owner cannot convoy a serving
            # pipeline's dispatcher; result() joins
            return Deferred(spawn(
                lambda: self._execute_includes(idx, call, shards,
                                               deadline=deadline)
            ))
        if name in ("Set", "Clear", "Store", "ClearRow") or name in _WRITE_BROADCAST:
            # writes keep eager in-order semantics at submit time
            return Deferred(value=self._execute_call(idx, call, shards))
        shard_list = shards if shards is not None else self._all_shards(idx.name)
        local, groups = self._route(idx.name, shard_list)
        if not groups:
            return self.local.submit(idx.name, call, shards=local,
                                     deadline=deadline)[0]
        if name == "TopN":
            return self._submit_topn(idx, call, local, groups,
                                     deadline=deadline)
        having = None
        if name == "GroupBy":
            having = having_predicate(
                call, has_agg=isinstance(call.arg("aggregate"), Call)
            )
        mapped = call
        if name in ("Rows", "GroupBy") and (
            call.arg("limit") or having is not None
        ):
            mapped = Call(
                name,
                {k: v for k, v in call.args.items()
                 if k not in ("limit", "having")},
                call.children,
            )
        # remote fan-out departs on a background thread FIRST (calls
        # whose local submit is eager — Rows — would otherwise serialize
        # ahead of it), then the local program enqueues on the device
        # stream; nothing blocks until result()
        remote_join = spawn(lambda: self._map_remote(idx.name, mapped, groups,
                                                     deadline=deadline))
        local_def = self.local.submit(idx.name, mapped, shards=local,
                                      deadline=deadline)[0]

        def finalize():
            local_res = local_def.result()
            partials = remote_join()
            return self._reduce(idx, call, local_res, partials, having=having)

        return Deferred(finalize)

    # -------------------------------------------------------- shard routing

    def _all_shards(self, index_name: str) -> list[int]:
        """Cluster-wide shard list: local shards ∪ peers' create-shard
        broadcasts (reference CreateShardMessage — new remote shards are
        visible immediately) ∪ a TTL-cached catalog poll as the backstop
        for missed broadcasts (e.g. this node restarted)."""
        with self._lock:
            hit = self._shards_cache.get(index_name)
            polled = hit[1] if hit and time.monotonic() - hit[0] < _SHARDS_TTL else None
        if polled is None:
            peers = [n for n in self.cluster.sorted_nodes()
                     if n.id != self.cluster.local.id]

            def poll(node):
                try:
                    out = self.cluster.client._call(
                        "GET",
                        f"{node.uri}/internal/shards/list?index={index_name}",
                    )
                    return out.get("shards", [])
                except ClientError:
                    return []

            polled = {s for chunk in concurrent_map(poll, peers)
                      for s in chunk}
            with self._lock:
                self._shards_cache[index_name] = (time.monotonic(), polled)
        shards = set(self.holder.index(index_name).available_shards())
        shards.update(polled)
        shards.update(self.cluster.get_known_shards(index_name))
        return sorted(shards)

    def _route(self, index_name: str, shards: list[int]):
        """Group shards by executing node (primary live replica; self
        preferred when we are any replica)."""
        local: list[int] = []
        remote: dict[str, tuple[Node, list[int]]] = {}
        for shard in shards:
            nodes = self.cluster.shard_nodes(index_name, shard)
            if any(n.id == self.cluster.local.id for n in nodes):
                local.append(shard)
                continue
            target = self._range_read_target(index_name, shard)
            if target is None:
                live = [n for n in nodes if n.state == "NORMAL"] or nodes
                target = live[0]
            remote.setdefault(target.id, (target, []))[1].append(shard)
        return local, list(remote.values())

    def _range_read_target(self, index_name: str, shard: int):
        """Read-preference refinement for a range-split shard (elastic
        plane): successive reads rotate across the split's span owners
        — every one holds the WHOLE fragment through the union
        override, so any pick reads correct bytes, and the rotation is
        what spreads a hot single shard's read QPS after the planner
        splits it. None for an unsplit shard (or a departed span
        owner): the caller falls back to plain owner routing."""
        spans = self.cluster.placement.get_ranges(index_name, shard)
        if not spans:
            return None
        self._range_rr += 1
        lo = spans[self._range_rr % len(spans)][0]
        nodes = self.cluster.range_read_nodes(index_name, shard, lo)
        if not nodes:
            return None
        live = [n for n in nodes if n.state == "NORMAL"]
        return live[0] if live else None

    def _route_all_replicas(self, index_name: str, shards: list[int]):
        """Group shards by EVERY replica that holds them. Row-wide writes
        (Store/ClearRow) must reach all owners like point writes do —
        routing them to one executing replica per shard (the read path's
        _route) leaves the other replicas' copies of the row stale, and
        replicas diverge until (or past: union repair cannot remove
        cleared bits) the next anti-entropy pass. Found by the
        randomized cluster property test (replica_n=2)."""
        local: list[int] = []
        remote: dict[str, tuple[Node, list[int]]] = {}
        for shard in shards:
            for n in self.cluster.shard_nodes(index_name, shard):
                if n.id == self.cluster.local.id:
                    local.append(shard)
                else:
                    remote.setdefault(n.id, (n, []))[1].append(shard)
        return local, list(remote.values())

    def _map_remote(self, index_name: str, call: Call, groups, _depth=0,
                    deadline=None):
        """One CONCURRENT sub-query per remote node (reference mapReduce:
        one goroutine per remote node — SURVEY.md §2 #12); returns a flat
        list of raw JSON partials (shard coverage exact; group order
        immaterial to every reducer).

        Replica fallback: a node that fails its sub-query is marked
        DEGRADED and its shards are re-routed to surviving NORMAL
        replicas (recursing once per hop, bounded); the query only fails
        when some shard has no live replica left. Reads therefore
        tolerate single-replica faults the way the reference's
        mapReduce retry loop does.

        With a QoS bundle wired, each sub-query additionally rides the
        hedged-read path (_query_group): circuit-broken nodes are skipped
        without paying a transport timeout, and a primary slower than the
        p95-tracked hedge delay races a budgeted duplicate at the next
        replica. DeadlineExceeded propagates — an expired budget is a
        property of the REQUEST, so replica retries must not chase it."""
        from pilosa_tpu.utils.tracing import current_query

        pql = call.to_pql()
        # in-flight inspector (GET /debug/queries): count this fan-out's
        # shards as outstanding, decrementing as each node's group
        # settles — plain attribute writes on the request's record
        inflight = current_query() if _depth == 0 else None
        if inflight is not None:
            inflight.shards_outstanding = (
                (inflight.shards_outstanding or 0)
                + sum(len(g[1]) for g in groups)
            )

        def one(group):
            node, shard_group = group
            try:
                return self._query_group(index_name, call, pql, node,
                                         shard_group, _depth, deadline)
            except ClientError as e:
                if deadline is not None and deadline.expired:
                    # the budget died with this hop: report the deadline,
                    # not the transport symptom — no retry can answer an
                    # expired request, so replica fallback must not run
                    raise DeadlineExceeded(
                        f"deadline exceeded during remote read "
                        f"({node.id}: {e})"
                    ) from e
                # Transport/5xx: the NODE is sick — degrade it and retry
                # siblings. 404: ambiguous — 'index/field not found' can
                # mean a schema-lagging replica, so retry siblings but do
                # NOT degrade a healthy node. Other 4xx: deterministic
                # query errors every replica would repeat — surface as
                # PQLError (HTTP 400), never 'internal'.
                if e.is_node_fault:
                    # a circuit-open error is synthetic — no contact was
                    # made, so it reroutes but must not override the
                    # heartbeat's view of the node
                    if not getattr(e, "circuit_open", False):
                        node.state = "DEGRADED"
                elif e.status != 404:
                    raise PQLError(str(e)) from e

                def give_up():
                    if (e.is_node_fault
                            and getattr(self.cluster, "degraded", False)):
                        # minority side of a partition: name the real
                        # condition (503 + Retry-After at the edge)
                        # instead of surfacing one peer's transport
                        # symptom — locally-owned reads still serve
                        raise ClusterDegradedError(
                            "cluster degraded (no member quorum): shards "
                            "owned by unreachable peers cannot be "
                            "served; only locally-owned reads are "
                            "available"
                        ) from e
                    if e.is_node_fault:
                        raise e
                    raise PQLError(str(e)) from e

                if _depth >= 2:
                    give_up()
                retry, orphans = self._reroute_groups(
                    index_name, shard_group, node.id
                )
                if orphans:
                    give_up()  # some shard has no live replica left
                return self._map_remote(
                    index_name, call, retry, _depth + 1, deadline=deadline,
                )

        def one_tracked(group):
            try:
                return one(group)
            finally:
                if inflight is not None:
                    inflight.shards_outstanding = max(
                        0, (inflight.shards_outstanding or 0)
                        - len(group[1]),
                    )

        return [p for chunk in concurrent_map(one_tracked, groups)
                for p in chunk]

    # ------------------------------------------------------- hedged reads

    def _reroute_groups(self, index_name: str, shards, exclude_id: str):
        """Next-live-replica routing shared by the failure fallback and
        the hedge path: bucket each shard onto its first NORMAL replica
        that is not ``exclude_id``. Returns ``(groups, orphans)`` —
        orphans are shards with no live alternate; the caller decides
        whether that aborts the query (fallback) or merely disables
        hedging. One implementation so a future replica-selection change
        cannot make the two paths route differently."""
        groups: dict[str, tuple[Node, list[int]]] = {}
        orphans: list[int] = []
        for shard in shards:
            alts = [
                n for n in self.cluster.shard_nodes(index_name, shard)
                if n.id != exclude_id and n.state == "NORMAL"
            ]
            if not alts:
                orphans.append(shard)
            else:
                groups.setdefault(alts[0].id, (alts[0], []))[1].append(shard)
        return list(groups.values()), orphans

    def _record_breaker_outcome(self, breaker, exc, deadline,
                                elapsed: float) -> None:
        """Classify a failed primary read for the circuit breaker.

        A transport/5xx fault with the request's budget still live is
        plain evidence against the node. At budget expiry it is
        ambiguous — transport timeouts are capped at the remaining
        budget (client.py hop_kwargs), so a TIGHT deadline makes a
        healthy node look faulty (deadline.py's invariant: a request
        property must not open breakers) while a truly stalled node
        always faults exactly at expiry and would otherwise never trip
        its breaker. Discriminate by how long the node was given: a
        fault after several multiples of the tracked hedge delay (and
        at least 1 s) counts even at expiry. A 4xx is a deterministic
        query error every replica would repeat — never node evidence.
        Inconclusive outcomes release a half-open probe seat without
        moving state. (See _map_remote for the inspector's
        shards-outstanding accounting.)"""
        if isinstance(exc, ClientError) and exc.is_node_fault:
            fair_chance = max(1.0, 4 * self.qos.hedge.delay())
            if (deadline is None or not deadline.expired
                    or elapsed >= fair_chance):
                breaker.record_failure()
                return
        breaker.record_inconclusive()

    def _alternate_groups(self, index_name: str, primary, shard_group):
        """Hedge targets for one sub-query. All-or-nothing — a partial
        hedge would return a partial result that cannot stand in for the
        primary's, so any shard without a live alternate disables hedging
        for the whole group."""
        groups, orphans = self._reroute_groups(index_name, shard_group,
                                               primary.id)
        return [] if orphans else groups

    @property
    def wave_batcher(self):
        """Lazy per-executor batcher (observability handle for /metrics)."""
        batcher = self._wave_batcher
        if batcher is None:
            with self._lock:
                if self._wave_batcher is None:
                    from pilosa_tpu.parallel.wavebatch import (
                        RemoteWaveBatcher,
                    )

                    self._wave_batcher = RemoteWaveBatcher(
                        self.cluster.client)
                batcher = self._wave_batcher
        return batcher

    def _remote_query(self, node, index_name: str, pql: str, shard_group,
                      deadline, _depth) -> dict:
        """One remote sub-query, through the wave batcher when eligible.
        Eligibility: batching enabled, deadline-free, and a depth-0
        primary leg — deadline-capped hops keep their per-hop transport
        cap, and hedge/fallback legs (depth ≥ 1) must not queue behind
        the very primary they are racing.

        Tracing: when this request is sampled, the leg gets a
        ``remote.query`` span, the hop carries ``X-Pilosa-Trace``, and
        the peer's returned span subtree is grafted under the leg — the
        coordinator's /debug/traces then shows one tree spanning the
        cluster (docs/OBSERVABILITY.md).

        PROFILE: when the request carries a cost profile (utils/cost.py)
        the hop asks the peer for ITS per-AST-node profile and grafts the
        returned subtree under this request's profile, exactly like the
        span graft — so a cluster query answers one stitched per-node
        profile tree. Profiled legs bypass the wave batcher (per-item
        profiles don't ride the batch wire, and a debugging request must
        not perturb its batchmates' group-commit)."""
        from pilosa_tpu.utils.cost import current_cost
        from pilosa_tpu.utils.tracing import global_tracer

        cost = current_cost()
        profile = cost.profile if cost is not None else None
        with global_tracer().span(
            "remote.query", node=node.id, shards=len(shard_group),
            depth=_depth,
        ) as span:
            trace = span.header_value() if span is not None else None
            if (self.remote_batch and deadline is None and _depth == 0
                    and profile is None):
                out = self.wave_batcher.query(node, index_name, pql,
                                              shard_group, trace=trace)
            else:
                # kwargs only when set: test doubles (and older client
                # shims) that predate the trace/deadline/profile
                # keywords keep working on the plain common path
                kw = {}
                if deadline is not None:
                    kw["deadline"] = deadline
                if trace is not None:
                    kw["trace"] = trace
                if profile is not None:
                    kw["profile"] = True
                out = self.cluster.client.query_node(
                    node.uri, index_name, pql, shard_group, remote=True,
                    **kw,
                )
            if isinstance(out, dict):
                if span is not None:
                    subtree = out.pop("trace", None)
                    if subtree is not None:
                        span.add_remote(subtree)
                if profile is not None:
                    sub = out.pop("profile", None)
                    if sub is not None:
                        profile.add_remote(node.id, len(shard_group), sub)
            return out

    def _query_group(self, index_name: str, call: Call, pql: str, node,
                     shard_group, _depth, deadline):
        """One node's sub-query with QoS: circuit breaker, then a hedged
        race against the next replica when the primary outlives the
        hedge delay. Returns a flat partial list; raises ClientError on
        failure so the caller's replica-fallback path stays authoritative
        for DEGRADED marking and rerouting."""
        qos = self.qos
        if qos is None:
            out = self._remote_query(node, index_name, pql, shard_group,
                                     deadline, _depth)
            return [out["results"][0]]
        breaker = qos.breaker(node.id)
        if not breaker.allow():
            # open circuit: don't pay this node's transport timeout —
            # fail fast into the caller's replica fallback. The error is
            # SYNTHETIC (no contact was made), so it must reroute like a
            # node fault without being treated as fresh evidence: the
            # circuit_open marker stops one() from re-marking a
            # heartbeat-recovered node DEGRADED off stale breaker state
            err = ClientError(f"circuit open for node {node.id}")
            err.circuit_open = True
            raise err
        # only EDGE fan-out legs (depth 0) count toward the hedge-budget
        # denominator and the p95 tracker: hedge legs and fallback
        # retries re-enter this function at depth >= 1, and counting them
        # as primaries would inflate the denominator the ≤budget-fraction
        # invariant divides by (and skew the delay toward retry latency)
        is_edge_leg = _depth == 0
        if is_edge_leg:
            qos.hedge.note_primary()
        t0 = time.monotonic()
        if (self.cluster.replica_n <= 1 or _depth >= 2
                or qos.hedge.budget_fraction <= 0):
            # no race partner is possible (unreplicated, depth-capped, or
            # hedging disabled via qos-hedge-budget=0): call inline — the
            # thread + condvar handshake below would be pure overhead
            try:
                out = self._remote_query(node, index_name, pql, shard_group,
                                         deadline, _depth)
            except BaseException as e:
                self._record_breaker_outcome(breaker, e, deadline,
                                             time.monotonic() - t0)
                raise
            if is_edge_leg:
                qos.hedge.record(time.monotonic() - t0)
            breaker.record_success()
            return [out["results"][0]]

        import contextvars

        cv = threading.Condition()
        state: dict = {}

        def finish(key, value):
            with cv:
                state.setdefault(key, value)
                cv.notify_all()

        def run_primary():
            try:
                out = self._remote_query(node, index_name, pql, shard_group,
                                         deadline, _depth)
            except BaseException as e:
                self._record_breaker_outcome(breaker, e, deadline,
                                             time.monotonic() - t0)
                finish("primary_err", e)
            else:
                if is_edge_leg:
                    qos.hedge.record(time.monotonic() - t0)
                breaker.record_success()
                finish("result", ("primary", [out["results"][0]]))

        # hedge-race legs run on bare threads: capture this context so
        # their remote.query spans land in the request's trace instead
        # of being orphaned (utils/tracing.py)
        primary_ctx = contextvars.copy_context()
        threading.Thread(target=lambda: primary_ctx.run(run_primary),
                         daemon=True,
                         name=f"qos-primary-{node.id}").start()
        delay = qos.hedge.delay()
        if deadline is not None:
            delay = min(delay, max(deadline.remaining(), 0))
        with cv:
            cv.wait_for(lambda: state, timeout=delay)
            pending = not state
        hedged = False
        if pending and not (deadline is not None and deadline.expired):
            # alternates are computed only now, on the slow path: the
            # ~95% of reads the primary answers within the delay never
            # pay the per-shard ring walks
            alt_groups = self._alternate_groups(index_name, node,
                                                shard_group)
            with cv:
                # the primary may have settled during the ring walk —
                # don't spend budget on a hedge that cannot win
                pending = not state
            if pending and alt_groups and qos.hedge.try_hedge():
                hedged = True

                def run_hedge():
                    from pilosa_tpu.utils.tracing import global_tracer

                    try:
                        with global_tracer().span("qos.hedge",
                                                  primary=node.id):
                            partials = self._map_remote(
                                index_name, call, alt_groups, _depth + 1,
                                deadline=deadline,
                            )
                    except BaseException as e:
                        finish("hedge_err", e)
                    else:
                        finish("result", ("hedge", partials))

                hedge_ctx = contextvars.copy_context()
                threading.Thread(target=lambda: hedge_ctx.run(run_hedge),
                                 daemon=True,
                                 name=f"qos-hedge-{node.id}").start()

        def settled():
            return ("result" in state
                    or ("primary_err" in state
                        and (not hedged or "hedge_err" in state)))

        with cv:
            if deadline is None:
                cv.wait_for(settled)
            else:
                # wake at settle OR budget expiry — no fixed-rate polling
                while not cv.wait_for(settled,
                                      timeout=max(deadline.remaining(),
                                                  1e-3)):
                    if deadline.expired:
                        break
        with cv:
            final = dict(state)
        if "result" in final:
            source, partials = final["result"]
            if source == "hedge":
                qos.hedge.note_win()
            return partials
        if "primary_err" in final:
            # both legs failed (or no hedge fired): surface the PRIMARY
            # error so the caller's fallback semantics (DEGRADED marking,
            # bounded reroute, 4xx propagation) are unchanged
            raise final["primary_err"]
        # neither leg settled: the only path here is the expired-budget
        # break above, so the check always raises DeadlineExceeded
        deadline.check("hedged read")
        raise AssertionError("hedged-read settle loop exited unexpectedly")

    def _map_remote_tolerant(self, index_name: str, call: Call, groups):
        """Row-wide write fan-out (Store/ClearRow): every replica is
        already a direct target, so there is nothing to fall back to — a
        replica unreachable at write time is marked DEGRADED and skipped
        (exactly like point writes in _execute_routed_write), the live
        replicas' write stands. Failing the whole request after some
        replicas already applied it would leave the SAME divergence plus
        a client told to retry. Deterministic (4xx) errors DO propagate —
        every replica would reject identically, so nothing was applied
        anywhere and the client must see the error.

        Divergence window: identical to a missed point write — the
        skipped replica is repaired when heartbeat death detection
        re-owns its shards or a join/re-fetch replaces its fragments;
        until then anti-entropy's union repair can resurface bits a
        ClearRow removed (documented in docs/PQL.md note 5)."""
        pql = call.to_pql()

        def one(group):
            node, shard_group = group
            try:
                out = self.cluster.client.query_node(
                    node.uri, index_name, pql, shard_group, remote=True
                )
                return out["results"][0]
            except ClientError as e:
                if e.is_node_fault:
                    node.state = "DEGRADED"
                    return False
                if e.status == 404:
                    # schema-lagging replica: skip it (no health signal);
                    # schema sync + anti-entropy catch it up
                    return False
                raise PQLError(str(e)) from e

        return concurrent_map(one, groups)

    # ----------------------------------------------------------- dispatch

    def _execute_call(self, idx, call: Call, shards=None, deadline=None):
        name = call.name
        if name in ("Set", "Clear"):
            return self._execute_routed_write(idx, call)
        if name in _WRITE_BROADCAST:
            res = self.local._execute_call(idx, call)
            self.cluster.send_sync(
                {"type": "forward-query", "index": idx.name, "pql": call.to_pql()}
            )
            return res
        if name in ("Store", "ClearRow"):
            # row-wide writes execute on EVERY replica of every shard,
            # concurrently (local evaluation overlaps the remote fan-out)
            shard_list = shards if shards is not None else self._all_shards(idx.name)
            local, groups = self._route_all_replicas(idx.name, shard_list)
            result, outs = run_concurrently(
                lambda: (self.local._execute_call(idx, call, local)
                         if local else False),
                lambda: self._map_remote_tolerant(idx.name, call, groups),
            )
            for out in outs:
                result = result or out
            return result

        # Reads (Options, TopN, IncludesColumn, and the generic
        # map→reduce family) share ONE orchestration: the pipelined
        # _submit_call, resolved immediately. submit's enqueue/spawn
        # overlap gives eager execution the same max(local, slowest peer)
        # wall time run_concurrently did, and the two paths cannot drift.
        return self._submit_call(idx, call, shards, deadline=deadline).result()

    # --------------------------------------------------------------- writes

    def _execute_routed_write(self, idx, call: Call):
        col = call.arg("_col")
        if isinstance(col, str):
            # keyed writes translate on the coordinator (via the resolver
            # hook); after translation the call routes by numeric column
            col = self.local._translate_col(idx, col, create=call.name == "Set")
            if col is None:
                return False
            call = Call(call.name, {**call.args, "_col": col}, call.children)
        if col is None:
            raise PQLError(f"{call.name} requires a column")
        shard = shard_of(int(col))
        owners = self.cluster.shard_nodes(idx.name, shard)
        owners = self._narrow_write_owners(idx, call, shard, int(col),
                                           owners)
        route_stats = global_route_stats()
        result = False
        pql = call.to_pql()
        for node in owners:
            if node.id == self.cluster.local.id:
                result = bool(self.local._execute_call(idx, call)) or result
                if result and call.name == "Set":
                    self.cluster.note_local_shards(idx.name, [shard])
            else:
                try:
                    route_stats.wire_bytes += len(pql)
                    out = self.cluster.client.query_node(
                        node.uri, idx.name, pql, [shard], remote=True
                    )
                    result = bool(out["results"][0]) or result
                except ClientError as e:
                    if e.is_node_fault:
                        node.state = "DEGRADED"
                    elif e.status != 404:  # 404 = schema lag: skip quietly
                        raise PQLError(str(e)) from e
        return result

    def _narrow_write_owners(self, idx, call: Call, shard: int, col: int,
                             owners):
        """Range-aware write routing for point writes: a plain ``Set``
        into a range-split shard goes only to its column span's owners
        (every other union owner converges through anti-entropy's union
        repair). Everything else — ``Clear`` (union repair cannot remove
        a bit a narrowed send skipped), mutex/bool (row moves), int
        (value overwrite), timestamped sets (extra view rows) — keeps
        the full union fan-out, as does a span whose owner departed."""
        route_stats = global_route_stats()
        if call.name != "Set" or call.arg("timestamp") is not None:
            route_stats.union_writes += 1
            return owners
        try:
            fname, _ = self.local._row_field_and_value(call)
            field = idx.field(fname)
        except PQLError:
            field = None
        if field is None or field.options.type in (TYPE_BOOL, TYPE_INT,
                                                   TYPE_MUTEX):
            route_stats.union_writes += 1
            return owners
        spans = self.cluster.range_write_spans(idx.name, shard)
        if spans:
            off = col - shard * SHARD_WIDTH
            for rlo, rhi, span_nodes in spans:
                if rlo <= off < rhi:
                    if span_nodes is not None:
                        route_stats.range_slices += 1
                        return span_nodes
                    route_stats.range_fallbacks += 1
                    return owners
        route_stats.union_writes += 1
        return owners

    # --------------------------------------------------------------- reduce

    def _reduce(self, idx, call: Call, local_res, partials, having=None):
        name = call.name
        if name == "Count":
            return int(local_res) + sum(int(p) for p in partials)
        if name in ("Sum",):
            total, count = local_res.value, local_res.count
            for p in partials:
                total += p["value"]
                count += p["count"]
            return ValCount(total, count)
        if name in ("Min", "Max"):
            want_max = name == "Max"
            best, count = (local_res.value, local_res.count) if local_res.count else (None, 0)
            for p in partials:
                if p["count"] == 0:
                    continue
                v = p["value"]
                if best is None or (v > best if want_max else v < best):
                    best, count = v, p["count"]
                elif v == best:
                    count += p["count"]
            return ValCount(best or 0, count)
        if name == "Rows":
            merged = set(local_res)
            for p in partials:
                merged.update(p)
            out = sorted(merged)
            limit = call.arg("limit", 0)
            return out[: int(limit)] if limit else out
        if name == "GroupBy":
            # Normalize each element to rowKey for keyed dim fields before
            # merging: a node whose translate replica lags emits rowID for
            # a row others report by key, which must not split the group.
            keyed: dict[str, bool] = {}

            def normalize(group) -> list[dict]:
                out = []
                for e in group:
                    fname = e["field"]
                    if fname not in keyed:
                        f = idx.field(fname)
                        keyed[fname] = bool(f and f.options.keys)
                    if keyed[fname] and "rowKey" not in e:
                        f = idx.field(fname)
                        (key,) = self.local._row_keys(idx, f, [e["rowID"]])
                        if key is not None:
                            e = {"field": fname, "rowKey": key}
                    out.append(e)
                return out

            # Merge key per element: rowKey when the dim field is keyed,
            # rowID otherwise.
            def gkey(group: list[dict]) -> tuple:
                return tuple(
                    e.get("rowKey", e.get("rowID")) for e in group
                )

            counts: dict[tuple, int] = {}
            sums: dict[tuple, int] = {}
            fields: dict[tuple, list] = {}
            for g in local_res:
                group = normalize(g.group)
                key = gkey(group)
                counts[key] = counts.get(key, 0) + g.count
                if g.sum is not None:
                    sums[key] = sums.get(key, 0) + g.sum
                fields[key] = group
            for p in partials:
                for g in p:
                    group = normalize(g["group"])
                    key = gkey(group)
                    counts[key] = counts.get(key, 0) + g["count"]
                    if g.get("sum") is not None:
                        sums[key] = sums.get(key, 0) + g["sum"]
                    fields[key] = group
            # Type-aware ordering: numeric rowIDs sort numerically (matching
            # the single-node executor), rowKeys lexicographically after.
            def order(kv):
                return tuple(
                    (1, e) if isinstance(e, str) else (0, int(e))
                    for e in kv[0]
                )

            if having is not None:
                counts = {
                    k: c for k, c in counts.items() if having(c, sums.get(k))
                }
            out = [
                GroupCount(fields[k], c, sum=sums.get(k))
                for k, c in sorted(counts.items(), key=order)
            ]
            limit = call.arg("limit", 0)
            return out[: int(limit)] if limit else out
        # bitmap calls → RowResult union
        if isinstance(local_res, RowResult):
            merged = local_res
            for p in partials:
                merged = merged.merge(_row_from_json(p))
            if idx.keys:
                merged.keys = sorted(
                    set(merged.keys or [])
                    | {k for p in partials for k in p.get("keys", [])}
                )
            return merged
        return local_res

    # ----------------------------------------------------------------- TopN

    def _submit_topn(self, idx, call: Call, local, groups,
                     deadline=None) -> Deferred:
        """Two-phase distributed TopN, pipelined: phase 1 (overfetched
        candidates) enqueues locally and departs remotely at SUBMIT time;
        phase 2 (exact recount of the merged candidate set) must wait for
        phase-1 readbacks, so it runs inside result()."""
        n = call.arg("n", 10)
        # threshold= filters on GLOBAL counts, so it is stripped from
        # every mapped sub-query (a per-node floor would drop candidates
        # whose cross-node sum qualifies) and applied after the merge
        mapped_args = {k: v for k, v in call.args.items() if k != "threshold"}
        explicit_ids = call.arg("ids")
        local1 = remote1 = None
        if explicit_ids is None:
            overfetch = max(n * TOPN_CANDIDATE_FACTOR, n + 10)
            phase1 = Call("TopN", {**mapped_args, "n": overfetch}, call.children)
            remote1 = spawn(lambda: self._map_remote(idx.name, phase1, groups,
                                                     deadline=deadline))
            local1 = self.local.submit(idx.name, phase1, shards=local,
                                       deadline=deadline)[0]

        def finalize():
            if explicit_ids is None:
                candidates = {p.id for p in local1.result()}
                for p in remote1():
                    candidates.update(pair["id"] for pair in p)
                if not candidates:
                    return []
                ids = sorted(candidates)
            else:
                ids = sorted(int(i) for i in explicit_ids)
            # phase 2: exact recount of the merged candidate set everywhere
            phase2 = Call("TopN", {**mapped_args, "ids": ids, "n": 0},
                          call.children)
            totals: dict[int, int] = {}
            local2, remote2 = run_concurrently(
                lambda: self.local._execute_call(idx, phase2, local),
                lambda: self._map_remote(idx.name, phase2, groups,
                                         deadline=deadline),
            )
            for p in local2:
                totals[p.id] = totals.get(p.id, 0) + p.count
            for partial in remote2:
                for pair in partial:
                    totals[pair["id"]] = totals.get(pair["id"], 0) + pair["count"]
            floor = max(1, int(call.arg("threshold", 0) or 0))
            order = sorted((-c, r) for r, c in totals.items() if c >= floor)
            pairs = [Pair(r, -negc) for negc, r in order[: n or len(order)]]
            field = idx.field(call.arg("_field") or call.arg("field"))
            return self.local._finish_pairs(idx, field, pairs)

        return Deferred(finalize)

    def _execute_includes(self, idx, call: Call, shards=None, deadline=None):
        target = self.local.includes_target(idx, call, shards)
        if target is None:
            return False
        col, shard = target
        # forward the NUMERIC column (a lagging translate replica on the
        # target could otherwise fail to resolve the key)
        call = Call(call.name, {**call.args, "column": int(col)},
                    call.children)
        if self.cluster.owns_shard(idx.name, shard):
            return self.local._execute_call(idx, call)
        node = self.cluster.primary_for_shard(idx.name, shard)
        out = self.cluster.client.query_node(
            node.uri, idx.name, call.to_pql(), [shard], remote=True,
            **({"deadline": deadline} if deadline is not None else {}),
        )
        return out["results"][0]


def _row_from_json(p: dict) -> RowResult:
    """Rebuild a RowResult from a peer's JSON columns."""
    cols = np.asarray(p.get("columns", []), np.uint64)
    segments: dict[int, np.ndarray] = {}
    if cols.size:
        shards = (cols >> np.uint64(20)).astype(np.int64)
        for shard in np.unique(shards).tolist():
            pos = cols[shards == shard] & np.uint64(SHARD_WIDTH - 1)
            segments[int(shard)] = pack_bits(pos, SHARD_WIDTH)
    return RowResult(segments, attrs=p.get("attrs") or {})
