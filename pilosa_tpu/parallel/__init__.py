"""Distributed execution: device mesh, sharded evaluation, cluster topology.

This package is the TPU-native replacement for the reference's cluster
data plane (cluster.go shard→node assignment + http/client.go remote
mapReduce + gossip — SURVEY.md §2 #13–17, §2.3–2.4):

- within a slice, shards are assigned to mesh positions and queries run as
  ONE compiled SPMD program via ``shard_map`` with ``psum``/all-gather
  reduces over ICI (pilosa_tpu.parallel.dist) — this replaces the
  reference's per-node HTTP scatter/gather;
- across slices/hosts, the same mesh extends over DCN via
  ``jax.distributed`` (pilosa_tpu.parallel.mesh.initialize_distributed);
- the host control plane (membership, replica placement, anti-entropy,
  resize) lives in pilosa_tpu.parallel.cluster.
"""

from pilosa_tpu.parallel.mesh import (
    GROUPS_AXIS,
    SHARDS_AXIS,
    ShardAssignment,
    make_mesh,
    mesh_groups,
)
from pilosa_tpu.parallel.dist import DistExecutor
