"""Token-bucket pacing for the repair/rebalance data plane.

A resize or anti-entropy storm moves whole fragments between nodes; left
unpaced, those bulk transfers compete with serving traffic for the same
NIC and the same Python accept loop. The pacer bounds the damage two
ways, both off by default:

- ``max_bytes_per_sec``: a token bucket debited per transferred payload.
  The bucket holds one second of budget (floored at 64 KiB so a tiny
  rate still admits one block), and a transfer that overdraws sleeps the
  deficit off before the next one starts — aggregate repair throughput
  converges on the configured rate while individual transfers stay
  unfragmented (the HTTP bodies are read whole by the pool).
- ``max_inflight``: a semaphore capping concurrent data-plane transfers,
  so a wide ``sync-workers`` pipeline cannot hold every connection-pool
  slot (and every peer handler thread) at once.

Sleep time is exported as the ``repair_paced_sleep_ms`` counter: a
growing value under resize means the pacer is actually shaping traffic,
not just configured.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager, nullcontext

# Minimum bucket depth: one typical roaring block payload, so a very low
# byte rate paces between transfers instead of deadlocking before the
# first one.
MIN_BURST_BYTES = 1 << 16


class RepairPacer:
    """Shared by every repair/resize transfer of one node's client."""

    def __init__(self, max_bytes_per_sec: float = 0,
                 max_inflight: int = 0, stats=None):
        self.rate = float(max_bytes_per_sec or 0)
        self.max_inflight = int(max_inflight or 0)
        self.burst = max(self.rate, MIN_BURST_BYTES)
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()
        self._sem = (threading.BoundedSemaphore(self.max_inflight)
                     if self.max_inflight > 0 else None)
        self.stats = stats
        # totals for /debug/vars-style introspection and tests
        self.paced_sleep_s = 0.0
        self.bytes_consumed = 0

    def slot(self):
        """Context manager bounding concurrent transfers (no-op when
        ``max_inflight`` is 0)."""
        if self._sem is None:
            return nullcontext()
        return self._slot()

    @contextmanager
    def _slot(self):
        self._sem.acquire()
        try:
            yield
        finally:
            self._sem.release()

    def consume(self, nbytes: int) -> float:
        """Debit ``nbytes`` from the bucket; sleep off any deficit.
        Returns the seconds slept (0.0 when unpaced or within budget)."""
        if nbytes <= 0:
            return 0.0
        with self._lock:
            self.bytes_consumed += int(nbytes)
            if self.rate <= 0:
                return 0.0
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t_last) * self.rate
            )
            self._t_last = now
            self._tokens -= nbytes
            wait = (-self._tokens / self.rate) if self._tokens < 0 else 0.0
            self.paced_sleep_s += wait
        if wait > 0:
            if self.stats is not None:
                self.stats.count("repair_paced_sleep_ms", wait * 1e3)
            time.sleep(wait)
        return wait
